"""AOT-validate the Llama-3-8B full-recipe training program on a virtual
v5p-64 mesh (BASELINE config 3/4 — the ≥40% MFU north star).

Mesh: dp8 × sharding4 × tensor2 (64 virtual devices) — the layout the
cost-model search picks for 8B on 64 × 95GB chips. The full train step
(fwd + bwd + AdamW, remat, fused chunked lm-head CE) is lowered with
abstract engine params; --compile also runs GSPMD partitioning and reports
collective counts. Like validate_70b_4d.py, the eager model build
materializes zero-filled fp32 host arrays (~4GB/8 layers); default --layers
8 keeps that modest.

--cp adds the LONG-CONTEXT leg (VERDICT r4 missing 3): the same 8B proxy
at S=32768 on dp2 × sharding4 × tensor2 × context4, context_parallel=True,
batch sharded P('data','context'). Asserts the compiled step (a) contains
collective-permute ring hops and (b) per-device temp bytes scale ~S/n_ctx
(compared against a context2 half-mesh compile) — where "CP works (tiny,
8 CPU devices)" and "8B recipe compiles (64 devices)" finally meet.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=64 JAX_PLATFORMS=cpu \
        python tools/validate_8b_recipe.py [--layers 32] [--compile] [--cp]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N_DEV = 64


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--compile", action="store_true")
    ap.add_argument("--cp", action="store_true",
                    help="long-context leg: S=32768 over dp2 x zero4 x "
                         "tp2 x context4 with ring attention")
    ap.add_argument("--moe", action="store_true",
                    help="MoE leg: 8-expert Mixtral-proxy over dp2 x "
                         "zero4 x expert8, sparse dispatch")
    ap.add_argument("--cp_seq", type=int, default=32768)
    ap.add_argument("--cp_layers", type=int, default=2)
    args = ap.parse_args()

    os.environ.setdefault(
        "XLA_FLAGS", f"--xla_force_host_platform_device_count={N_DEV}")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import (ClusterDesc, ModelDesc,
                                                      search)
    from paddle_tpu.models import LlamaForCausalLM, llama3_8b_config

    assert jax.device_count() >= N_DEV
    # sanity: the cost model agrees this mesh family is right for 8B/v5p-64
    pick = search(ModelDesc(n_params=8_030_000_000, hidden_size=4096,
                            num_layers=32, num_attention_heads=32,
                            seq_len=args.seq),
                  ClusterDesc(n_devices=N_DEV, hbm_bytes=95 << 30,
                              peak_flops=459e12), global_batch=args.batch)
    print(f"cost-model pick for 8B/v5p-64: {pick['strategy'].degrees()} "
          f"(pred step {pick['cost'].step_s * 1e3:.0f} ms rel)")

    devs = np.asarray(jax.devices()[:N_DEV]).reshape(8, 4, 2)
    mesh = Mesh(devs, ("data", "sharding", "tensor"))
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    cfg = llama3_8b_config(num_hidden_layers=args.layers,
                           max_position_embeddings=args.seq,
                           dtype="float32")  # CPU AllReducePromotion bf16 bug
    t0 = time.time()
    paddle.seed(0)
    from paddle_tpu.nn import initializer as I

    def _zeros_init(self, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)

    for cls in (I.Normal, I.Uniform, I.XavierNormal, I.XavierUniform,
                I.KaimingNormal, I.KaimingUniform, I.TruncatedNormal):
        cls.__call__ = _zeros_init
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"model built: {n_params/1e9:.2f}B params ({args.layers} layers) "
          f"in {time.time()-t0:.0f}s")

    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel.engine import ParallelEngine

    opt = AdamW(learning_rate=3e-4, parameters=model.parameters())
    eng = ParallelEngine(model, optimizer=opt, loss_fn=None, mesh=mesh,
                         fsdp=True, remat=True, abstract=True)
    step = eng.build_train_step()

    ids = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32,
                               sharding=NamedSharding(mesh, P("data", None)))
    lbl = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int64,
                               sharding=NamedSharding(mesh, P("data", None)))
    p_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding)
             for k, v in eng.params.items()}
    st_abs = jax.tree.map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding),
        eng.opt_state)
    sc = jax.ShapeDtypeStruct((), jnp.int32)

    t0 = time.time()
    lowered = step.lower(p_abs, st_abs, sc, 3e-4, (ids, lbl))
    txt = lowered.as_text()
    n_shard = txt.count("sdy.sharding") + txt.count("mhlo.sharding")
    print(f"lowered in {time.time()-t0:.0f}s; {len(txt) // 1024}kB StableHLO, "
          f"{n_shard} sharding annotations")
    assert n_shard > 0
    if args.compile:
        t0 = time.time()
        hlo = lowered.compile().as_text()
        print(f"GSPMD-compiled in {time.time()-t0:.0f}s")
        counts = {c: hlo.count(c) for c in
                  ("all-gather", "reduce-scatter", "all-reduce")}
        for c, n in counts.items():
            print(f"  {c}: {n} sites")
        assert counts["all-reduce"] > 0
        assert counts["all-gather"] + counts["reduce-scatter"] > 0, \
            "ZeRO collectives missing"
    print("Llama-3-8B full-recipe (dp8 x zero4 x tp2, v5p-64) validation OK")

    if args.cp:
        validate_cp_leg(args)
    if args.moe:
        validate_moe_leg(args)


def validate_moe_leg(args):
    """MoE/EP at recipe scale: an 8-expert Mixtral-proxy train step AOT-
    compiled over dp2 × sharding4 × expert8 (64 devices) — expert weights
    sharded over 'expert', token exchange via the collectives GSPMD
    inserts around the sparse dispatch gathers."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama3_8b_config
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel.engine import ParallelEngine

    devs = np.asarray(jax.devices()[:64]).reshape(2, 4, 8)
    mesh = Mesh(devs, ("data", "sharding", "expert"))
    cfg = llama3_8b_config(num_hidden_layers=args.layers,
                           max_position_embeddings=args.seq,
                           dtype="float32", moe_num_experts=8,
                           moe_top_k=2)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"MoE leg: {n_params/1e9:.1f}B total params "
          f"({args.layers}L x 8 experts), mesh dp2 x zero4 x expert8")
    opt = AdamW(learning_rate=3e-4, parameters=model.parameters())
    eng = ParallelEngine(model, optimizer=opt, loss_fn=None, mesh=mesh,
                         fsdp=True, remat=True, abstract=True)
    step = eng.build_train_step()
    B = args.batch
    ids = jax.ShapeDtypeStruct(
        (B, args.seq), jnp.int32,
        sharding=NamedSharding(mesh, P("data", None)))
    lbl = jax.ShapeDtypeStruct(
        (B, args.seq), jnp.int64,
        sharding=NamedSharding(mesh, P("data", None)))
    p_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding)
             for k, v in eng.params.items()}
    st_abs = jax.tree.map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                       sharding=v.sharding),
        eng.opt_state)
    sc = jax.ShapeDtypeStruct((), jnp.int32)
    t0 = time.time()
    compiled = step.lower(p_abs, st_abs, sc, 3e-4, (ids, lbl)).compile()
    hlo = compiled.as_text()
    counts = {c: hlo.count(c) for c in
              ("all-to-all", "all-gather", "all-reduce", "reduce-scatter",
               "collective-permute")}
    print(f"  compiled in {time.time()-t0:.0f}s; collective sites: "
          f"{counts}")
    assert counts["all-reduce"] > 0
    # expert exchange: the sparse dispatch's gathers over expert-sharded
    # buckets lower to all-to-all / all-gather+dynamic-slice families —
    # SOME expert-axis data exchange must exist
    assert counts["all-to-all"] + counts["all-gather"] + \
        counts["collective-permute"] > 0, "no expert token exchange"
    print("Llama-3-8B MoE leg (dp2 x zero4 x expert8) validation OK")


def validate_cp_leg(args):
    """8B-proxy long-context leg: ring attention composed into the
    north-star mesh family, AOT-compiled at S=32768 over 64 devices."""
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama3_8b_config
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel.engine import ParallelEngine

    def compile_ctx(n_ctx, n_data):
        devs = np.asarray(jax.devices()[:n_data * 4 * 2 * n_ctx]).reshape(
            n_data, 4, 2, n_ctx)
        mesh = Mesh(devs, ("data", "sharding", "tensor", "context"))
        cfg = llama3_8b_config(num_hidden_layers=args.cp_layers,
                               max_position_embeddings=args.cp_seq,
                               dtype="float32", context_parallel=True)
        paddle.seed(0)
        model = LlamaForCausalLM(cfg)
        opt = AdamW(learning_rate=3e-4, parameters=model.parameters())
        eng = ParallelEngine(model, optimizer=opt, loss_fn=None, mesh=mesh,
                             fsdp=True, remat=True, abstract=True,
                             batch_spec=P(("data",), "context"))
        step = eng.build_train_step()
        B = 2 * n_data
        ids = jax.ShapeDtypeStruct(
            (B, args.cp_seq), jnp.int32,
            sharding=NamedSharding(mesh, P("data", "context")))
        lbl = jax.ShapeDtypeStruct(
            (B, args.cp_seq), jnp.int64,
            sharding=NamedSharding(mesh, P("data", "context")))
        p_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                         sharding=v.sharding)
                 for k, v in eng.params.items()}
        st_abs = jax.tree.map(
            lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                           sharding=v.sharding),
            eng.opt_state)
        sc = jax.ShapeDtypeStruct((), jnp.int32)
        t0 = time.time()
        compiled = step.lower(p_abs, st_abs, sc, 3e-4, (ids, lbl)).compile()
        hlo = compiled.as_text()
        mem = compiled.memory_analysis()
        temp = getattr(mem, "temp_size_in_bytes", None)
        print(f"  context{n_ctx} (dp{n_data}): compiled in "
              f"{time.time()-t0:.0f}s; collective-permute "
              f"{hlo.count('collective-permute')} sites, temp "
              f"{temp/1e9 if temp else -1:.2f} GB/device")
        return hlo, temp

    print(f"CP leg: 8B proxy ({args.cp_layers}L), S={args.cp_seq}, "
          f"mesh dp2 x zero4 x tp2 x context4")
    hlo4, temp4 = compile_ctx(4, 2)
    assert hlo4.count("collective-permute") > 0, \
        "CP leg compiled without ring communication"
    # activation scaling: context2 on a half mesh (same dp) doubles the
    # per-device sequence shard -> per-device temp must ~double
    hlo2, temp2 = compile_ctx(2, 2)
    assert hlo2.count("collective-permute") > 0
    if temp4 and temp2:
        ratio = temp4 / temp2
        print(f"  per-device temp ratio context4/context2 = {ratio:.2f} "
              f"(ideal 0.5)")
        assert ratio < 0.72, \
            f"activation bytes do not scale with S/n_context ({ratio:.2f})"
    print(f"Llama-3-8B LONG-CONTEXT leg (S={args.cp_seq}, "
          f"dp2 x zero4 x tp2 x context4) validation OK")


if __name__ == "__main__":
    main()
