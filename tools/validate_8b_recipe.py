"""AOT-validate the Llama-3-8B full-recipe training program on a virtual
v5p-64 mesh (BASELINE config 3/4 — the ≥40% MFU north star).

Mesh: dp8 × sharding4 × tensor2 (64 virtual devices) — the layout the
cost-model search picks for 8B on 64 × 95GB chips. The full train step
(fwd + bwd + AdamW, remat, fused chunked lm-head CE) is lowered with
abstract engine params; --compile also runs GSPMD partitioning and reports
collective counts. Like validate_70b_4d.py, the eager model build
materializes zero-filled fp32 host arrays (~4GB/8 layers); default --layers
8 keeps that modest.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=64 JAX_PLATFORMS=cpu \
        python tools/validate_8b_recipe.py [--layers 32] [--compile]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N_DEV = 64


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--compile", action="store_true")
    args = ap.parse_args()

    os.environ.setdefault(
        "XLA_FLAGS", f"--xla_force_host_platform_device_count={N_DEV}")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.distributed.auto_parallel import (ClusterDesc, ModelDesc,
                                                      search)
    from paddle_tpu.models import LlamaForCausalLM, llama3_8b_config

    assert jax.device_count() >= N_DEV
    # sanity: the cost model agrees this mesh family is right for 8B/v5p-64
    pick = search(ModelDesc(n_params=8_030_000_000, hidden_size=4096,
                            num_layers=32, num_attention_heads=32,
                            seq_len=args.seq),
                  ClusterDesc(n_devices=N_DEV, hbm_bytes=95 << 30,
                              peak_flops=459e12), global_batch=args.batch)
    print(f"cost-model pick for 8B/v5p-64: {pick['strategy'].degrees()} "
          f"(pred step {pick['cost'].step_s * 1e3:.0f} ms rel)")

    devs = np.asarray(jax.devices()[:N_DEV]).reshape(8, 4, 2)
    mesh = Mesh(devs, ("data", "sharding", "tensor"))
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    cfg = llama3_8b_config(num_hidden_layers=args.layers,
                           max_position_embeddings=args.seq,
                           dtype="float32")  # CPU AllReducePromotion bf16 bug
    t0 = time.time()
    paddle.seed(0)
    from paddle_tpu.nn import initializer as I

    def _zeros_init(self, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)

    for cls in (I.Normal, I.Uniform, I.XavierNormal, I.XavierUniform,
                I.KaimingNormal, I.KaimingUniform, I.TruncatedNormal):
        cls.__call__ = _zeros_init
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"model built: {n_params/1e9:.2f}B params ({args.layers} layers) "
          f"in {time.time()-t0:.0f}s")

    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel.engine import ParallelEngine

    opt = AdamW(learning_rate=3e-4, parameters=model.parameters())
    eng = ParallelEngine(model, optimizer=opt, loss_fn=None, mesh=mesh,
                         fsdp=True, remat=True, abstract=True)
    step = eng.build_train_step()

    ids = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32,
                               sharding=NamedSharding(mesh, P("data", None)))
    lbl = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int64,
                               sharding=NamedSharding(mesh, P("data", None)))
    p_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding)
             for k, v in eng.params.items()}
    st_abs = jax.tree.map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding),
        eng.opt_state)
    sc = jax.ShapeDtypeStruct((), jnp.int32)

    t0 = time.time()
    lowered = step.lower(p_abs, st_abs, sc, 3e-4, (ids, lbl))
    txt = lowered.as_text()
    n_shard = txt.count("sdy.sharding") + txt.count("mhlo.sharding")
    print(f"lowered in {time.time()-t0:.0f}s; {len(txt) // 1024}kB StableHLO, "
          f"{n_shard} sharding annotations")
    assert n_shard > 0
    if args.compile:
        t0 = time.time()
        hlo = lowered.compile().as_text()
        print(f"GSPMD-compiled in {time.time()-t0:.0f}s")
        counts = {c: hlo.count(c) for c in
                  ("all-gather", "reduce-scatter", "all-reduce")}
        for c, n in counts.items():
            print(f"  {c}: {n} sites")
        assert counts["all-reduce"] > 0
        assert counts["all-gather"] + counts["reduce-scatter"] > 0, \
            "ZeRO collectives missing"
    print("Llama-3-8B full-recipe (dp8 x zero4 x tp2, v5p-64) validation OK")


if __name__ == "__main__":
    main()
