#!/usr/bin/env python
"""graftlint CLI — JAX/TPU tracing-safety static analyzer.

Usage:
    python tools/graftlint.py paddle_tpu              # lint against baseline
    python tools/graftlint.py paddle_tpu --json       # machine-readable
    python tools/graftlint.py paddle_tpu --update-baseline
    python tools/graftlint.py --list-rules

Exit codes: 0 = clean (all findings baselined/suppressed), 1 = new
violations, 2 = usage/internal error.

Importing paddle_tpu.analysis pulls no jax — the linter runs anywhere
(pre-commit, CI containers without an accelerator runtime).
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
sys.path.insert(0, str(REPO_ROOT))

from paddle_tpu.analysis import (all_rules, analyze_paths, build_baseline,
                                 filter_new, load_baseline, save_baseline)

DEFAULT_BASELINE = REPO_ROOT / "tools" / "graftlint_baseline.json"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint", description=__doc__)
    ap.add_argument("paths", nargs="*", default=["paddle_tpu"],
                    help="files/directories to lint (default: paddle_tpu)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON object instead of text")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default: tools/graftlint_baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from current findings and exit 0")
    ap.add_argument("--root", default=str(REPO_ROOT),
                    help="root for repo-relative paths (default: repo root)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.id}  {r.name:<16} {r.description}")
        return 0

    paths = args.paths or ["paddle_tpu"]
    try:
        findings, n_files, n_sup = analyze_paths(paths, root=Path(args.root))
    except OSError as e:
        print(f"graftlint: {e}", file=sys.stderr)
        return 2

    if args.update_baseline:
        save_baseline(args.baseline, build_baseline(findings))
        print(f"graftlint: baseline updated — {len(findings)} finding(s) "
              f"across {n_files} file(s) -> {args.baseline}")
        return 0

    baseline = {} if args.no_baseline else load_baseline(args.baseline)
    new, n_base, n_stale = filter_new(findings, baseline)

    if args.as_json:
        by_rule = Counter(f.rule_id for f in new)
        print(json.dumps({
            "files": n_files,
            "findings": len(findings),
            "new": [f.__dict__ for f in new],
            "baselined": n_base,
            "suppressed": n_sup,
            "stale_baseline_entries": n_stale,
            "by_rule": dict(sorted(by_rule.items())),
            "ok": not new,
        }, indent=1))
    else:
        for f in new:
            print(f.format())
        print(f"graftlint: {n_files} files, {len(findings)} finding(s): "
              f"{len(new)} new, {n_base} baselined, {n_sup} suppressed"
              + (f", {n_stale} stale baseline entries "
                 f"(run --update-baseline)" if n_stale else ""))
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
