"""Benchmark/analysis drivers, runnable as scripts or ``python -m
tools.<name>`` (the package form keeps repo-root imports working from
any cwd)."""
