"""Per-op device profile of one train-step config (VERDICT r5 item 2: the
S=16384 step has a 0.4185 MFU with no train-level accounting).

Traces N steps with jax.profiler, parses the Chrome trace the xplane
converter writes, and buckets device-op time into attention kernels /
lm-head+CE / optimizer updates / other fusions — so "is long-S bound by
the 9-plane attention kernel or by CE/scan overhead?" gets a measured
answer instead of an inference.

Usage: python tools/profile_step.py [--seq 16384 --batch 1]
       [--layers 8 --hidden 2048]    # 509M headline dims by default
"""
from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def bucket_of(name: str, args: dict) -> str:
    """Buckets keyed on the HLO metadata, not the mangled event name: the
    flash BACKWARD kernels surface as `transpose_jvp___*` (the autodiff
    transpose of the custom_vjp) with hlo_category=custom-call — name
    matching alone mislabels them as layout copies (r5 lesson)."""
    n = name.lower()
    tf_op = str(args.get("tf_op", "")).lower()
    cat = str(args.get("hlo_category", "")).lower()
    src_line = str(args.get("source", ""))
    if "pallas" in tf_op or "custom-call" in cat or "mosaic" in n:
        if "flash" in src_line or "llama.py" in src_line or "flash" in n:
            return "attention_kernels"
        return "custom_calls"
    if "fused_ce" in src_line or "log_softmax" in n or "take_along" in n:
        return "lmhead_ce"
    if "while" in n:
        return "loops(ce_chunks/stream)"
    if "optimizer" in src_line or "adam" in n:
        return "optimizer"
    if n and n[0].isdigit() or n.startswith("jit_"):
        return "_step_markers"  # parent regions, excluded from totals
    if "copy" in n or "transpose" in n:
        return "copy_transpose"
    if "fusion" in n or "dot" in n or "conv" in n:
        return "matmul_fusions"
    return "other"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq", type=int, default=16384)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=2048)
    ap.add_argument("--inter", type=int, default=5632)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--remat", action="store_true")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--keep", default=None,
                    help="keep the trace dir at this path")
    ap.add_argument("--parse-only", default=None,
                    help="re-analyze an existing trace dir; no chip run")
    args = ap.parse_args()

    if args.parse_only:
        meta = {}
        mp = os.path.join(args.parse_only, "pt_profile_meta.json")
        if os.path.exists(mp):
            meta = json.load(open(mp))
        return analyze(args.parse_only, args,
                       ms=meta.get("step_ms", 0.0),
                       n_params=meta.get("n_params", 0),
                       steps_traced=meta.get("steps_traced",
                                             args.steps + 1))
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import ParallelEngine
    from paddle_tpu.utils.bench_timing import device_time_ms, tpu_lock

    assert any(d.platform in ("tpu", "axon") for d in jax.devices()), \
        "profile_step wants the real chip"
    cfg = LlamaConfig(vocab_size=32000, hidden_size=args.hidden,
                      intermediate_size=args.inter,
                      num_hidden_layers=args.layers,
                      num_attention_heads=args.hidden // 128,
                      num_key_value_heads=max(args.hidden // 256, 1),
                      max_position_embeddings=args.seq, dtype="bfloat16",
                      use_flash_attention=True)
    paddle.seed(0)
    trace_dir = args.keep or tempfile.mkdtemp(prefix="pt_trace_")
    with tpu_lock(timeout_s=900.0) as locked:
        model = LlamaForCausalLM(cfg)
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        opt = AdamW(learning_rate=1e-4, parameters=model.parameters())
        engine = ParallelEngine(model, optimizer=opt, loss_fn=None,
                                remat=args.remat)
        engine.build_train_step()
        rng = np.random.RandomState(0)
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size,
                        (args.batch, args.seq)).astype("int32"))
        labels = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size,
                        (args.batch, args.seq)).astype("int64"))
        ms = device_time_ms(lambda: engine.train_batch(ids, labels),
                            reps=2, warmup=2)  # warms compile + cache
        jax.profiler.start_trace(trace_dir)
        for _ in range(args.steps):
            engine.train_batch(ids, labels)
        # force completion INSIDE the trace window
        float(np.asarray(engine.train_batch(ids, labels).value))
        jax.profiler.stop_trace()

    with open(os.path.join(trace_dir, "pt_profile_meta.json"), "w") as f:
        json.dump({"step_ms": ms, "n_params": n_params,
                   "steps_traced": args.steps + 1,
                   "config": vars(args)}, f)
    analyze(trace_dir, args, ms, n_params, args.steps + 1)
    if not args.keep:
        import shutil

        shutil.rmtree(trace_dir, ignore_errors=True)


def analyze(trace_dir, args, ms, n_params, steps_traced):
    traces = glob.glob(os.path.join(
        trace_dir, "**", "*.trace.json.gz"), recursive=True)
    assert traces, f"no trace written under {trace_dir}"
    with gzip.open(sorted(traces)[-1], "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    # device lanes: pick pids whose process names mention TPU/device
    pid_names = {e["pid"]: e["args"].get("name", "")
                 for e in events if e.get("ph") == "M"
                 and e.get("name") == "process_name"}
    dev_pids = {p for p, n in pid_names.items()
                if "tpu" in n.lower() or "device" in n.lower()
                or "/device" in n.lower()}
    if not dev_pids:  # fall back: everything that isn't python/host
        dev_pids = {p for p, n in pid_names.items()
                    if "python" not in n.lower() and "host" not in n.lower()}
    agg, buckets = {}, {}
    total = 0.0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        name = e.get("name", "?")
        b = bucket_of(name, e.get("args", {}))
        dur = e.get("dur", 0) / 1e3  # ms
        a = agg.setdefault(name, [0, 0.0, b])
        a[0] += 1
        a[1] += dur
        if b == "_step_markers":
            continue  # parent spans would double-count their children
        buckets[b] = buckets.get(b, 0.0) + dur
        total += dur
    print(f"\n== device-op profile: {n_params/1e6:.0f}M, B={args.batch} "
          f"S={args.seq} remat={args.remat} ({steps_traced} steps traced, "
          f"step {ms:.1f} ms) ==")
    print(f"total device-op time {total:.1f} ms "
          f"({total / steps_traced:.1f} ms/step vs {ms:.1f} wall — "
          f"overlap if smaller)")
    print("\n-- buckets --")
    for b, t in sorted(buckets.items(), key=lambda kv: -kv[1]):
        print(f"  {b:<20} {t:>9.1f} ms  {100 * t / max(total, 1e-9):5.1f}%")
    print(f"\n-- top {args.top} ops --")
    for name, (calls, t, b) in sorted(agg.items(), key=lambda kv: -kv[1][1]
                                      )[:args.top]:
        print(f"  {t:>9.2f} ms  x{calls:<5} [{b:<16}] {name[:90]}")


if __name__ == "__main__":
    main()
