"""ERNIE-3.0 base shape sweep: is 0.254 MFU the shape or the framework?

VERDICT r4 weak item 3: the first on-chip ERNIE row (B=32 S=128, 0.254
MFU) was labelled "the shape's ceiling territory" without evidence. This
driver sweeps B ∈ {32,128,256} × S ∈ {128,512} under the drift-robust
round-robin discipline (configs interleave; ranking + per-config medians).
If MFU climbs with B·S the 0.254 was the finetune shape; if it plateaus,
the encoder path has framework overhead to find.

Usage: python tools/bench_ernie_sweep.py [--rounds 2] [--configs 32x128,...]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_CHILD = r"""
import json, sys
import numpy as np
sys.path.insert(0, %(repo)r)
import jax
assert any(d.platform in ("tpu", "axon") for d in jax.devices()), \
    "TPU required, backend is " + jax.devices()[0].platform
import paddle_tpu as paddle
from paddle_tpu.models.ernie import ErnieConfig, ErnieForSequenceClassification
from paddle_tpu.optimizer import AdamW
from paddle_tpu.parallel import ParallelEngine
from paddle_tpu.utils.bench_timing import device_time_ms, peak_flops

B, S = %(B)d, %(S)d
cfg = ErnieConfig(vocab_size=40000, hidden_size=768, num_hidden_layers=12,
                  num_attention_heads=12, intermediate_size=3072,
                  hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                  max_position_embeddings=2048)
model = ErnieForSequenceClassification(cfg, num_classes=2)
n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
opt = AdamW(learning_rate=5e-5, parameters=model.parameters())
engine = ParallelEngine(model, optimizer=opt, loss_fn=model.loss_fn,
                        remat=False)
engine.build_train_step()
rng = np.random.RandomState(0)
ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype("int32"))
labels = paddle.to_tensor(rng.randint(0, 2, (B,)).astype("int64"))
ms = device_time_ms(lambda: engine.train_batch(ids, labels), reps=6, warmup=2)
toks = B * S / (ms / 1e3)
print(json.dumps({"ms": round(ms, 2), "tok_s": round(toks, 1),
                  "ex_s": round(B / (ms / 1e3), 1),
                  "mfu": round(toks * 6.0 * n_params / peak_flops(), 4)}))
"""


def run_once(b, s):
    from paddle_tpu.utils.bench_timing import tpu_lock

    code = _CHILD % {"repo": _REPO, "B": b, "S": s}
    try:
        with tpu_lock(timeout_s=900.0) as locked:
            if not locked:
                print("  [ernie] chip lock contended; sample dropped")
                return None
            out = subprocess.run([sys.executable, "-c", code],
                                 env=dict(os.environ), capture_output=True,
                                 text=True, timeout=900)
        if out.returncode != 0:
            sys.stderr.write((out.stderr or "")[-400:] + "\n")
            return None
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs",
                    default="32x128,128x128,256x128,32x512,128x512")
    ap.add_argument("--rounds", type=int, default=2)
    args = ap.parse_args()
    configs = [tuple(int(v) for v in c.split("x"))
               for c in args.configs.split(",")]
    results = {c: [] for c in configs}
    for r in range(args.rounds):
        for c in configs:
            res = run_once(*c)
            if res is None:
                print(f"  round {r}: B={c[0]:3d} S={c[1]:3d}: FAILED/OOM",
                      flush=True)
                continue
            results[c].append(res)
            print(f"  round {r}: B={c[0]:3d} S={c[1]:3d}: MFU {res['mfu']:.4f}"
                  f" ({res['ms']:.1f} ms, {res['tok_s']:.0f} tok/s,"
                  f" {res['ex_s']:.0f} ex/s)", flush=True)
    print("\n== medians (ERNIE-3.0 base, 118M) ==")
    for c, rs in sorted(results.items()):
        if not rs:
            print(f"  B={c[0]:3d} S={c[1]:3d}: no data")
            continue
        med = statistics.median(x["mfu"] for x in rs)
        tok = statistics.median(x["tok_s"] for x in rs)
        print(f"  B={c[0]:3d} S={c[1]:3d}: median MFU {med:.4f} "
              f"({tok:.0f} tok/s, n={len(rs)})")


if __name__ == "__main__":
    main()
