"""GenerationServer under-load benchmark — continuous batching on chip.

VERDICT r3 item 7: the serving engine (slot pool, mid-flight refill — the
AnalysisPredictor-equivalent deployment story, ref
inference/api/analysis_predictor.cc:929) had CPU tests but no on-chip
throughput-under-load number; tools/decode_benchmark.py measures only raw
``generate``. This driver submits a burst of mixed-prompt-length requests
against a slot pool smaller than the burst (so refill churns), and reports
generated tok/s + per-request completion latency p50/p95.

``--paged`` switches the server to the block-table KV pool (chunked
prefill + prefix caching, docs/serving.md): the JSON line then also
carries ``peak_kv_blocks``/``kv_blocks_total``/``kv_block_size`` so the
memory-proportionality claim (peak blocks ~ active tokens, not
``slots·max_len``) is measured, not asserted. ``--json`` emits exactly ONE
machine-readable JSON line on stdout (bench.py style); without it the same
line is printed plus a human-readable summary on stderr.

Sync honesty: every server tick pulls next-token ids to host
(np.asarray in ``step``), so wall-clock over the drain IS device time —
no reliance on block_until_ready (which lies on the tunneled backend).

Usage: python tools/serving_benchmark.py [--requests 48] [--slots 8]
       [--paged [--block-size 16] [--num-blocks N] [--prefill-chunk 64]]
       [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--max-len", type=int, default=None)
    ap.add_argument("--tick-window", type=int, default=16,
                    help="decode ticks per host round trip (amortizes the "
                         "d2h sync; 1 = exact per-token semantics)")
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 (model.quantize_int8()) under "
                         "the same load — composes the decode win with "
                         "the tick-window server")
    ap.add_argument("--long-prompts", action="store_true",
                    help="mixed prompts 64-512 over buckets (64,128,256,"
                         "512); raises max-len to 768 unless given")
    ap.add_argument("--paged", action="store_true",
                    help="paged KV cache: block-table pool + chunked "
                         "prefill + prefix caching (cache='paged')")
    ap.add_argument("--block-size", type=int, default=16,
                    help="tokens per KV block (paged only)")
    ap.add_argument("--num-blocks", type=int, default=None,
                    help="total KV blocks in the pool (paged only; default "
                         "sizes for dense parity)")
    ap.add_argument("--prefill-chunk", type=int, default=64,
                    help="tokens per chunked-prefill program (paged only)")
    ap.add_argument("--json", action="store_true",
                    help="emit exactly one machine-readable JSON line "
                         "(bench.py style) on stdout and nothing else")
    args = ap.parse_args()
    if args.max_len is None:
        args.max_len = 768 if args.long_prompts else 256

    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import GenerationServer
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5632, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=args.max_len,
                          dtype="bfloat16", use_flash_attention=True)
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=64,
                          intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=args.max_len,
                          dtype="float32", use_flash_attention=False)
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    rng = np.random.RandomState(0)

    def burst(server, n):
        """Mixed prompt lengths across the bucket ladder."""
        lens = rng.choice([64, 128, 256, 400, 512] if args.long_prompts
                          else [16, 30, 64, 100, 128], size=n)
        rids = {}
        for ln in lens:
            prompt = rng.randint(1, cfg.vocab_size, int(ln)).tolist()
            rids[server.submit(prompt, max_new_tokens=args.max_new)] = int(ln)
        return rids

    import contextlib

    from paddle_tpu.utils.bench_timing import tpu_lock

    def make_server():
        if args.paged:
            return GenerationServer(
                model, max_batch=args.slots, max_len=args.max_len,
                tick_window=args.tick_window, cache="paged",
                block_size=args.block_size, num_blocks=args.num_blocks,
                prefill_chunk=args.prefill_chunk)
        return GenerationServer(model, max_batch=args.slots,
                                max_len=args.max_len,
                                prompt_buckets=((64, 128, 256, 512)
                                                if args.long_prompts
                                                else (32, 64, 128)),
                                tick_window=args.tick_window)

    # CPU smoke runs don't touch the chip — don't serialize on its lock
    lock = tpu_lock(timeout_s=900.0) if on_tpu else \
        contextlib.nullcontext(True)
    with lock as locked:
        if args.int8:
            model.quantize_int8()
        server = make_server()
        # warmup drain: compiles the decode tick + the prefill program(s)
        burst(server, min(args.slots, 4))
        server.run()

        rids = burst(server, args.requests)
        t0 = time.perf_counter()
        done_at = {}
        while True:
            remaining = server.step()
            now = time.perf_counter()
            for rid in list(server._results):
                if rid not in done_at:
                    done_at[rid] = now - t0
            if remaining == 0:
                break
        dt = time.perf_counter() - t0
        out = server._results
    gen_tokens = sum(len(v) - rids[r] for r, v in out.items() if r in rids)
    lats = sorted(done_at[r] for r in rids if r in done_at)
    p50 = lats[len(lats) // 2]
    p95 = lats[min(len(lats) - 1, int(len(lats) * 0.95))]
    line = {"metric": "serving_continuous_batching_tok_s_1chip",
            "value": round(gen_tokens / dt, 1),
            "unit": f"generated tok/s ({args.requests} reqs, {args.slots} "
                    f"slots, max_new={args.max_new}, mixed prompts "
                    f"{'64-512' if args.long_prompts else '16-128'}, "
                    f"tick_window={args.tick_window}, "
                    f"{'int8' if args.int8 else 'bf16'} weights, "
                    f"params={n_params/1e6:.0f}M)",
            "kv_cache": "paged" if args.paged else "dense",
            "p50_s": round(p50, 3), "p95_s": round(p95, 3),
            "wall_s": round(dt, 2)}
    if args.paged:
        stats = server.kv_stats()
        line["peak_kv_blocks"] = stats["peak_blocks_in_use"]
        line["kv_blocks_total"] = stats["num_blocks"]
        line["kv_block_size"] = stats["block_size"]
        line["prefix_hit_blocks"] = stats["prefix_hit_blocks"]
        line["prefill_chunk"] = server.prefill_chunk
    if not locked:
        line["lock_contended"] = True
    print(json.dumps(line))
    if not args.json:
        mode = "paged" if args.paged else "dense"
        extra = (f", peak blocks {line.get('peak_kv_blocks')}/"
                 f"{line.get('kv_blocks_total')}" if args.paged else "")
        print(f"[{mode}] {line['value']} tok/s, p50 {line['p50_s']}s, "
              f"p95 {line['p95_s']}s over {line['wall_s']}s{extra}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
