"""GenerationServer under-load benchmark — continuous batching on chip.

VERDICT r3 item 7: the serving engine (slot pool, mid-flight refill — the
AnalysisPredictor-equivalent deployment story, ref
inference/api/analysis_predictor.cc:929) had CPU tests but no on-chip
throughput-under-load number; tools/decode_benchmark.py measures only raw
``generate``. This driver submits a burst of mixed-prompt-length requests
against a slot pool smaller than the burst (so refill churns), and reports
generated tok/s + per-request completion latency p50/p95.

``--paged`` switches the server to the block-table KV pool (chunked
prefill + prefix caching, docs/serving.md): the JSON line then also
carries ``peak_kv_blocks``/``kv_blocks_total``/``kv_block_size`` so the
memory-proportionality claim (peak blocks ~ active tokens, not
``slots·max_len``) is measured, not asserted. ``--json`` emits exactly ONE
machine-readable JSON line on stdout (bench.py style); without it the same
line is printed plus a human-readable summary on stderr.

Sync honesty: every server tick pulls next-token ids to host
(np.asarray in ``step``), so wall-clock over the drain IS device time —
no reliance on block_until_ready (which lies on the tunneled backend).

``--spec K`` stacks speculative decoding on the paged server (drafter →
one fused k+1-wide verify program, exact acceptance): the JSON line gains
``acceptance_rate`` and ``draft_tokens_proposed/accepted``;
``--repeat-suffix`` switches to the repeated-suffix workload where
prompt-lookup drafting shines.

Overload / scheduling (docs/serving.md "Scheduling and host KV offload"):
``--arrival-rate R`` switches from the submit-everything burst to an
OPEN-LOOP bursty generator — requests arrive in ``--burst``-sized clumps
on a pre-drawn timeline (exponential inter-burst gaps at R req/s overall)
that does NOT wait for the server, so queueing delay shows up in TTFT
instead of being hidden by closed-loop self-pacing. The whole traffic
trace (lengths, arrival times, priorities) is drawn up front from
``--seed``, so a run is reproducible end to end. ``--pool-frac F``
shrinks the KV pool to F× dense parity (demand > pool → swap-preemption
fires), ``--scheduler priority --mixed-priority`` splits traffic across
priority classes/tenants, and the JSON line gains
``ttft_p50_s/ttft_p95_s`` (plus per-class splits), ``tpot_p50_ms/
tpot_p95_ms``, and the preemption/swap counters.

Chaos soak (docs/serving.md "Fault tolerance and degradation"):
``--chaos`` runs the seeded traffic twice — a fault-free reference pass,
then the measured pass with ``FaultPlan.chaos(--seed)`` injected into
the paged substrate (allocator exhaustion, tick faults, drafter
failures, bit-flipped swap payloads) and the scheduler clock wrapped by
the injector. Pool conservation is asserted after every tick; the JSON
line gains ``faults_injected/quarantined/token_mismatches/ref_tok_s``.
``--strict`` turns telemetry on and exits non-zero on any watchdog
finding (under ``--chaos``, on the post-plan recovery burst, which must
come back clean).

Fleet (docs/serving.md "Fleet: routing, failover, migration"):
``--fleet N`` routes the same seeded traffic through a ``FleetRouter``
of N replica engines (prefix-aware routing, health-checked membership)
and the JSON line gains one row per replica (``fleet_metrics()``).
Under ``--chaos`` the reference pass becomes an UNDISTURBED single
engine and the measured fleet runs ``FaultPlan.fleet_chaos(--seed)``:
one replica is killed mid-decode and the line reports
``fleet_deaths / token_mismatches / quarantined`` plus
``ref_drain_recompiles / drain_recompiles`` — the failover drain is
held to the twin's compile budget by the same jit-cache guard.

Multi-chip (docs/serving.md "Multi-chip serving"): ``--mesh tp=N``
serves the same workload with params, KV block pool, int8 scales, and
LoRA pages sharded over an N-way tensor-parallel mesh (placement-only
GSPMD sharding — the compiled programs are unchanged and tokens are
bit-identical to tp=1). The JSON line gains ``tp`` / ``mesh`` /
``tok_s_per_chip`` (= value / (tp x replicas)) and a
``tokens_fingerprint`` hash of every output sequence, so a suite gate
can assert token-equality across mesh widths from the lines alone. On
CPU (JAX_PLATFORMS=cpu) the tool forces enough XLA host devices for the
dryrun mesh. ``--disagg`` (with ``--fleet N``) specializes the replicas
into floor(N/2) prefill-class + the rest decode-class engines: fresh
prompts route to the prefill class, finished prefills hand off over the
CRC-verified migration path, and the line gains ``prefill_replicas`` /
``decode_replicas`` / ``handoffs`` / ``handoff_requests`` plus
migration-latency percentiles. Under ``--chaos`` the disaggregated
fleet runs a seeded PREFILL-replica kill (``FaultPlan.disagg_chaos``)
instead of the generic fleet plan, so the salvage-onto-decode-class
path is what the twin comparison exercises.

Long-context (docs/serving.md "Long-context serving"):
``--long-context`` draws prompts from a log-spaced 8k-128k ladder
(``--lc-min/--lc-max`` rescale it for CPU dryruns), ``--shared-prefix F``
overlays one shared per-seed prefix on every prompt (the cross-request
prefix-cache workload), and ``--mesh tp=NxCp=M`` adds a context-parallel
axis that shards the chunked prefill's sequence dimension — tokens stay
bit-identical to cp=1. ``--tier-demote LOW:HIGH`` turns on
watermark-driven hot->warm KV demotion (``--warm-pool-mb`` caps the warm
tier; over budget, demotions fall to cold re-prefill). The paged JSON
line then reports ``prefill_tok_s_per_chip`` and ``tier_hit_rate``
{hot, warm, cold} alongside the demotion/promotion counters.

Every JSON line carries ``schema_version`` plus ``config_fingerprint``
(a stable hash of the resolved workload/config knobs, reporting-only
flags excluded) so downstream tooling can both detect schema drift and
refuse to diff lines that measured different configurations.

Traffic is decoupled from the serving config: the measured requests
(and the open-loop schedule) come from one ``RandomState(--seed)``
stream, while the config-scaled warmup bursts draw from a DISJOINT
xor-seeded stream with their own request counter — so any two configs
at the same ``--seed`` serve byte-identical traffic. The line's
``traffic_fingerprint`` hashes the measured submit timeline directly
(and token-exact serving then makes ``tokens_fingerprint`` match
across configs too — the autotuner's correctness gate rides on this).

Autotuning (docs/autotuning.md): ``--profile PATH`` replays a tuned
profile (``paddle_tpu.autotune`` JSON) — the server is built via
``GenerationServer(profile=...)`` and the profile's knobs override the
per-knob flags; the line gains ``profile_fingerprint`` /
``profile_workload_match``. ``--tune BUDGET`` runs the cost-model
search over THIS benchmark's seeded workload first, replays the
winning config as the measured run, and (with ``--profile PATH``)
saves the winner there; the line gains ``tuned`` / ``tune_budget`` /
``tune_baseline_tok_s`` / ``tune_trials``.

Usage: python tools/serving_benchmark.py [--requests 48] [--slots 8]
       [--seed 0] [--arrival-rate R --burst B]
       [--scheduler fifo|priority|wfq [--mixed-priority]]
       [--paged [--block-size 16] [--num-blocks N] [--pool-frac F]
        [--host-pool-mb M] [--prefill-chunk 64]
        [--spec 4 [--spec-drafter ngram|model] [--repeat-suffix]]
        [--long-context [--lc-min A --lc-max B] [--shared-prefix F]]
        [--tier-demote L:H [--warm-pool-mb M]]
        [--mesh tp=N[xcp=M]] [--fleet N [--disagg]] [--chaos [--strict]]
        [--profile PATH | --tune BUDGET [--profile OUT]]]
       [--json]
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

#: Bump when the JSON line's keys change meaning or go away (adding keys
#: is compatible and does NOT bump): 2 = schema_version/config_fingerprint
#: introduced alongside the --fleet rows; 3 = ``value`` is still FLEET-WIDE
#: tok/s but the normalized figure moved to the new ``tok_s_per_chip``
#: (value / (tp x replicas)) — readers that treated the fleet ``value`` as
#: a per-chip number must switch keys. Every v2 key is still present.
#: 4 = long-context serving: ``mesh`` strings may now carry a cp axis
#: (``tpNcpM``) and per-chip figures divide by tp x cp; paged lines gain
#: ``prefill_tok_s_per_chip`` and ``tier_hit_rate`` {hot, warm, cold}.
#: Every v3 key is still present with its v3 meaning at cp=1.
#: 5 = ``kernels`` is stamped on EVERY line (v4 only stamped it on paged
#: microbench lines — readers keying dispatch mode off its presence must
#: read its value instead); ``--kernels megakernel`` joins the enum and
#: paged microbench lines gain ``megakernel_active`` (the eager guard's
#: verdict) plus ``megakernel_tok_s`` / ``megakernel_dispatch_us`` (the
#: whole-tick program at server shapes) when the rung engaged.
#: 6 = fleet scale: ``--fleet`` lines now carry ``slo`` (per-tenant
#: TTFT/TPOT attainment + burn rate from the router's roll-up) on every
#: line, not only under --strict; the new ``--sim`` mode emits a
#: ``serving_fleetsim_sessions_s`` line (discrete-event day simulation,
#: no engine) with ``sim_sessions`` / ``sim_virtual_hours`` /
#: ``replica_hours`` / ``autoscale_events`` / per-tenant ``slo``.
#: Every v5 key is still present with its v5 meaning.
SCHEMA_VERSION = 6


def config_fingerprint(args) -> str:
    """Stable hash of every resolved knob that defines the measured
    configuration (reporting-only flags excluded). Two JSON lines with
    the same fingerprint measured the same setup — the suite gate and
    regression tooling refuse to diff lines whose fingerprints differ."""
    skip = {"json", "telemetry_out", "strict"}
    src = {k: v for k, v in sorted(vars(args).items()) if k not in skip}
    return hashlib.sha256(
        json.dumps(src, sort_keys=True, default=str).encode()
    ).hexdigest()[:12]


def kernel_microbench(server, cfg, args, iters: int = 10):
    """Per-op dispatch timing of the paged decode-attention program at the
    SERVER'S shapes (slots, table width, block size, kv heads) — the
    kernel-level tok/s figure behind the end-to-end line. Times the active
    dispatch (``kernel_tok_s`` / ``kernel_dispatch_us``) and the pinned jnp
    reference (``kernel_ref_tok_s``) so the win is measured, not asserted.
    Runs AFTER the measured drain — it jits two fresh closures and must not
    count against the steady-state recompile guard."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from paddle_tpu import ops
    from paddle_tpu.framework.dtype import convert_dtype
    from paddle_tpu.ops import paged_attention as pa

    B = args.slots
    bs = args.block_size
    H = cfg.num_attention_heads
    KV = cfg.num_key_value_heads
    D = cfg.hidden_size // H
    M = server._table_width
    N = server.alloc.num_blocks
    dt = convert_dtype(cfg.dtype)
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, 1, H, D), dt)
    tables = jnp.asarray(
        rng.randint(1, max(N, 2), (B, M)).astype(np.int32))
    pos = jnp.full((B,), min(args.max_len - 1, M * bs - 1), jnp.int32)
    if args.kv_quant == "int8":
        kq = jnp.asarray(rng.randint(-127, 128, (N, bs, KV, D)), jnp.int8)
        ks = jnp.asarray(np.abs(rng.randn(N, KV)).astype(np.float32))
        op_args = (q, kq, ks, kq, ks, tables, pos)
        op = pa.paged_decode_attention_q
    else:
        kp = jnp.asarray(rng.randn(N, bs, KV, D), dt)
        op_args = (q, kp, kp, tables, pos)
        op = pa.paged_decode_attention

    def timed(fn):
        jf = jax.jit(fn)
        jf(*op_args)[0].block_until_ready()        # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            out = jf(*op_args)
        out.block_until_ready()
        return (time.perf_counter() - t0) / iters

    mode = ops.kernel_mode()
    try:
        active_s = timed(lambda *a: op(*a))
        ops.set_kernel_mode("reference")
        ref_s = timed(lambda *a: op(*a))
    finally:
        ops.set_kernel_mode(mode)
    out = {"kernel_tok_s": round(B / active_s, 1),
           "kernel_ref_tok_s": round(B / ref_s, 1),
           "kernel_dispatch_us": round(active_s * 1e6, 1)}
    ex = getattr(server, "_exec", None)
    if args.kernels == "megakernel":
        out["megakernel_active"] = bool(getattr(ex, "megakernel", False))
        if not out["megakernel_active"]:
            out["megakernel_reason"] = getattr(
                ex, "megakernel_reason", None)
    if out.get("megakernel_active"):
        # the whole-tick persistent program at the same server shapes —
        # ``kernel_tok_s`` above times ONE layer's attention op, this
        # times embed-to-last-layer in a single dispatch
        from paddle_tpu.ops import decode_megakernel as mkk

        L = cfg.num_hidden_layers
        flat = []
        for _ in range(L):
            for _kv in range(2):
                if args.kv_quant == "int8":
                    flat.append(jnp.asarray(
                        rng.randint(-127, 128, (N, bs, KV, D)), jnp.int8))
                    flat.append(jnp.asarray(
                        np.abs(rng.randn(N, KV)).astype(np.float32) + 1e-3))
                else:
                    flat.append(jnp.asarray(rng.randn(N, bs, KV, D), dt))
        xa = jnp.asarray(rng.randn(B, 1, cfg.hidden_size), dt)
        m = server.model.model
        cosr, sinr = mkk.gather_rope_rows(m._cos, m._sin, pos, 1)
        w, geom = ex._mk_weights, ex._mk_geometry

        def tick_fn(xx, *fl):
            xo, _ = mkk.decode_tick(
                xx, list(fl), tables, pos, w, cosr, sinr,
                block_size=bs, geometry=geom, eps=cfg.rms_norm_eps)
            return xo

        try:
            ops.set_kernel_mode("megakernel")
            jf = jax.jit(lambda *a: tick_fn(*a))
            jf(xa, *flat).block_until_ready()
            t0 = time.perf_counter()
            for _ in range(iters):
                tick_out = jf(xa, *flat)
            tick_out.block_until_ready()
        finally:
            ops.set_kernel_mode(mode)
        mk_s = (time.perf_counter() - t0) / iters
        out["megakernel_tok_s"] = round(B / mk_s, 1)
        out["megakernel_dispatch_us"] = round(mk_s * 1e6, 1)
    return out


def sim_main(args):
    """--sim: the discrete-event day simulation (paddle_tpu.fleetsim) —
    a million seeded session arrivals against the analytic replica
    model under the elastic autoscaler, in virtual time. Emits one
    ``serving_fleetsim_sessions_s`` JSON line whose payload (including
    ``autoscale_events`` and per-tenant ``slo``) is byte-identical per
    seed; ``value`` is the only wall-time-dependent key (simulator
    throughput, sessions per wall second)."""
    from paddle_tpu.fleetsim import (DayTrafficSpec, FleetSimulation,
                                     ReplicaServiceModel, draw_day)
    from paddle_tpu.inference.autoscale import (AutoscalePolicy,
                                                ElasticAutoscaler,
                                                verify_replay)

    spec = DayTrafficSpec(sessions=args.sim_sessions, seed=args.seed)
    cap = float(args.sim_capacity)
    policy = AutoscalePolicy(min_replicas=1,
                             max_replicas=args.sim_max_replicas,
                             up_cooldown_s=120.0, down_cooldown_s=1200.0)
    engine = ElasticAutoscaler(cap, policy=policy)
    model = ReplicaServiceModel(decode_tok_s=cap, prefill_tok_s=8.0 * cap,
                                slots=16, spawn_delay_s=30.0)
    t0 = time.perf_counter()
    trace = draw_day(spec)
    report = FleetSimulation(trace, model, autoscaler=engine,
                             initial_replicas=2,
                             control_interval_s=60.0,
                             forecast_horizon_s=900.0).run()
    wall = time.perf_counter() - t0
    # the journal must replay before it is reported — an event log that
    # does not reproduce its own decisions is a log of accidents
    verify_replay(report["autoscale_events"], cap, policy=policy)
    line = {"metric": "serving_fleetsim_sessions_s",
            "value": round(args.sim_sessions / wall, 1),
            "unit": f"simulated sessions / wall second "
                    f"({args.sim_sessions} sessions, "
                    f"{report['sim_virtual_hours']}h virtual, "
                    f"cap={cap:g} tok/s, "
                    f"max={args.sim_max_replicas} replicas)",
            "sim_sessions": report["sim_sessions"],
            "sim_virtual_hours": report["sim_virtual_hours"],
            "replica_hours": report["replica_hours"],
            "static_replicas": report["static_replicas"],
            "static_replica_hours": report["static_replica_hours"],
            "elastic_beats_static": report["elastic_beats_static"],
            "autoscale_events": report["autoscale_event_count"],
            "scale_ups": report["scale_ups"],
            "scale_downs": report["scale_downs"],
            "peak_replicas": report["peak_replicas"],
            "completed": report["completed"],
            "mean_ttft_s": report["mean_ttft_s"],
            "tokens_served": report["tokens_served"],
            "slo": report["slo"],
            "slo_attained": report["slo_attained"],
            "slo_target": report["slo_target"],
            "traffic_signature": report["traffic_signature"],
            "wall_s": round(wall, 2),
            "seed": args.seed,
            "schema_version": SCHEMA_VERSION,
            "kernels": args.kernels,
            "config_fingerprint": config_fingerprint(args)}
    print(json.dumps(line))
    if not args.json:
        print(f"[fleetsim] {args.sim_sessions} sessions / "
              f"{report['sim_virtual_hours']}h virtual in {wall:.2f}s "
              f"wall; elastic {report['replica_hours']}h vs static "
              f"{report['static_replica_hours']}h replica-hours "
              f"({report['scale_ups']} ups, {report['scale_downs']} "
              f"downs, peak {report['peak_replicas']}), SLO attained: "
              f"{report['slo_attained']}", file=sys.stderr)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=48)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-new", type=int, default=None,
                    help="generated tokens per request (default 64; 128 "
                         "under --repeat-suffix, whose long-form "
                         "repetitive generations are the point)")
    ap.add_argument("--max-len", type=int, default=None)
    ap.add_argument("--tick-window", type=int, default=None,
                    help="decode ticks per host round trip (amortizes the "
                         "d2h sync; 1 = exact per-token semantics). Default "
                         "16; 4 under --spec, where each window already "
                         "advances up to k+1 tokens so fewer windows per "
                         "trip keep per-trip emission comparable while "
                         "cutting surplus verify work past finished "
                         "requests")
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 (model.quantize_int8()) under "
                         "the same load — composes the decode win with "
                         "the tick-window server")
    ap.add_argument("--long-prompts", action="store_true",
                    help="mixed prompts 64-512 over buckets (64,128,256,"
                         "512); raises max-len to 768 unless given")
    ap.add_argument("--long-context", action="store_true",
                    help="long-context preset (paged only): prompt "
                         "lengths drawn from a log-spaced ladder "
                         "--lc-min..--lc-max (5 rungs, rounded to block "
                         "multiples); raises max-len to lc-max + max-new "
                         "unless given. Combine with --shared-prefix / "
                         "--tier-demote to exercise the hot/warm/cold "
                         "KV ladder (docs/serving.md)")
    ap.add_argument("--lc-min", type=int, default=8192,
                    help="shortest long-context prompt rung (default 8k; "
                         "shrink for CPU dryruns)")
    ap.add_argument("--lc-max", type=int, default=131072,
                    help="longest long-context prompt rung (default 128k)")
    ap.add_argument("--shared-prefix", type=float, default=0.0, metavar="F",
                    help="fraction [0,1] of every prompt replaced by ONE "
                         "shared token prefix (drawn once per seed) — the "
                         "cross-request prefix-cache / warm-tier workload")
    ap.add_argument("--tier-demote", default=None, metavar="LOW:HIGH",
                    help="enable watermark-driven hot->warm KV demotion "
                         "(paged only): when the free-block fraction "
                         "falls below LOW, cached blocks demote to the "
                         "host warm tier until HIGH is free again "
                         "(e.g. 0.1:0.3)")
    ap.add_argument("--warm-pool-mb", type=float, default=None,
                    help="cap the warm-tier byte budget (default "
                         "unbounded); over-budget demotions fall to the "
                         "cold tier (re-prefill from replay)")
    ap.add_argument("--paged", action="store_true",
                    help="paged KV cache: block-table pool + chunked "
                         "prefill + prefix caching (cache='paged')")
    ap.add_argument("--block-size", type=int, default=16,
                    help="tokens per KV block (paged only)")
    ap.add_argument("--num-blocks", type=int, default=None,
                    help="total KV blocks in the pool (paged only; default "
                         "sizes for dense parity)")
    ap.add_argument("--prefill-chunk", type=int, default=64,
                    help="tokens per chunked-prefill program (paged only)")
    ap.add_argument("--kv-quant", choices=("none", "int8"), default="none",
                    help="KV pool storage (paged only): int8 stores blocks "
                         "as int8 codes + per-block-per-head f32 scales "
                         "with dequant fused into the attention programs. "
                         "Without --num-blocks, the pool is sized to the "
                         "SAME byte budget the fp pool would get, so the "
                         "JSON's kv_blocks_total shows the capacity win "
                         "directly (~2x bf16 / ~4x f32)")
    ap.add_argument("--lora-adapters", type=int, default=0, metavar="N",
                    help="multi-tenant LoRA workload (paged only): register "
                         "N random adapters, assign requests round-robin "
                         "(request i uses adapter a{i%%N}, tenant t{i%%N}) "
                         "so adapter residency churns; JSON line gains "
                         "adapter_pool_bytes / adapter_hit_rate / "
                         "adapter_uploads + the per-tenant TTFT/TPOT "
                         "breakdown")
    ap.add_argument("--lora-rank", type=int, default=8,
                    help="rank of every generated adapter (and the pool's "
                         "max_rank)")
    ap.add_argument("--lora-live", type=int, default=None, metavar="M",
                    help="adapter-pool pages = max concurrently-resident "
                         "adapters (default min(N, slots)); N > M forces "
                         "LRU eviction + re-upload churn")
    ap.add_argument("--kernels",
                    choices=("auto", "pallas", "megakernel", "reference"),
                    default="auto",
                    help="attention/projection kernel dispatch for the "
                         "compiled serving programs: auto = Pallas on TPU / "
                         "jnp reference elsewhere, pallas = force the "
                         "Pallas kernels (interpret mode off-TPU), "
                         "megakernel = the whole-tick persistent program "
                         "(paged only; falls back to pallas when the "
                         "eager guard rejects the geometry), reference = "
                         "pin the jnp compositions")
    ap.add_argument("--guard-recompiles", action="store_true",
                    help="wrap the measured drain in jit_cache_guard: any "
                         "steady-state recompile after warmup fails the "
                         "run (exit 1)")
    ap.add_argument("--spec", type=int, default=0, metavar="K",
                    help="speculative decoding with K drafts per verify "
                         "window (paged only). The ngram drafter runs "
                         "in-program, so tick-window verify windows fuse "
                         "into one compiled scan per host trip; the model "
                         "drafter forces tick-window=1. JSON line gains "
                         "acceptance_rate + draft_tokens_proposed/accepted")
    ap.add_argument("--spec-drafter", choices=("ngram", "model"),
                    default="ngram",
                    help="drafter: prompt-lookup n-gram (hermetic) or a "
                         "small draft llama sharing the tokenizer")
    ap.add_argument("--repeat-suffix", action="store_true",
                    help="repeated-suffix workload: prompts tile a short "
                         "motif, so generation loops the drafter can "
                         "predict — the speculative showcase")
    ap.add_argument("--seed", type=int, default=0,
                    help="seeds the whole traffic trace (prompt lengths, "
                         "contents, arrival times, priority assignment) — "
                         "same seed, same workload, run to run")
    ap.add_argument("--arrival-rate", type=float, default=None, metavar="R",
                    help="open-loop arrivals at R requests/s overall, in "
                         "--burst clumps with exponential inter-burst gaps "
                         "(drawn from --seed). Without it, all requests "
                         "are submitted up front (closed-loop burst)")
    ap.add_argument("--burst", type=int, default=4,
                    help="requests per arrival clump in open-loop mode")
    ap.add_argument("--scheduler", choices=("fifo", "priority", "wfq"),
                    default="fifo",
                    help="GenerationServer policy= (inference/scheduler.py)")
    ap.add_argument("--mixed-priority", action="store_true",
                    help="assign priorities round-robin (high/normal/low) "
                         "and tenants (a/b) so --scheduler priority|wfq "
                         "has classes to separate; the JSON line then "
                         "splits TTFT percentiles per class")
    ap.add_argument("--pool-frac", type=float, default=None, metavar="F",
                    help="shrink the paged pool to F x dense parity so "
                         "demand exceeds the pool and swap-preemption "
                         "fires (overload mode; paged only)")
    ap.add_argument("--host-pool-mb", type=float, default=None,
                    help="cap the host swap pool (default unbounded); "
                         "0 disables swapping — victims stall instead")
    ap.add_argument("--telemetry-out", metavar="PATH", default=None,
                    help="enable serving telemetry (span tracer + flight "
                         "recorder) and dump PATH.metrics.json (registry "
                         "snapshot + watchdog findings), PATH.trace.json "
                         "(chrome trace: one timeline row per request), "
                         "and PATH.flight.json (per-tick flight ring) "
                         "after the drain. The TTFT/TPOT percentiles in "
                         "the JSON line come from the same registry "
                         "histograms either way")
    ap.add_argument("--mesh", default=None, metavar="tp=N[xcp=M]",
                    help="serve over a device mesh (paged only): 'tp=N' "
                         "shards params, KV block pool, int8 scales, and "
                         "LoRA pages over an N-way tensor-parallel axis; "
                         "'cp=M' / 'tp=NxCp=M' adds an M-way "
                         "context-parallel axis that shards the chunked "
                         "prefill's sequence dimension (long-context "
                         "prefill scaling). Tokens stay bit-identical to "
                         "tp=1/cp=1 (the line's tokens_fingerprint "
                         "proves it) and the line gains "
                         "tp/cp/tok_s_per_chip/prefill_tok_s_per_chip. "
                         "Accepts 'tp=N', 'cp=M', 'tp=NxCp=M', or a bare "
                         "int (tp). On CPU the tool forces NxM XLA host "
                         "devices for the dryrun")
    ap.add_argument("--disagg", action="store_true",
                    help="with --fleet N: specialize the replicas into "
                         "floor(N/2) prefill-class + the rest "
                         "decode-class engines — fresh prompts route to "
                         "the prefill class, finished prefills hand off "
                         "to the decode class over the CRC-verified "
                         "migration path; the line gains "
                         "prefill_replicas/decode_replicas/handoffs + "
                         "migration-latency percentiles. With --chaos "
                         "the seeded plan kills a PREFILL replica so "
                         "the decode-class salvage path is exercised")
    ap.add_argument("--fleet", type=int, default=0, metavar="N",
                    help="route the traffic through a FleetRouter of N "
                         "replica engines (paged only, N >= 2): "
                         "prefix-aware routing + health-checked "
                         "membership; the JSON line gains per-replica "
                         "rows. With --chaos the reference is an "
                         "UNDISTURBED single engine and the fleet runs "
                         "FaultPlan.fleet_chaos(--seed) — one replica "
                         "dies mid-decode; the line reports fleet_deaths"
                         "/token_mismatches/quarantined and the failover "
                         "drain is held to the twin's compile budget")
    ap.add_argument("--chaos", action="store_true",
                    help="chaos soak (paged only): run the seeded traffic "
                         "twice — a fault-free reference pass, then the "
                         "measured pass with FaultPlan.chaos(--seed) "
                         "injected (pool exhaustion, tick faults, drafter "
                         "failures, swap corruption) and the scheduler "
                         "clock injector-wrapped. Pool conservation is "
                         "asserted after EVERY tick; the JSON line gains "
                         "faults_injected / quarantined / "
                         "token_mismatches (non-quarantined outputs vs "
                         "the reference) / ref_tok_s")
    ap.add_argument("--profile", metavar="PATH", default=None,
                    help="apply a tuned serving profile (paddle_tpu."
                         "autotune JSON): the server is built via "
                         "GenerationServer(profile=...) and the profile's "
                         "knobs OVERRIDE the per-knob flags (--block-size/"
                         "--tick-window/--kv-quant/--scheduler/...). With "
                         "--tune, PATH is where the freshly tuned profile "
                         "is written before the measured replay")
    ap.add_argument("--geometry-cache", metavar="PATH", default=None,
                    help="install a swept kernel-geometry winner cache "
                         "(kernel_bench.py --sweep-geometry --emit-cache "
                         "JSON) before the server is built: every kernel "
                         "trace resolves its schedule from the cache "
                         "(source 'swept'); a --profile with its own "
                         "kernel_geometry takes precedence")
    ap.add_argument("--tune", type=int, default=None, metavar="BUDGET",
                    help="run the cost-model autotuner (paddle_tpu."
                         "autotune) over this benchmark's seeded workload "
                         "with BUDGET measured candidate trials, then "
                         "replay the WINNING config as the measured run; "
                         "--profile PATH saves the winner")
    ap.add_argument("--strict", action="store_true",
                    help="enable telemetry and exit non-zero on any "
                         "watchdog finding — over the measured drain, or "
                         "(under --chaos) over a post-plan recovery burst, "
                         "which must come back clean")
    ap.add_argument("--sim", action="store_true",
                    help="discrete-event fleet simulation instead of an "
                         "engine run: draw a seeded day of traffic "
                         "(paddle_tpu.fleetsim), replay it against the "
                         "analytic replica model under the elastic "
                         "autoscaler in fast-time, and emit one "
                         "serving_fleetsim_sessions_s line — no model, "
                         "no chip, byte-identical per --seed")
    ap.add_argument("--sim-sessions", type=int, default=1_000_000,
                    help="sessions in the simulated day (default 1M)")
    ap.add_argument("--sim-capacity", type=float, default=400.0,
                    metavar="TOK_S",
                    help="analytic per-replica decode capacity for --sim "
                         "(tokens/s; the cost model supplies this on a "
                         "real deployment via capacity_tok_s)")
    ap.add_argument("--sim-max-replicas", type=int, default=12,
                    help="autoscaler ceiling for --sim")
    ap.add_argument("--json", action="store_true",
                    help="emit exactly one machine-readable JSON line "
                         "(bench.py style) on stdout and nothing else")
    args = ap.parse_args()
    if args.sim:
        if args.fleet or args.chaos or args.paged or args.spec \
                or args.tune is not None or args.profile is not None:
            ap.error("--sim is the pure fast-time simulator — it takes "
                     "no engine knobs (--paged/--fleet/--chaos/--spec/"
                     "--profile/--tune); size it with --sim-sessions/"
                     "--sim-capacity/--sim-max-replicas and --seed")
        sim_main(args)
        return
    if args.chaos and not args.paged:
        ap.error("--chaos requires --paged (the fault sites live in the "
                 "paged substrate)")
    if args.fleet:
        if not args.paged:
            ap.error("--fleet requires --paged (migration rides the "
                     "per-request KV capture)")
        if args.fleet < 2:
            ap.error("--fleet needs N >= 2 — failover has to have "
                     "somewhere to go")
        if args.spec:
            ap.error("--fleet is incompatible with --spec (one knob at "
                     "a time; spec state does migrate, but the fleet "
                     "benchmark measures routing/failover)")
        if args.arrival_rate is not None:
            ap.error("--fleet uses the closed-loop burst (seeded bursty "
                     "traffic); --arrival-rate is not modeled for it")
    if args.disagg and not args.fleet:
        ap.error("--disagg requires --fleet N (N >= 2): prefill and "
                 "decode classes need separate replicas")
    if args.profile is not None or args.tune is not None:
        if not args.paged:
            ap.error("--profile/--tune require --paged (every tuned "
                     "config serves from the paged substrate)")
        if args.fleet or args.chaos:
            ap.error("--profile/--tune are incompatible with --fleet/"
                     "--chaos (tune the single-engine config; fleet "
                     "knobs ride the profile's fleet_* entries)")
        if args.tune is not None and args.tune < 1:
            ap.error("--tune BUDGET must be >= 1")
        if args.tune is not None and args.lora_adapters:
            ap.error("--tune does not model the adapter pool yet — "
                     "tune the base-engine knobs without --lora-adapters, "
                     "then replay the profile WITH them")
    tp, cp = 1, 1
    if args.mesh is not None:
        if not args.paged:
            ap.error("--mesh requires --paged (the sharded pools ARE the "
                     "paged substrate)")
        # mirrors paddle_tpu.parallel.serving_mesh.parse_mesh, but WITHOUT
        # importing it: the XLA host-device-count flag below only takes
        # effect if set before the first jax import
        m = str(args.mesh).strip().lower()
        try:
            if "=" not in m:
                tp = int(m)
            else:
                for part in m.split("x"):
                    k, _, v = part.partition("=")
                    if k.strip() == "tp":
                        tp = int(v)
                    elif k.strip() == "cp":
                        cp = int(v)
                    else:
                        raise ValueError(part)
        except ValueError:
            ap.error("--mesh must be an int tp degree, 'tp=N', 'cp=M', "
                     "or 'tp=NxCp=M'")
        if tp < 1 or cp < 1:
            ap.error("--mesh axis degrees must be >= 1")
        if tp * cp > 1 and os.environ.get("JAX_PLATFORMS", "") == "cpu" \
                and "xla_force_host_platform_device_count" \
                not in os.environ.get("XLA_FLAGS", ""):
            # CPU dryrun: the mesh needs tp*cp host devices, and the flag
            # only takes effect if set BEFORE jax is imported (which is
            # why the jax imports below sit under main())
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count"
                + f"={tp * cp}").strip()
    if args.pool_frac is not None and not args.paged:
        ap.error("--pool-frac requires --paged")
    if args.host_pool_mb is not None and not args.paged:
        ap.error("--host-pool-mb requires --paged")
    if args.warm_pool_mb is not None and not args.paged:
        ap.error("--warm-pool-mb requires --paged (the warm tier parks "
                 "paged KV blocks)")
    tier_low = tier_high = None
    if args.tier_demote is not None:
        if not args.paged:
            ap.error("--tier-demote requires --paged (only block-pool KV "
                     "demotes)")
        try:
            lo, _, hi = args.tier_demote.partition(":")
            tier_low, tier_high = float(lo), float(hi)
        except ValueError:
            ap.error("--tier-demote must be LOW:HIGH (two floats, e.g. "
                     "0.1:0.3)")
    if args.long_context:
        if not args.paged:
            ap.error("--long-context requires --paged (chunked prefill + "
                     "the block pool are the long-context substrate)")
        if args.long_prompts or args.repeat_suffix:
            ap.error("--long-context replaces the prompt ladder; drop "
                     "--long-prompts/--repeat-suffix")
        if not (0 < args.lc_min <= args.lc_max):
            ap.error("--lc-min/--lc-max must satisfy 0 < min <= max")
    if not (0.0 <= args.shared_prefix <= 1.0):
        ap.error("--shared-prefix must be a fraction in [0, 1]")
    if args.burst < 1:
        ap.error("--burst must be >= 1")
    if args.max_new is None:
        args.max_new = 128 if args.repeat_suffix else 64
    if args.max_len is None:
        if args.long_context:
            args.max_len = args.lc_max + args.max_new
        else:
            args.max_len = 768 if args.long_prompts else 256
            if args.repeat_suffix:
                args.max_len = max(args.max_len, 128 + args.max_new)
    if args.kv_quant != "none" and not args.paged:
        ap.error("--kv-quant requires --paged (the int8 pool is the "
                 "block pool)")
    if args.lora_adapters:
        if not args.paged:
            ap.error("--lora-adapters requires --paged (the adapter pool "
                     "shares the paged slot machinery)")
        if args.int8:
            ap.error("--lora-adapters is incompatible with --int8 weights "
                     "(serve LoRA over fp base weights; --kv-quant int8 "
                     "is fine)")
        if args.lora_rank < 1:
            ap.error("--lora-rank must be >= 1")
    if args.spec:
        if not args.paged:
            ap.error("--spec requires --paged (the verify op is paged)")
        if args.spec_drafter == "model":
            args.tick_window = 1  # host-side drafter: one window per trip
        elif args.tick_window is None:
            args.tick_window = 4
    if args.tick_window is None:
        args.tick_window = 16

    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import GenerationServer
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5632, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=args.max_len,
                          dtype="bfloat16", use_flash_attention=True)
    else:
        # CPU stand-in: hidden 128 keeps the decode tick matmul-bound —
        # at hidden 64 per-op overhead swamps compute and every serving
        # ratio (tick-window, spec verify width) measures dispatch, not
        # the design
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=256, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=args.max_len,
                          dtype="float32", use_flash_attention=False)
    paddle.seed(0)   # model weights are part of the benchmark definition
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # --seed governs TRAFFIC only: same weights, different load trace
    rng = np.random.RandomState(args.seed)
    # warmup draws come from a DISJOINT stream (xor'd seed, own counter):
    # warmup sizing scales with --slots/--pool-frac, and if it shared the
    # measured stream the measured traffic would shift whenever a serving
    # knob changed — the autotuner's cross-config token-fingerprint gate
    # (and any two-line diff at one seed) needs byte-identical traffic
    wrng = np.random.RandomState((args.seed ^ 0x5EED) & 0x7FFFFFFF)
    _warm_state = wrng.get_state()

    motif = rng.randint(1, cfg.vocab_size, 8).tolist()
    lc_lens = None
    if args.long_context:
        # log-spaced rungs, rounded DOWN to block multiples so tier
        # demotion/promotion always moves whole blocks (the last-token
        # rule then leaves exactly the final block uncacheable)
        raw = np.geomspace(args.lc_min, args.lc_max, 5)
        lc_lens = sorted({max(args.block_size,
                              int(v) // args.block_size * args.block_size)
                          for v in raw})
    shared_tokens = None
    if args.shared_prefix > 0.0:
        # one shared prefix per seed, from its OWN stream — enabling the
        # knob must not shift the measured traffic draws
        srng = np.random.RandomState((args.seed + 0x5AFE) & 0x7FFFFFFF)
        shared_tokens = srng.randint(1, cfg.vocab_size, args.max_len)
    _counter = [0]
    _wcounter = [0]
    prios = {}
    # measured-pass submit timeline (prompts, priorities, tenants,
    # adapters + the pre-drawn open-loop schedule) — hashed into the
    # line's traffic_fingerprint so traffic/config decoupling is
    # checkable from the JSON alone
    _trace = []
    _sched_trace = []

    tuned_profile, wspec = None, None
    if args.tune is not None or args.profile is not None:
        from paddle_tpu.autotune import (TrialRunner, TunedProfile,
                                         WorkloadSpec)
        from paddle_tpu.autotune import autotune as run_autotune
        from paddle_tpu.autotune.workload import (LONG_PROMPT_LADDER,
                                                  SHORT_PROMPT_LADDER)

        wspec = WorkloadSpec(
            requests=args.requests, max_new=args.max_new,
            prompt_ladder=(tuple(lc_lens) if args.long_context
                           else LONG_PROMPT_LADDER if args.long_prompts
                           else SHORT_PROMPT_LADDER),
            vocab_size=cfg.vocab_size, repeat_suffix=args.repeat_suffix,
            mixed_priority=args.mixed_priority,
            lora_adapters=args.lora_adapters,
            arrival_rate=args.arrival_rate, burst=args.burst,
            long_context=args.long_context,
            shared_prefix_frac=args.shared_prefix,
            seed=args.seed)
        if args.tune is not None:
            runner = TrialRunner(model, wspec, max_batch=args.slots,
                                 max_len=args.max_len)
            tlog = (None if args.json
                    else (lambda s: print(f"[tune] {s}", file=sys.stderr)))
            tuned_profile, _trials = run_autotune(
                runner, budget=args.tune, seed=args.seed, log=tlog)
            if args.profile:
                tuned_profile.save(args.profile, now=time.time())
        else:
            tuned_profile = TunedProfile.load(args.profile)
        # sync the reporting knobs (unit string, kernel-microbench
        # shapes, config_fingerprint) to what the profile actually pins
        _pc = tuned_profile.config
        args.block_size = int(_pc["block_size"])
        args.tick_window = int(_pc["tick_window"])
        args.prefill_chunk = int(_pc["prefill_chunk"])
        args.kv_quant = str(_pc["kv_quant"])
        args.scheduler = str(_pc["policy"])
        args.spec = int(_pc.get("draft_k", 0))
        args.spec_drafter = "ngram"
        _pf = float(_pc.get("pool_frac", 1.0))
        args.pool_frac = _pf if _pf < 1.0 else None
        args.host_pool_mb = _pc.get("host_pool_mb")
        args.num_blocks = None

    if args.geometry_cache is not None:
        # installed BEFORE any server build so every kernel trace sees
        # it; a profile carrying its own kernel_geometry re-installs
        # with source "profile" inside the GenerationServer ctor
        from paddle_tpu.autotune.kernel_geometry import (GeometryCache,
                                                         install_geometry_cache)

        with open(args.geometry_cache) as f:
            install_geometry_cache(GeometryCache.from_dict(json.load(f)),
                                   source="swept")

    lora_cfg, lora_live = None, 0
    if args.lora_adapters:
        from paddle_tpu.inference.lora import (LORA_TARGETS, AdapterRegistry,
                                               LoRAConfig, target_dims)

        # adapter factors ride the traffic seed: same seed, same tenants'
        # weights — the model stays the fixed benchmark-definition model
        arng = np.random.RandomState(args.seed + 17)
        dims = target_dims(cfg)
        reg = AdapterRegistry()
        for a in range(args.lora_adapters):
            w = {}
            for layer in range(cfg.num_hidden_layers):
                for t in LORA_TARGETS:
                    fi, fo = dims[t]
                    w[(layer, t)] = (
                        arng.normal(0, 0.02, (fi, args.lora_rank))
                        .astype(np.float32),
                        arng.normal(0, 0.02, (args.lora_rank, fo))
                        .astype(np.float32))
            reg.register(f"a{a}", w, rank=args.lora_rank,
                         alpha=2.0 * args.lora_rank)
        lora_live = args.lora_live or min(args.lora_adapters, args.slots)
        lora_cfg = LoRAConfig(reg, max_live_adapters=lora_live,
                              max_rank=args.lora_rank)

    def burst(server, n, warm=False):
        """Mixed prompt lengths across the bucket ladder; round-robin
        priority classes + tenants under --mixed-priority. ``warm``
        bursts draw from the disjoint warmup stream (own counter) so
        config-scaled warmup never perturbs the measured traffic."""
        r = wrng if warm else rng
        ctr = _wcounter if warm else _counter
        if args.long_context:
            lens = r.choice(lc_lens, size=n)
        else:
            lens = r.choice([64, 128, 256, 400, 512] if args.long_prompts
                            else [16, 30, 64, 100, 128], size=n)
        rids = {}
        for ln in lens:
            if args.repeat_suffix:
                # tile one shared motif: greedy decoding locks onto the
                # repetition, which prompt-lookup drafts perfectly — and
                # the shared prefix exercises the prefix cache
                prompt = (motif * (int(ln) // len(motif) + 1))[:int(ln)]
            else:
                prompt = r.randint(1, cfg.vocab_size, int(ln)).tolist()
            if shared_tokens is not None:
                # overlay the seed's shared prefix — the cross-request
                # prefix-cache (and warm-tier re-hit) workload
                k = int(int(ln) * args.shared_prefix)
                prompt[:k] = shared_tokens[:k].tolist()
            i = ctr[0]
            ctr[0] += 1
            prio, tenant, adapter = 1, "default", None
            if args.mixed_priority:
                prio = (0, 1, 2)[i % 3]
                tenant = ("a", "b")[i % 2]
            if args.lora_adapters:
                # one tenant per adapter: the WFQ share → adapter
                # residency coupling is what the workload exercises
                adapter = f"a{i % args.lora_adapters}"
                tenant = f"t{i % args.lora_adapters}"
            rid = server.submit(prompt, max_new_tokens=args.max_new,
                                priority=prio, tenant=tenant,
                                adapter=adapter)
            rids[rid] = int(ln)
            prios[rid] = prio
            if not warm:
                _trace.append([prompt, int(args.max_new), prio, tenant,
                               adapter or ""])
        return rids

    import contextlib

    from paddle_tpu.analysis.recompile_guard import jit_cache_guard
    from paddle_tpu.utils.bench_timing import tpu_lock

    def make_server(faults=None, sched=None, role="any"):
        if tuned_profile is not None:
            # tuned path: the profile pins every engine knob through
            # GenerationServer(profile=...); only workload inputs
            # (model/slots/max_len) and reporting plumbing stay on args
            return GenerationServer(
                model, max_batch=args.slots, max_len=args.max_len,
                profile=tuned_profile, lora=lora_cfg, faults=faults,
                telemetry=bool(args.telemetry_out) or args.strict,
                kernels=args.kernels, role=role, mesh=args.mesh)
        if args.paged:
            spec = None
            if args.spec:
                from paddle_tpu.inference.speculative import SpecConfig

                draft_model = None
                if args.spec_drafter == "model":
                    paddle.seed(1)
                    dcfg = LlamaConfig(
                        vocab_size=cfg.vocab_size,
                        hidden_size=cfg.hidden_size // 2,
                        intermediate_size=cfg.intermediate_size // 2,
                        num_hidden_layers=max(cfg.num_hidden_layers // 4, 1),
                        num_attention_heads=max(
                            cfg.num_attention_heads // 2, 1),
                        num_key_value_heads=max(
                            cfg.num_key_value_heads // 2, 1),
                        max_position_embeddings=args.max_len,
                        dtype=cfg.dtype,
                        use_flash_attention=cfg.use_flash_attention)
                    draft_model = LlamaForCausalLM(dcfg)
                spec = SpecConfig(k=args.spec, drafter=args.spec_drafter,
                                  draft_model=draft_model)
            host_pool = (None if args.host_pool_mb is None
                         else int(args.host_pool_mb * 1e6))
            pool_bytes = None
            num_blocks = args.num_blocks
            if args.kv_quant != "none" and num_blocks is None:
                # equal-HBM comparison: hand the int8 server the byte
                # budget the DEFAULT fp pool would occupy (dense parity:
                # slots*ceil(max_len/bs)+1 blocks) and let it derive its
                # block count — kv_blocks_total then reports the capacity
                # win at constant memory instead of constant blocks
                from paddle_tpu.inference.serving import kv_block_bytes

                bs = args.block_size
                fp_blocks = args.slots * (-(-args.max_len // bs)) + 1
                pool_bytes = fp_blocks * kv_block_bytes(cfg, bs, "none")
            if args.pool_frac is not None:
                # overload mode: pool sized BELOW peak demand, so the
                # scheduler must preempt (swap KV to host) to make room
                if pool_bytes is not None:
                    pool_bytes = max(1, int(pool_bytes * args.pool_frac))
                elif num_blocks is None:
                    parity = args.slots * (-(-args.max_len
                                             // args.block_size)) + 1
                    num_blocks = max(4, int(parity * args.pool_frac))
            return GenerationServer(
                model, max_batch=args.slots, max_len=args.max_len,
                tick_window=args.tick_window, cache="paged",
                block_size=args.block_size, num_blocks=num_blocks,
                prefill_chunk=args.prefill_chunk, spec=spec,
                kv_quant=args.kv_quant, pool_bytes=pool_bytes,
                policy=sched if sched is not None else args.scheduler,
                host_pool_bytes=host_pool,
                warm_pool_bytes=(None if args.warm_pool_mb is None
                                 else int(args.warm_pool_mb * 1e6)),
                tier_demote_low=tier_low, tier_demote_high=tier_high,
                lora=lora_cfg, faults=faults,
                telemetry=bool(args.telemetry_out) or args.strict,
                kernels=args.kernels, role=role, mesh=args.mesh)
        return GenerationServer(model, max_batch=args.slots,
                                max_len=args.max_len,
                                prompt_buckets=((64, 128, 256, 512)
                                                if args.long_prompts
                                                else (32, 64, 128)),
                                tick_window=args.tick_window,
                                policy=args.scheduler,
                                telemetry=bool(args.telemetry_out)
                                or args.strict,
                                kernels=args.kernels)

    def run_pass(server, chaos_inj=None, allowed_compiles=0):
        """Warmup + the measured drain against the seeded traffic.

        The caller resets the traffic rng/counters before each pass, so
        two passes submit identical requests in identical order (and
        thus identical rids) — the chaos comparison relies on it (which
        is also why every warmup decision keys off args, never off
        which pass this is). Returns the drain's backend-compile count
        alongside the results: the chaos pass is held to the reference
        pass's compile budget — injected faults must not add a single
        program beyond what the fault-free drain compiles."""
        from paddle_tpu.analysis.recompile_guard import compile_count

        # warmup drain: compiles the decode tick + the prefill program(s)
        burst(server, min(args.slots, 4), warm=True)
        server.run()
        if (args.pool_frac is not None or tier_low is not None) \
                and (args.chaos or args.guard_recompiles):
            # overload warmup wave: churn so the swap gather/scatter
            # programs — which the tier ladder's demotion gather and
            # promotion scatter share shapes with — get a chance to
            # compile BEFORE the measured window (first preemption
            # after it still counts against the budget — hence the
            # reference-pass allowance)
            burst(server, args.slots * 2 + 2, warm=True)
            server.run()
        # warmup boundary: drop histogram samples, spans, and flight
        # ticks so registry percentiles (and any --telemetry-out dump)
        # cover the measured drain only; counters keep lifetime totals.
        # The reset folds warmup program keys into flight.warm_progs, so
        # the post-drain watchdog neither resurfaces a warmup compile as
        # a steady_state_recompile finding nor blanket-excuses a warm
        # program recompiling inside the first measured ticks
        server.telemetry.reset()
        if args.paged:
            # scope the prefill-throughput and cold-refill figures to
            # the measured drain (warmup churn demotes too)
            server._prefill_tokens = 0
            server._prefill_wall_s = 0.0
            server._cold_refills = 0
        if chaos_inj is not None:
            chaos_inj.enabled = True   # plan ordinals start at the drain

        # pre-draw the whole open-loop arrival timeline from the seeded
        # rng — the trace is fixed before the clock starts, so it cannot
        # react to server speed (open loop) and replays exactly per seed
        schedule = []
        if args.arrival_rate is not None:
            t, left = 0.0, args.requests
            while left > 0:
                n = min(args.burst, left)
                schedule.append((t, n))
                left -= n
                t += float(rng.exponential(args.burst / args.arrival_rate))
        _sched_trace[:] = [[t, n] for t, n in schedule]
        rids = {} if schedule else burst(server, args.requests)
        if chaos_inj is not None:
            guard = jit_cache_guard("chaos measured drain",
                                    allowed=allowed_compiles)
        elif args.guard_recompiles:
            guard = jit_cache_guard("serving_benchmark measured drain")
        else:
            guard = contextlib.nullcontext()
        c0 = compile_count()
        with guard:
            t0 = time.perf_counter()
            done_at = {}
            pending = list(schedule)
            while True:
                now = time.perf_counter() - t0
                while pending and pending[0][0] <= now:
                    rids.update(burst(server, pending.pop(0)[1]))
                remaining = server.step()
                if chaos_inj is not None:
                    # soak invariant: pool conservation after EVERY tick
                    server.assert_conserved()
                now = time.perf_counter() - t0
                for rid in list(server._results):
                    if rid not in done_at:
                        done_at[rid] = now
                if remaining == 0:
                    if not pending:
                        break
                    # open-loop lull: nothing in flight, next clump later
                    time.sleep(max(0.0, min(pending[0][0] - now, 0.01)))
            dt = time.perf_counter() - t0
        return rids, server._results, done_at, dt, compile_count() - c0

    def fleet_pass():
        """--fleet N: the seeded burst through a FleetRouter. Under
        --chaos the reference is an UNDISTURBED single engine over the
        identical traffic (rng state + request counter reset before each
        measured burst, so the trace matches request-for-request) and the
        fleet's failover drain is guarded at the twin's compile budget.
        Returns (json line, watchdog findings or None)."""
        from paddle_tpu.analysis.recompile_guard import compile_count
        from paddle_tpu.inference.fleet import FleetRouter

        traffic_state = rng.get_state()

        def reset_traffic():
            rng.set_state(traffic_state)
            wrng.set_state(_warm_state)
            _counter[0] = 0
            _wcounter[0] = 0
            prios.clear()
            del _trace[:]
            del _sched_trace[:]

        # reference twin: warm, then the measured drain
        ref_server = make_server()
        burst(ref_server, min(args.slots, 4), warm=True)
        ref_server.run()
        reset_traffic()
        ref_rids = burst(ref_server, args.requests)
        c0 = compile_count()
        t0 = time.perf_counter()
        ref_out = ref_server.run()
        ref_dt = time.perf_counter() - t0
        ref_compiles = compile_count() - c0
        ref_order = list(ref_rids)
        del ref_server

        # --disagg: floor(N/2) prefill-class replicas first, the rest
        # decode-class — index order matters, the chaos plan below aims
        # its seeded kill at a prefill index
        n_prefill = args.fleet // 2 if args.disagg else 0
        roles = (["prefill"] * n_prefill
                 + ["decode"] * (args.fleet - n_prefill)
                 if args.disagg else ["any"] * args.fleet)
        inj = None
        if args.chaos:
            from paddle_tpu.inference.faults import FaultInjector, FaultPlan

            plan = (FaultPlan.disagg_chaos(args.seed, replicas=args.fleet,
                                           prefill=n_prefill)
                    if args.disagg
                    else FaultPlan.fleet_chaos(args.seed,
                                               replicas=args.fleet))
            inj = FaultInjector(plan)
            inj.enabled = False    # hooks wire now, plan fires at the drain
        fleet = FleetRouter([make_server(role=r) for r in roles],
                            faults=inj)
        # warm EVERY replica's prefill/decode (routing spreads the warmup
        # burst by load), then replay the identical measured traffic
        burst(fleet, args.fleet * min(args.slots, 4), warm=True)
        if args.disagg:
            # the router only hands decode replicas KV payloads, so their
            # chunk-prefill programs never compile through routed warmup
            # — submit to them directly so the post-kill re-prefill
            # salvage path compiles nothing new inside the guarded drain
            for rep in fleet._replicas:
                if rep.role == "decode":
                    burst(rep.server, min(args.slots, 4), warm=True)
        fleet.run()
        for rep in fleet._replicas:
            rep.server.telemetry.reset()
        reset_traffic()
        if inj is not None:
            inj.enabled = True
        rids = burst(fleet, args.requests)
        done_at = {}
        guard = (jit_cache_guard("fleet measured drain",
                                 allowed=ref_compiles)
                 if (args.chaos or args.guard_recompiles)
                 else contextlib.nullcontext())
        c0 = compile_count()
        with guard:
            t0 = time.perf_counter()
            while True:
                remaining = fleet.step()
                if args.chaos:
                    # soak invariant, fleet-wide: every engine conserves
                    fleet.assert_conserved()
                now = time.perf_counter() - t0
                for rid in list(fleet._results):
                    if rid not in done_at:
                        done_at[rid] = now
                if remaining == 0:
                    break
            dt = time.perf_counter() - t0
        drain_compiles = compile_count() - c0
        out = fleet.run()
        fm = fleet.fleet_metrics()

        gen_tokens = sum(len(v) - rids[r]
                         for r, v in out.items() if r in rids)
        lats = sorted(done_at[r] for r in rids if r in done_at)
        roles_note = (f" ({n_prefill} prefill + "
                      f"{args.fleet - n_prefill} decode)"
                      if args.disagg else "")
        line = {"metric": "serving_fleet_tok_s_1chip",
                "value": round(gen_tokens / dt, 1),
                "unit": f"generated tok/s ({args.requests} reqs, "
                        f"{args.fleet} replicas{roles_note} x "
                        f"{args.slots} slots, max_new={args.max_new}, "
                        f"params={n_params/1e6:.0f}M)",
                "kv_cache": "paged", "fleet": args.fleet,
                "tp": tp, "cp": cp,
                "mesh": f"tp{tp}" if cp == 1 else f"tp{tp}cp{cp}",
                "tok_s_per_chip": round(
                    gen_tokens / dt / (tp * cp * args.fleet), 1),
                "tokens_fingerprint": hashlib.sha256(json.dumps(
                    [out[r] for r in sorted(rids)
                     if r in out]).encode()).hexdigest()[:16],
                "traffic_fingerprint": hashlib.sha256(json.dumps(
                    {"schedule": _sched_trace,
                     "requests": _trace}).encode()).hexdigest()[:16],
                "disagg": bool(args.disagg),
                "prefill_replicas": fm["prefill_replicas"],
                "decode_replicas": fm["decode_replicas"],
                "handoffs": fm["handoffs"],
                "handoff_requests": fm["handoff_requests"],
                "migration_latency_p50_s": round(
                    fm["migration_latency_p50_s"], 6),
                "migration_latency_p95_s": round(
                    fm["migration_latency_p95_s"], 6),
                "migration_latency_samples":
                    fm["migration_latency_samples"],
                "p50_s": round(lats[len(lats) // 2], 3) if lats else 0.0,
                "p95_s": round(lats[min(len(lats) - 1,
                                        int(len(lats) * 0.95))], 3)
                if lats else 0.0,
                "wall_s": round(dt, 2),
                "seed": args.seed, "scheduler": args.scheduler,
                "kv_quant": args.kv_quant,
                "fleet_states": fm["states"],
                "fleet_routed": fm["routed"],
                "fleet_misroutes": fm["misroutes"],
                "fleet_migrations": fm["migrations"],
                "fleet_migrated_requests": fm["migrated_requests"],
                "fleet_migrated_kv": fm["migrated_kv"],
                "fleet_deaths": fm["deaths"],
                "fleet_heartbeat_stalls": fm["heartbeat_stalls"],
                "quarantined": fm["quarantined"],
                "replicas": fm["replicas"],
                # schema v6: per-tenant SLO attainment on EVERY fleet
                # line (the roll-up the canary gate and the autoscaler's
                # burn-rate input both read)
                "slo": {tenant: {
                    "target": row["target"],
                    "ttft": {"attainment": round(
                                 row["ttft"]["attainment"], 6),
                             "burn_rate": round(
                                 row["ttft"]["burn_rate"], 6),
                             "samples": row["ttft"]["samples"]},
                    "tpot": {"attainment": round(
                                 row["tpot"]["attainment"], 6),
                             "burn_rate": round(
                                 row["tpot"]["burn_rate"], 6),
                             "samples": row["tpot"]["samples"]}}
                    for tenant, row in fm["slo"].items()}}
        strict = None
        if args.chaos:
            failed = [r for r in rids if fleet.status(r) == "failed"]
            mismatch = sum(
                1 for a, b in zip(ref_order, list(rids))
                if b not in failed and out.get(b) != ref_out.get(a))
            ref_gen = sum(len(v) - ref_rids[r]
                          for r, v in ref_out.items() if r in ref_rids)
            line["chaos"] = True
            st = inj.stats()
            line["faults_injected"] = st["fired"]
            line["fault_sites"] = st["fired_sites"]
            line["token_mismatches"] = mismatch
            line["ref_tok_s"] = round(ref_gen / ref_dt, 1)
            line["ref_drain_recompiles"] = ref_compiles
            line["drain_recompiles"] = drain_compiles
            if args.strict or args.telemetry_out:
                # recovery tail on the survivors: a fresh burst with the
                # plan spent must come back watchdog-clean
                for rep in fleet._replicas:
                    rep.server.telemetry.reset()
                burst(fleet, min(args.slots, 4), warm=True)
                fleet.run()
                strict = []
                for rep in fleet._replicas:
                    if rep.state in ("live", "degraded"):
                        strict.extend(rep.server.telemetry.watchdog())
                line["watchdog_after_recovery"] = len(strict)
        elif args.strict:
            strict = []
            for rep in fleet._replicas:
                if rep.state in ("live", "degraded"):
                    strict.extend(rep.server.telemetry.watchdog())
            line["watchdog_findings"] = len(strict)
        return line, strict

    # CPU smoke runs don't touch the chip — don't serialize on its lock
    lock = tpu_lock(timeout_s=900.0) if on_tpu else \
        contextlib.nullcontext(True)
    with lock as locked:
        if args.int8:
            model.quantize_int8()
        if args.fleet:
            line, strict_findings = fleet_pass()
            line["schema_version"] = SCHEMA_VERSION
            line["kernels"] = args.kernels
            line["config_fingerprint"] = config_fingerprint(args)
            if not locked:
                line["lock_contended"] = True
            print(json.dumps(line))
            if args.strict and strict_findings:
                for f in strict_findings:
                    print(f"watchdog: {f}", file=sys.stderr)
                sys.exit(1)
            if not args.json:
                print(f"[fleet x{args.fleet}] {line['value']} tok/s, "
                      f"p50 {line['p50_s']}s, p95 {line['p95_s']}s over "
                      f"{line['wall_s']}s, states {line['fleet_states']}"
                      + (f", mismatches {line['token_mismatches']}"
                         if args.chaos else ""),
                      file=sys.stderr)
            return
        traffic_state = rng.get_state()
        inj, ref_out, ref_tok_s, ref_compiles = None, None, None, 0
        if args.chaos:
            from paddle_tpu.inference.faults import FaultInjector, FaultPlan
            from paddle_tpu.inference.scheduler import Scheduler

            ref_server = make_server()
            ref_rids, ref_out, _, ref_dt, ref_compiles = run_pass(ref_server)
            ref_tok_s = sum(len(v) - ref_rids[r]
                            for r, v in ref_out.items() if r in ref_rids) \
                / ref_dt
            del ref_server
            # identical traffic for the measured pass: same rng state,
            # same rid counter -> rid-for-rid comparable outputs
            rng.set_state(traffic_state)
            wrng.set_state(_warm_state)
            _counter[0] = 0
            _wcounter[0] = 0
            prios.clear()
            del _trace[:]
            del _sched_trace[:]
            inj = FaultInjector(FaultPlan.chaos(args.seed))
            inj.enabled = False        # hooks wire now, plan fires later
            sched = Scheduler(policy=args.scheduler,
                              clock=inj.wrap_clock(time.monotonic))
            server = make_server(faults=inj, sched=sched)
        else:
            server = make_server()
        rids, out, done_at, dt, drain_compiles = run_pass(
            server, chaos_inj=inj, allowed_compiles=ref_compiles)
    gen_tokens = sum(len(v) - rids[r] for r, v in out.items() if r in rids)
    lats = sorted(done_at[r] for r in rids if r in done_at)
    p50 = lats[len(lats) // 2]
    p95 = lats[min(len(lats) - 1, int(len(lats) * 0.95))]

    # TTFT (submit -> first generated token, queue wait included) and
    # per-token decode latency — read from the registry histograms the
    # server feeds in _emit_result (telemetry.MetricsRegistry is the one
    # source of truth; the warmup reset above scoped the samples to the
    # measured drain, so no per-rid filtering is needed here)
    reg = server.telemetry.registry

    def hpct(name, q, **where):
        v = reg.percentile(name, q, where=where or None)
        return v if v is not None else 0.0

    line = {"metric": "serving_continuous_batching_tok_s_1chip",
            "value": round(gen_tokens / dt, 1),
            "unit": f"generated tok/s ({args.requests} reqs, {args.slots} "
                    f"slots, max_new={args.max_new}, mixed prompts "
                    f"{'64-512' if args.long_prompts else '16-128'}, "
                    f"tick_window={args.tick_window}, "
                    f"{'int8' if args.int8 else 'bf16'} weights, "
                    f"params={n_params/1e6:.0f}M)",
            "kv_cache": "paged" if args.paged else "dense",
            "tp": tp, "cp": cp,
            "mesh": f"tp{tp}" if cp == 1 else f"tp{tp}cp{cp}",
            "tok_s_per_chip": round(gen_tokens / dt / (tp * cp), 1),
            "tokens_fingerprint": hashlib.sha256(json.dumps(
                [out[r] for r in sorted(rids)
                 if r in out]).encode()).hexdigest()[:16],
            "traffic_fingerprint": hashlib.sha256(json.dumps(
                {"schedule": _sched_trace,
                 "requests": _trace}).encode()).hexdigest()[:16],
            "p50_s": round(p50, 3), "p95_s": round(p95, 3),
            "wall_s": round(dt, 2),
            "seed": args.seed, "scheduler": args.scheduler,
            "ttft_p50_s": round(hpct("serving_ttft_s", 50), 4),
            "ttft_p95_s": round(hpct("serving_ttft_s", 95), 4),
            "tpot_p50_ms": round(hpct("serving_tpot_ms", 50), 3),
            "tpot_p95_ms": round(hpct("serving_tpot_ms", 95), 3)}
    if args.arrival_rate is not None:
        line["arrival_rate"] = args.arrival_rate
        line["burst"] = args.burst
    if args.mixed_priority:
        for cls, name in ((0, "high"), (1, "normal"), (2, "low")):
            line[f"ttft_p95_s_{name}"] = round(
                hpct("serving_ttft_s", 95, priority=str(cls)), 4)
    sm = server.sched_metrics()
    if sm["preemptions"] or sm["prefill_aborts"] or sm["expired"] \
            or args.pool_frac is not None or args.scheduler != "fifo":
        line["preemptions"] = sm["preemptions"]
        line["prefill_aborts"] = sm["prefill_aborts"]
        line["resumes"] = sm["resumes"]
        line["expired"] = sm["expired"]
        if args.paged:
            ks = server.kv_stats()
            line["swap_out_blocks"] = ks["swap_out_blocks"]
            line["swap_in_blocks"] = ks["swap_in_blocks"]
            line["host_bytes_peak"] = ks["host_bytes_peak"]
    if args.paged:
        stats = server.kv_stats()
        line["peak_kv_blocks"] = stats["peak_blocks_in_use"]
        line["kv_blocks_total"] = stats["num_blocks"]
        line["kv_block_size"] = stats["block_size"]
        line["prefix_hit_blocks"] = stats["prefix_hit_blocks"]
        line["prefill_chunk"] = server.prefill_chunk
        line["kv_quant"] = args.kv_quant
        # bytes one cached token costs across all layers (K+V, incl.
        # scale rows amortized over the block) — the bandwidth/capacity
        # figure the int8 pool halves vs bf16 (quarters vs f32)
        line["kv_bytes_per_token"] = round(
            stats["bytes_per_block"] / stats["block_size"], 2)
        line["kv_pool_bytes"] = stats["bytes_per_block"] * stats["num_blocks"]
        # chunked-prefill throughput over the measured drain, normalized
        # per chip (tp x cp) — the figure the cp axis is meant to scale
        line["prefill_tok_s_per_chip"] = round(
            server._prefill_tokens
            / max(server._prefill_wall_s, 1e-9) / (tp * cp), 1)
        # hot/warm rates are block-level fractions of prefix-cache
        # lookups; cold is re-prefill-over-demoted-content events per
        # measured request (the re-prefill IS the cold tier, so a
        # preempted-and-resumed request can legitimately count twice)
        looked = max(stats["prefix_lookup_blocks"], 1)
        line["tier_hit_rate"] = {
            "hot": round(stats["prefix_hit_blocks"] / looked, 4),
            "warm": round(stats["warm_hit_blocks"] / looked, 4),
            "cold": round(stats["cold_refills"] / max(len(rids), 1), 4)}
        line["tier_demotions"] = stats["warm_demoted_blocks"]
        line["tier_promotions"] = stats["warm_promoted_blocks"]
        line["warm_bytes_peak"] = stats["warm_bytes_peak"]
        if args.long_context:
            line["long_context"] = True
            line["lc_lens"] = lc_lens
        if args.shared_prefix:
            line["shared_prefix"] = args.shared_prefix
        line.update(kernel_microbench(server, cfg, args))
    if args.lora_adapters:
        am = server.sched_metrics()
        line["lora_adapters"] = args.lora_adapters
        line["lora_rank"] = args.lora_rank
        line["lora_live"] = lora_live
        line["adapter_pool_bytes"] = am["adapter_pool_bytes"]
        line["adapter_hit_rate"] = round(am["adapter_hit_rate"], 4)
        line["adapter_uploads"] = am["adapter_uploads"]
        line["adapter_evictions"] = am["adapter_evictions"]
        line["tenants"] = am["tenants"]
    if args.spec:
        sm = server.spec_metrics()
        line["spec_k"] = args.spec
        line["spec_drafter"] = args.spec_drafter
        line["acceptance_rate"] = round(sm["acceptance_rate"], 4)
        line["draft_tokens_proposed"] = sm["draft_tokens_proposed"]
        line["draft_tokens_accepted"] = sm["draft_tokens_accepted"]
    kg = getattr(server, "kernel_geometry", None)
    if kg and any(src != "default" for _, src in kg.values()):
        line["kernel_geometry_source"] = {op: src
                                          for op, (_, src) in kg.items()}
        line["kernel_geometry"] = {op: g.asdict()
                                   for op, (g, src) in kg.items()
                                   if src != "default"}
    if tuned_profile is not None:
        line["profile_fingerprint"] = tuned_profile.config_fingerprint
        line["profile_workload_match"] = bool(
            tuned_profile.workload == wspec.to_dict())
        if args.tune is not None:
            line["tuned"] = True
            line["tune_budget"] = args.tune
            line["tune_trials"] = tuned_profile.search["trials"]
            line["tune_baseline_tok_s"] = round(
                float(tuned_profile.baseline["tok_s"]), 1)
    strict_findings = None
    if args.chaos:
        st = inj.stats()
        failed = [r for r in rids if server.status(r) == "failed"]
        mismatch = sum(1 for r in rids
                       if r not in failed and out.get(r) != ref_out.get(r))
        server.assert_conserved()
        line["chaos"] = True
        line["faults_injected"] = st["fired"]
        line["fault_sites"] = st["fired_sites"]
        line["tick_retries"] = server._tick_faults
        line["quarantined"] = len(failed)
        line["token_mismatches"] = mismatch
        line["ref_tok_s"] = round(ref_tok_s, 1)
        # the jit_cache_guard in run_pass already hard-failed if the
        # chaos drain compiled MORE than the fault-free reference; the
        # counts land in the line so the suite gate can record them
        line["ref_drain_recompiles"] = ref_compiles
        line["drain_recompiles"] = drain_compiles
        if server.telemetry.enabled:
            # recovery tail: with the plan spent, a fresh burst must run
            # with a CLEAN watchdog — degradation is a response, not a
            # new steady state
            server.telemetry.reset()
            burst(server, min(args.slots, 4), warm=True)
            server.run()
            strict_findings = server.telemetry.watchdog()
            line["watchdog_after_recovery"] = len(strict_findings)
    elif args.strict:
        strict_findings = server.telemetry.watchdog()
        line["watchdog_findings"] = len(strict_findings)
    if args.telemetry_out:
        base = args.telemetry_out
        d = os.path.dirname(base)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(base + ".metrics.json", "w") as f:
            json.dump(server.telemetry_snapshot(), f, indent=1)
        server.export_chrome_trace(base + ".trace.json")
        with open(base + ".flight.json", "w") as f:
            json.dump({"ticks": server.telemetry.flight.dump(),
                       "warm_progs": sorted(
                           server.telemetry.flight.warm_progs),
                       "watchdog": server.telemetry.watchdog()}, f, indent=1)
        line["telemetry_out"] = base
    line["schema_version"] = SCHEMA_VERSION
    line["kernels"] = args.kernels
    line["config_fingerprint"] = config_fingerprint(args)
    if not locked:
        line["lock_contended"] = True
    print(json.dumps(line))
    if args.strict and strict_findings:
        for f in strict_findings:
            print(f"watchdog: {f}", file=sys.stderr)
        sys.exit(1)
    if not args.json:
        mode = "paged" if args.paged else "dense"
        if args.spec:
            mode += f"+spec{args.spec}:{args.spec_drafter}"
        if args.lora_adapters:
            mode += (f"+lora{args.lora_adapters}r{args.lora_rank}"
                     f"/{lora_live}live")
        extra = (f", peak blocks {line.get('peak_kv_blocks')}/"
                 f"{line.get('kv_blocks_total')}" if args.paged else "")
        if args.spec:
            extra += f", accept {line['acceptance_rate']:.2f}"
        print(f"[{mode}] {line['value']} tok/s, p50 {line['p50_s']}s, "
              f"p95 {line['p95_s']}s over {line['wall_s']}s{extra}",
              file=sys.stderr)


if __name__ == "__main__":
    main()
