"""Decode (generation) throughput benchmark for the flagship Llama model.

Measures single-chip autoregressive tokens/s through
LlamaForCausalLM.generate's compiled scan loop — the serving-side
counterpart of bench.py's training MFU. Decode is HBM-bandwidth-bound
(params re-read per token), so the roofline is
bandwidth / params_bytes tokens/s; the report includes that ceiling.

Usage: python tools/decode_benchmark.py [--new 128] [--batch 8]
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--prompt", type=int, default=128)
    ap.add_argument("--new", type=int, default=128)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 decode (model.quantize_int8())")
    args = ap.parse_args()

    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5632, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=args.prompt + args.new,
                          dtype="bfloat16", use_flash_attention=True)
        hbm_bw = 819e9  # v5e
    else:
        cfg = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                          num_hidden_layers=2, num_attention_heads=4,
                          num_key_value_heads=2,
                          max_position_embeddings=args.prompt + args.new,
                          dtype="float32", use_flash_attention=False)
        hbm_bw = 0
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    if args.int8:
        model.quantize_int8()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(
        rng.randint(0, cfg.vocab_size, (args.batch, args.prompt)).astype("int32"))

    # generate() is one long autoregressive chain; a single timed run is
    # fine but the sync must be a real transfer (block_until_ready does not
    # wait on the tunneled axon platform)
    from paddle_tpu.utils.bench_timing import pull_scalar, tpu_lock

    with tpu_lock(timeout_s=900.0) as locked:
        out = model.generate(ids, max_new_tokens=args.new)  # compile + run
        pull_scalar(out)
        t0 = time.perf_counter()
        out = model.generate(ids, max_new_tokens=args.new, seed=1)
        pull_scalar(out)
        dt = time.perf_counter() - t0

    steps = args.prompt + args.new - 1
    tps = args.batch * steps / dt
    line = {"metric": "llama_decode_tokens_per_sec_1chip",
            "value": round(tps, 1),
            "unit": f"tok/s (B={args.batch}, {steps} steps, "
                    f"params={n_params/1e6:.0f}M)"}
    if hbm_bw:
        bytes_per_param = 1.0 if args.int8 else 2.0  # int8 vs bf16
        ceiling = hbm_bw / (bytes_per_param * n_params) * args.batch
        # per-DECODE-step weight-streaming bound. The throughput above can
        # legitimately exceed it: generate() runs the whole prompt as ONE
        # flash-prefill forward, so prompt tokens are produced without
        # streaming the weights per token (measured bf16 7.4k tok/s vs
        # 6.4k "roofline" at prompt 128 + new 128).
        line["decode_step_roofline_tok_s"] = round(ceiling, 1)
        line["weights"] = "int8" if args.int8 else "bf16"
    if not locked:
        line["lock_contended"] = True
    import json

    print(json.dumps(line))


if __name__ == "__main__":
    main()
