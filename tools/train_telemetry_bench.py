#!/usr/bin/env python
"""Train-telemetry overhead gate: instrumented vs bare step time.

Runs the same tiny-but-real ``ParallelEngine`` trajectory twice — once
with ``telemetry=None`` (the default: no timestamps, no per-step
``block_until_ready``) and once with a live :class:`TrainTelemetry` —
and reports the median post-warmup step-time ratio (median, not mean —
one scheduler hiccup on a shared host would otherwise swing the gate).
Suite stage 8b (``tools/run_tpu_suite.sh``) asserts:

- ``overhead_ratio`` (bare median / instrumented median) >= 0.95, i.e. the
  host-side recording costs at most ~5% of a step even on a model small
  enough that hooks are maximally visible;
- the instrumented run produced a non-empty train timeline (chrome
  trace has ``train_step`` spans on the reserved train row);
- the fault-free watchdog is clean and ``train_goodput_ratio == 1.0``.

Both arms force the loss to host (``float(np.asarray(...))``) so the
bare arm cannot win by leaving work queued on the device — the
comparison is step wall, not dispatch wall.

``--out PREFIX`` writes ``PREFIX.metrics.json`` / ``PREFIX.trace.json``
/ ``PREFIX.flight.json`` — the artifacts ``tools/telemetry_dump.py``
pretty-prints. CPU-runnable: ``JAX_PLATFORMS=cpu python
tools/train_telemetry_bench.py --steps 24 --json``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def run_arms(args, telemetry):
    """Run the bare and instrumented engines INTERLEAVED — step i of one
    arm right after step i of the other — so load drift on a shared
    host hits both arms alike instead of biasing whichever ran second.
    Returns (bare_times, instrumented_times) in seconds."""
    import paddle_tpu as paddle
    from tools.train_chaos import build_factories

    make_engine, make_batch = build_factories(args)
    eng_bare = make_engine(telemetry=None)
    eng_inst = make_engine(telemetry=telemetry)

    def timed_step(eng, i):
        X, y = make_batch(i)
        t0 = time.perf_counter()
        loss = eng.train_batch(paddle.to_tensor(X), paddle.to_tensor(y))
        float(np.asarray(loss.value))  # force to host in BOTH arms
        return time.perf_counter() - t0

    bare, inst = [], []
    for i in range(args.steps):
        bare.append(timed_step(eng_bare, i))
        inst.append(timed_step(eng_inst, i))
    return bare, inst


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=32)
    p.add_argument("--warmup", type=int, default=4,
                   help="leading steps excluded from the medians "
                        "(covers the compile)")
    # defaults sized so one step is ~10ms: small enough to run in
    # seconds anywhere, big enough that the fixed per-step cost of the
    # instrumented arm (span timestamps + the block_until_ready the
    # device_wait span needs) amortizes to ~1-2% instead of dominating
    # a sub-millisecond step the way a toy width would
    p.add_argument("--width", type=int, default=1024)
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--model-seed", type=int, default=5)
    p.add_argument("--data-seed", type=int, default=100)
    p.add_argument("--out", default=None,
                   help="artifact prefix; writes PREFIX.metrics.json, "
                        "PREFIX.trace.json, PREFIX.flight.json")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    if args.steps <= args.warmup + 1:
        p.error("--steps must exceed --warmup + 1")

    from paddle_tpu.telemetry import TRAIN_RID, TrainTelemetry

    tel = TrainTelemetry()
    bare, instrumented = run_arms(args, tel)

    med = lambda xs: float(np.median(xs))
    med_bare = med(bare[args.warmup:])
    med_inst = med(instrumented[args.warmup:])
    # paired per-step ratios: step i of each arm ran back-to-back, so a
    # load spike inflates both and cancels in the quotient; the median
    # of the quotients is far stabler than the quotient of the medians
    overhead_ratio = med([b / t for b, t in
                          zip(bare[args.warmup:], instrumented[args.warmup:])
                          if t > 0])

    train_spans = [s for s in tel.tracer.spans(TRAIN_RID)
                   if s["name"] == "train_step"]
    findings = tel.watchdog()
    result = {
        "bench": "train_telemetry",
        "schema_version": 1,
        "steps": args.steps,
        "warmup": args.warmup,
        "median_step_bare_s": med_bare,
        "median_step_instrumented_s": med_inst,
        "overhead_ratio": overhead_ratio,
        "train_step_spans": len(train_spans),
        "flight_ticks": tel.flight.total,
        "watchdog_findings": len(findings),
        "watchdog": findings,
        "train_goodput_ratio": tel.goodput.ratio(),
    }

    if args.out:
        with open(args.out + ".metrics.json", "w") as f:
            json.dump(tel.snapshot(), f, indent=1)
        tel.export_chrome_trace(args.out + ".trace.json")
        with open(args.out + ".flight.json", "w") as f:
            json.dump({"ticks": tel.flight.dump(),
                       "warm_progs": sorted(tel.flight.warm_progs),
                       "watchdog": findings}, f, indent=1)
        result["artifacts"] = [args.out + ext for ext in
                               (".metrics.json", ".trace.json",
                                ".flight.json")]

    print(json.dumps(result) if args.as_json else
          f"train_telemetry_bench: ratio={overhead_ratio:.3f} "
          f"(bare={med_bare * 1e3:.3f}ms inst={med_inst * 1e3:.3f}ms) "
          f"spans={len(train_spans)} findings={len(findings)} "
          f"goodput={result['train_goodput_ratio']}")
    # the hard gate lives in run_tpu_suite.sh stage 8b; here only sanity
    ok = (len(train_spans) == args.steps
          and result["train_goodput_ratio"] == 1.0)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
