"""Op-level benchmark harness (ref tools/ci_op_benchmark.sh + the op
benchmark CI it drives — relative perf gates on core ops).

Measures wall latency of a representative op set through the public API on
the current backend and writes JSON: {op: {"ms": ..., "shape": ...}}.
Pair with check_op_benchmark_result.py to gate regressions between runs:

    python tools/op_benchmark.py -o base.json        # on the base commit
    python tools/op_benchmark.py -o head.json        # on the candidate
    python tools/check_op_benchmark_result.py base.json head.json --tol 1.15
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _bench(fn, *args, warmup=2, iters=10):
    from paddle_tpu.utils.bench_timing import device_time_ms

    return device_time_ms(lambda: fn(*args), reps=iters, warmup=warmup)


def build_suite():
    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    rng = np.random.RandomState(0)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    dt = "bfloat16" if on_tpu else "float32"
    n = 2048 if on_tpu else 256

    a = paddle.to_tensor(rng.randn(n, n).astype("float32")).astype(dt)
    b = paddle.to_tensor(rng.randn(n, n).astype("float32")).astype(dt)
    img = paddle.to_tensor(rng.randn(8, 64, 56, 56).astype("float32"))
    conv = paddle.nn.Conv2D(64, 128, 3, padding=1)
    x3 = paddle.to_tensor(rng.randn(32, n).astype("float32"))
    ln = paddle.nn.LayerNorm(n)
    emb_ids = paddle.to_tensor(rng.randint(0, 32000, (8, 512)).astype("int32"))
    emb = paddle.nn.Embedding(32000, 512)

    suite = {
        "matmul": (lambda: paddle.matmul(a, b), f"({n},{n})x({n},{n}) {dt}"),
        "conv2d_3x3": (lambda: conv(img), "(8,64,56,56)->128ch"),
        "softmax": (lambda: F.softmax(x3, axis=-1), f"(32,{n})"),
        "layer_norm": (lambda: ln(x3), f"(32,{n})"),
        "embedding": (lambda: emb(emb_ids), "(8,512) of 32000x512"),
        "reduce_sum": (lambda: paddle.sum(a, axis=-1), f"({n},{n})"),
    }
    if on_tpu:
        from paddle_tpu.ops.flash_attention import flash_attention

        q = jnp.asarray(rng.randn(4, 16, 2048, 128), dtype=jnp.bfloat16)
        k = jnp.asarray(rng.randn(4, 8, 2048, 128), dtype=jnp.bfloat16)
        v = jnp.asarray(rng.randn(4, 8, 2048, 128), dtype=jnp.bfloat16)
        fa = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))
        suite["flash_attention_causal_gqa"] = (
            lambda: fa(q, k, v), "B4 H16/8 S2048 D128 bf16")
    return suite


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default=None)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    from paddle_tpu.utils.bench_timing import UnstableMeasurement

    results = {}
    for name, (fn, shape) in build_suite().items():
        try:
            ms = _bench(fn, iters=args.iters)
        except UnstableMeasurement as e:  # below the timing noise floor
            print(f"{name:28s}   UNSTABLE   {shape}  ({e})")
            continue
        results[name] = {"ms": round(ms, 4), "shape": shape}
        print(f"{name:28s} {ms:9.3f} ms   {shape}")
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=2)
        print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
