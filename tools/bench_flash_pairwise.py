"""Round-robin paired comparison of flash block configs — drift-robust.

The tunneled chip's effective throughput drifts over minutes (the same
config measured 4.1 ms and 7.0 ms half an hour apart), so one-shot A/Bs
mis-rank configs.  This driver interleaves the candidate configs
round-robin (A B C A B C ...) so slow drift hits every config equally,
then ranks by per-config MEDIAN across rounds.  Each run is a subprocess
(block sizes bake into the compiled kernel) under the cross-process
tpu_lock.

Usage:
    python tools/bench_flash_pairwise.py --shape 8,2048,16,8,128 \
        --configs 512x512:512x512,512x1024:512x512 [--rounds 3] [--fwd-only]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_CHILD = r"""
import json, sys
import numpy as np
sys.path.insert(0, %(repo)r)
import jax, jax.numpy as jnp
from paddle_tpu.utils.bench_timing import device_time_ms
from paddle_tpu.ops.flash_attention import flash_attention

B, S, H, KV, D = %(shape)s
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, H, S, D).astype("float32")).astype(jnp.bfloat16)
k = jnp.asarray(rng.randn(B, KV, S, D).astype("float32")).astype(jnp.bfloat16)
v = jnp.asarray(rng.randn(B, KV, S, D).astype("float32")).astype(jnp.bfloat16)
if %(fwd_only)s:
    fn = jax.jit(lambda a, b, c: flash_attention(a, b, c, True))
    reps = 60 if S <= 4096 else 16
else:
    fn = jax.jit(jax.grad(lambda a, b, c: jnp.sum(
        flash_attention(a, b, c, True).astype(jnp.float32)), argnums=(0, 1, 2)))
    reps = 20 if S <= 4096 else 8
ms = device_time_ms(lambda: fn(q, k, v), reps=reps, repeats=5)
print(json.dumps({"ms": ms}))
"""


if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def run_once(shape, fwd_blocks, bwd_blocks, fwd_only):
    from paddle_tpu.utils.bench_timing import tpu_lock

    env = dict(os.environ)
    env.pop("PT_FLASH_BLOCK_Q", None)
    env.pop("PT_FLASH_BLOCK_K", None)
    env["PT_FLASH_BLOCKS"] = f"{shape[1]}:{fwd_blocks}"
    env["PT_FLASH_BLOCKS_BWD"] = f"{shape[1]}:{bwd_blocks}"
    code = _CHILD % {"repo": _REPO, "shape": tuple(shape),
                     "fwd_only": fwd_only}
    try:
        # bounded wait: a wedged previous lock holder must not hang the
        # sweep forever — but a contended (unlocked) sample must not pick
        # block-table winners either, so it is dropped, visibly
        with tpu_lock(timeout_s=900.0) as locked:
            if not locked:
                print("  [pairwise] chip lock contended; sample dropped")
                return None
            out = subprocess.run([sys.executable, "-c", code], env=env,
                                 capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            return None
        return json.loads(out.stdout.strip().splitlines()[-1])["ms"]
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shape", default="8,2048,16,8,128",
                    help="B,S,H,KV,D")
    ap.add_argument("--configs", required=True,
                    help="comma list of FWDBQxFWDBK:BWDBQxBWDBK entries")
    ap.add_argument("--rounds", type=int, default=3)
    ap.add_argument("--fwd-only", action="store_true")
    args = ap.parse_args()

    shape = tuple(int(x) for x in args.shape.split(","))
    configs = []
    for ent in args.configs.split(","):
        fwd_b, _, bwd_b = ent.partition(":")
        configs.append((fwd_b, bwd_b or fwd_b))

    samples = {c: [] for c in configs}
    for rnd in range(args.rounds):
        for c in configs:
            ms = run_once(shape, c[0], c[1], args.fwd_only)
            tag = f"fwd={c[0]} bwd={c[1]}"
            if ms is None:
                print(f"  round {rnd}: {tag}: FAILED")
                continue
            samples[c].append(ms)
            print(f"  round {rnd}: {tag}: {ms:7.3f} ms", flush=True)

    print("\n== medians ==")
    ranked = sorted((statistics.median(v), c) for c, v in samples.items() if v)
    for med, c in ranked:  # ascending; winner first
        spread = (max(samples[c]) - min(samples[c])) / med * 100
        print(f"  fwd={c[0]:9s} bwd={c[1]:9s}: median {med:7.3f} ms "
              f"(spread {spread:4.0f}%, n={len(samples[c])})")
    if ranked:
        med, c = ranked[0]
        print(f"WINNER: fwd={c[0]} bwd={c[1]} at {med:.3f} ms")


if __name__ == "__main__":
    main()
