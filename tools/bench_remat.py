"""Remat-policy sweep on the large-model (886M) single-chip config.

VERDICT r3 item 4: the 886M config (largest honest AdamW fit) measured
0.573–0.598 MFU with the ``dots`` policy vs 0.675 at 509M; this driver
A/Bs the checkpoint policies (engine remat_policy values, anchored on the
checkpoint_name annotations in models/llama.py) under the drift-robust
round-robin discipline of bench_flash_pairwise: policies interleave so
slow chip drift hits each equally; ranking by per-policy median.

Usage: python tools/bench_remat.py [--policies dots,save_attn,...]
       [--rounds 2]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

_CHILD = r"""
import json, sys
import numpy as np
sys.path.insert(0, %(repo)r)
import jax
# fail loudly if the tunnel dropped: a CPU sample must never enter the
# per-policy medians (same contract as bench.py's _PADDLE_TPU_BENCH_REQUIRE_TPU)
assert any(d.platform in ("tpu", "axon") for d in jax.devices()), \
    "TPU required, backend is " + jax.devices()[0].platform
from bench import _measure
from paddle_tpu.models import LlamaConfig

cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, intermediate_size=5632,
                  num_hidden_layers=16, num_attention_heads=16,
                  num_key_value_heads=8, max_position_embeddings=2048,
                  dtype="bfloat16", use_flash_attention=True)
mfu, tps, n, loss = _measure(cfg, 2, 2048, 5, 2, remat=%(remat)s)
print(json.dumps({"mfu": mfu, "tok_s": tps, "loss": loss}))
"""


def run_once(policy):
    from paddle_tpu.utils.bench_timing import tpu_lock

    env = dict(os.environ)
    remat = policy != "none"
    env["BENCH_REMAT_POLICY"] = policy if remat else "dots"
    code = _CHILD % {"repo": _REPO, "remat": remat}
    try:
        with tpu_lock(timeout_s=900.0) as locked:
            if not locked:
                print("  [remat] chip lock contended; sample dropped")
                return None
            out = subprocess.run([sys.executable, "-c", code], env=env,
                                 capture_output=True, text=True, timeout=900)
        if out.returncode != 0:
            sys.stderr.write((out.stderr or "")[-400:] + "\n")
            return None
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return None


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--policies",
                    default="dots,save_attn,save_attn_mlp,save_qkv_attn,none")
    ap.add_argument("--rounds", type=int, default=2)
    args = ap.parse_args()
    policies = args.policies.split(",")
    results = {p: [] for p in policies}
    for r in range(args.rounds):
        for p in policies:
            res = run_once(p)
            if res is None:
                print(f"  round {r}: {p:14s}: FAILED/OOM")
                continue
            results[p].append(res)
            print(f"  round {r}: {p:14s}: MFU {res['mfu']:.4f} "
                  f"({res['tok_s']:.0f} tok/s, loss {res['loss']:.3f})")
    print("\n== medians (886M, B=2 S=2048) ==")
    ranked = []
    for p, rs in results.items():
        if not rs:
            print(f"  {p:14s}: no data")
            continue
        med = statistics.median(x["mfu"] for x in rs)
        ranked.append((med, p))
        print(f"  {p:14s}: median MFU {med:.4f} (n={len(rs)})")
    if ranked:
        ranked.sort(reverse=True)
        print(f"WINNER: {ranked[0][1]} at MFU {ranked[0][0]:.4f}")


if __name__ == "__main__":
    main()
