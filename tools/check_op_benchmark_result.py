"""Compare two op_benchmark.py JSON outputs and fail on regressions (ref
tools/check_op_benchmark_result.py — the CI gate comparing op perf vs the
develop branch)."""
from __future__ import annotations

import argparse
import json
import sys


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("base")
    ap.add_argument("head")
    ap.add_argument("--tol", type=float, default=1.15,
                    help="fail if head latency > tol * base latency")
    args = ap.parse_args()

    with open(args.base) as f:
        base = json.load(f)
    with open(args.head) as f:
        head = json.load(f)

    failed = []
    for op, rec in sorted(head.items()):
        if op not in base:
            print(f"NEW      {op:28s} {rec['ms']:9.3f} ms")
            continue
        b, h = base[op]["ms"], rec["ms"]
        ratio = h / b if b else float("inf")
        status = "OK" if ratio <= args.tol else "REGRESSED"
        print(f"{status:8s} {op:28s} base {b:9.3f} ms  head {h:9.3f} ms  "
              f"x{ratio:.2f}")
        if ratio > args.tol:
            failed.append(op)
    for op in sorted(set(base) - set(head)):
        print(f"MISSING  {op:28s} (present in base, absent in head)")
        failed.append(op)

    if failed:
        print(f"\nFAILED: {len(failed)} op(s) regressed or missing: {failed}")
        sys.exit(1)
    print("\nall ops within tolerance")


if __name__ == "__main__":
    main()
