#!/usr/bin/env python
"""Cost-model-driven serving autotuner CLI (paddle_tpu.autotune).

Searches the engine-tier serving config space (block geometry, tick
window, speculation, KV quant, pool sizing, scheduler policy) against a
seeded workload, with the analytic paged-tick cost model pruning the
candidate pool between measured rungs. The search is deterministic per
``--seed``: same seed + same workload -> same trial sequence and a
byte-identical winning profile (minus the timestamp).

Outputs:

- ``--out PATH``      the winning TunedProfile JSON — feed it back with
                      ``GenerationServer(profile=PATH)`` or
                      ``serving_benchmark --profile PATH``
- ``--trials-out DIR``  one ``trial_NN.json`` per measured trial
                      (``"kind": "autotune_trial"``) —
                      ``tools/telemetry_dump.py`` tabulates N of them
- ``--json``          one machine-readable summary line on stdout

``--pin knob=value`` (repeatable) freezes a knob, shrinking the space:
``--pin draft_k=0`` tunes everything but speculation, ``--pin
kv_quant='"int8"'`` forces the int8 pool. Values parse as JSON first,
bare strings otherwise.

``--fake-clock`` swaps the wall clock for a deterministic counting
clock: every measurement (hence the whole search) becomes bit-exact —
CI determinism checks run this twice and byte-compare the profiles.

Usage: python -m tools.autotune --budget 8 --seed 0 --out tuned.json
       [--requests 16 --max-new 32 --slots 8] [--repeat-suffix]
       [--long-prompts] [--mixed-priority] [--arrival-rate R --burst B]
       [--pin knob=value ...] [--trials-out DIR] [--fake-clock] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _parse_pin(s: str):
    if "=" not in s:
        raise argparse.ArgumentTypeError(
            f"--pin wants knob=value, got {s!r}")
    name, raw = s.split("=", 1)
    try:
        val = json.loads(raw)
    except ValueError:
        val = raw            # bare string, e.g. --pin kv_quant=int8
    return name.strip(), val


class _CountingClock:
    """Deterministic stand-in for time.perf_counter: each call advances
    a fixed quantum, so measured durations count events, not seconds."""

    def __init__(self, quantum: float = 1e-4):
        self.t = 0.0
        self.quantum = quantum

    def __call__(self) -> float:
        self.t += self.quantum
        return self.t


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[1],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--budget", type=int, default=8,
                    help="measured candidate trials (the default-config "
                         "reference trial is extra)")
    ap.add_argument("--seed", type=int, default=0,
                    help="seeds the candidate stream AND the workload "
                         "traffic")
    ap.add_argument("--out", metavar="PATH", default=None,
                    help="write the winning TunedProfile JSON here")
    ap.add_argument("--trials-out", metavar="DIR", default=None,
                    help="write every trial record as DIR/trial_NN.json")
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--max-new", type=int, default=32)
    ap.add_argument("--slots", type=int, default=8,
                    help="GenerationServer max_batch for every trial")
    ap.add_argument("--max-len", type=int, default=None,
                    help="serving horizon (default: fits the workload)")
    ap.add_argument("--long-prompts", action="store_true",
                    help="prompt ladder 64-512 instead of 16-128")
    ap.add_argument("--repeat-suffix", action="store_true",
                    help="motif-tiled prompts (the speculative showcase)")
    ap.add_argument("--mixed-priority", action="store_true",
                    help="round-robin priority classes + tenants")
    ap.add_argument("--arrival-rate", type=float, default=None,
                    metavar="R", help="open-loop arrivals at R req/s")
    ap.add_argument("--burst", type=int, default=4,
                    help="requests per arrival clump in open-loop mode")
    ap.add_argument("--pin", action="append", type=_parse_pin, default=[],
                    metavar="KNOB=VALUE",
                    help="freeze a knob (repeatable); values parse as "
                         "JSON first, bare strings otherwise")
    ap.add_argument("--fake-clock", action="store_true",
                    help="deterministic counting clock instead of the "
                         "wall clock (CI determinism checks)")
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable summary line on stdout")
    args = ap.parse_args(argv)
    if args.budget < 1:
        ap.error("--budget must be >= 1")

    import jax
    import numpy as np   # noqa: F401  (benchmark parity: seeded weights)

    import paddle_tpu as paddle
    from paddle_tpu.autotune import TrialRunner, autotune, engine_space
    from paddle_tpu.autotune.workload import (LONG_PROMPT_LADDER,
                                              SHORT_PROMPT_LADDER,
                                              WorkloadSpec)
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    ladder = LONG_PROMPT_LADDER if args.long_prompts else SHORT_PROMPT_LADDER
    need = max(ladder) + args.max_new + 1
    max_len = args.max_len if args.max_len is not None else need

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5632, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=max_len,
                          dtype="bfloat16", use_flash_attention=True)
    else:
        # the serving_benchmark CPU stand-in: hidden 128 keeps the tick
        # matmul-bound so serving ratios measure the design, not dispatch
        cfg = LlamaConfig(vocab_size=256, hidden_size=128,
                          intermediate_size=256, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=max_len,
                          dtype="float32", use_flash_attention=False)
    paddle.seed(0)   # fixed weights: --seed varies traffic, not the model
    model = LlamaForCausalLM(cfg)

    workload = WorkloadSpec(
        requests=args.requests, max_new=args.max_new,
        prompt_ladder=ladder, vocab_size=cfg.vocab_size,
        repeat_suffix=args.repeat_suffix,
        mixed_priority=args.mixed_priority,
        arrival_rate=args.arrival_rate, burst=args.burst, seed=args.seed)
    clock = _CountingClock() if args.fake_clock else None
    runner = TrialRunner(model, workload, max_batch=args.slots,
                         max_len=max_len, clock=clock)
    space = engine_space(max_len=max_len, pins=dict(args.pin))
    log = None if args.json else (
        lambda s: print(f"[autotune] {s}", file=sys.stderr))
    profile, trials = autotune(runner, budget=args.budget,
                               seed=args.seed, space=space, log=log)

    if args.out:
        d = os.path.dirname(args.out)
        if d:
            os.makedirs(d, exist_ok=True)
        # the timestamp is the one non-deterministic field; --fake-clock
        # runs leave it unset so byte-comparisons stay meaningful
        profile.save(args.out,
                     now=None if args.fake_clock else time.time())
    if args.trials_out:
        os.makedirs(args.trials_out, exist_ok=True)
        for t in trials:
            p = os.path.join(args.trials_out, f"trial_{t.index:02d}.json")
            with open(p, "w") as f:
                json.dump(t.to_dict(), f, sort_keys=True, indent=1)
                f.write("\n")

    line = {
        "metric": "autotune_winner_tok_s",
        "value": round(float(profile.metrics["tok_s"]), 1),
        "unit": f"generated tok/s ({args.requests} reqs, {args.slots} "
                f"slots, max_new={args.max_new}, budget={args.budget})",
        "baseline_tok_s": round(float(profile.baseline["tok_s"]), 1),
        "config_fingerprint": profile.config_fingerprint,
        "config": profile.config,
        "workload_signature": profile.workload_signature,
        "trials": profile.search["trials"],
        "rejected": len(profile.search["rejected"]),
        "plan": profile.search["plan"],
        "seed": args.seed,
        "budget": args.budget,
        "fake_clock": bool(args.fake_clock),
        "out": args.out,
    }
    print(json.dumps(line))
    if not args.json:
        print(f"[autotune] winner {profile.config_fingerprint} "
              f"{line['value']} tok/s (default {line['baseline_tok_s']}), "
              f"{line['trials']} trials, {line['rejected']} rejected"
              + (f", profile -> {args.out}" if args.out else ""),
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
