#!/bin/bash
# One-command on-hardware sequence — run from the repo root on a host that
# can reach a TPU chip.  Each stage is independent; results land in
# BASELINE.md-ready form on stdout and under /tmp/tpu_runs.
#
# All measurement children serialize on the cross-process tpu_lock
# (paddle_tpu/utils/bench_timing.py) — do NOT run two stages, or two copies
# of this script, in parallel: a second workload on the shared chip
# corrupts both sets of numbers even with the lock (the lock bounds its
# wait and proceeds).
set -u
mkdir -p /tmp/tpu_runs
cd "$(dirname "$0")/.."

echo "== 0. graftlint (tracing-safety gate; fails on NEW findings only) =="
if ! python tools/graftlint.py paddle_tpu > /tmp/tpu_runs/graftlint.log 2>&1; then
  tail -10 /tmp/tpu_runs/graftlint.log
  echo "graftlint found new tracer-unsafe code — fix or baseline before burning chip time"
  exit 1
fi
tail -2 /tmp/tpu_runs/graftlint.log

echo "== 1. probe =="
timeout 120 python -c "import jax; ds=jax.devices(); print('DEVOK', ds[0].platform, len(ds))" \
  || { echo "TPU unreachable — aborting"; exit 1; }

echo "== 2. compiled-Mosaic kernel tier (tests_tpu/) =="
python -m pytest tests_tpu/ -q 2>&1 | tee /tmp/tpu_runs/tests_tpu.log | tail -3

echo "== 3. flash block-size sweeps (fwd winners -> _BLOCK_REGIMES_FWD /"
echo "      PT_FLASH_BLOCKS; bwd winners -> _BLOCK_REGIMES_BWD /"
echo "      PT_FLASH_BLOCKS_BWD — the env vars are direction-specific) =="
python tools/bench_flash_sweep.py --shapes small 2>&1 | tee /tmp/tpu_runs/sweep_small.log | tail -12
python tools/bench_flash_sweep.py --shapes small --bwd 2>&1 | tee /tmp/tpu_runs/sweep_small_bwd.log | tail -12
python tools/bench_flash_sweep.py --shapes mid 2>&1 | tee /tmp/tpu_runs/sweep_mid.log | tail -12
python tools/bench_flash_sweep.py --shapes mid --bwd 2>&1 | tee /tmp/tpu_runs/sweep_mid_bwd.log | tail -12
python tools/bench_flash_sweep.py --shapes long 2>&1 | tee /tmp/tpu_runs/sweep_long.log | tail -12
python tools/bench_flash_sweep.py --shapes long --bwd 2>&1 | tee /tmp/tpu_runs/sweep_long_bwd.log | tail -12

echo "== 3b. drift-robust ranking of close sweep winners (the chip's"
echo "       throughput drifts ~40% between quiet windows; trust medians) =="
python tools/bench_flash_pairwise.py \
  --configs "512x512:512x512,512x1024:512x512,512x1024:512x1024" --rounds 3 \
  2>&1 | tee /tmp/tpu_runs/pairwise.log | tail -8

echo "== 4. headline bench (509M MFU + 0.9B and S=8192 extras) =="
python bench.py 2>/tmp/tpu_runs/bench_err.log | tee /tmp/tpu_runs/bench.json

echo "== 5. explicit long-context rows =="
BENCH_SKIP_LARGE=1 BENCH_B=2 BENCH_S=8192 python bench.py 2>/dev/null | tee /tmp/tpu_runs/bench_s8192.json
BENCH_SKIP_LARGE=1 BENCH_B=1 BENCH_S=16384 python bench.py 2>/dev/null | tee /tmp/tpu_runs/bench_s16384.json

echo "== 6. decode + conv-path model benchmarks =="
python tools/decode_benchmark.py 2>/dev/null | tee /tmp/tpu_runs/decode_bf16.json
python tools/decode_benchmark.py --int8 2>/dev/null | tee /tmp/tpu_runs/decode_int8.json
python tools/model_benchmark.py -o /tmp/tpu_runs/model_bench.json 2>/dev/null | tail -3

echo "== 7. serving under load (continuous batching; paged + speculative) =="
python tools/serving_benchmark.py --json 2>/dev/null | tee /tmp/tpu_runs/serving_dense.json
python tools/serving_benchmark.py --paged --json 2>/dev/null | tee /tmp/tpu_runs/serving_paged.json
python tools/serving_benchmark.py --paged --repeat-suffix --json 2>/dev/null | tee /tmp/tpu_runs/serving_paged_rs.json
python tools/serving_benchmark.py --paged --spec 4 --repeat-suffix --json 2>/dev/null | tee /tmp/tpu_runs/serving_spec.json
python tools/serving_benchmark.py --paged --kv-quant int8 --guard-recompiles --json 2>/dev/null | tee /tmp/tpu_runs/serving_paged_int8.json \
  || { echo "int8 KV serving pass FAILED (recompile guard or crash)"; exit 1; }
python - <<'PY'
# int8 KV gate: equal byte budget must hold >=1.8x the blocks of the fp
# pool (the bandwidth/capacity claim), and tok/s must not regress >20%
# (drift margin; the two runs share a chip minutes apart)
import json
q = json.load(open("/tmp/tpu_runs/serving_paged_int8.json"))
fp = json.load(open("/tmp/tpu_runs/serving_paged.json"))
blocks_ratio = q["kv_blocks_total"] / fp["kv_blocks_total"]
tok_ratio = q["value"] / fp["value"]
print(f"int8/fp blocks at equal budget: {blocks_ratio:.2f}x, "
      f"tok/s ratio: {tok_ratio:.2f} "
      f"(kv_bytes_per_token {q['kv_bytes_per_token']} vs "
      f"{fp['kv_bytes_per_token']})")
assert blocks_ratio >= 1.8, "int8 pool capacity win below 1.8x"
if tok_ratio < 0.8:
    raise SystemExit("int8 KV serving slower than fp paged beyond drift "
                     "margin — check the fused-dequant programs")
PY
python - <<'PY'
# spec smoke gate: the speculative line must carry a sane acceptance_rate
# and beat the paged repeat-suffix baseline (same workload, same chip)
import json
spec = json.load(open("/tmp/tpu_runs/serving_spec.json"))
base = json.load(open("/tmp/tpu_runs/serving_paged_rs.json"))
assert 0.0 <= spec["acceptance_rate"] <= 1.0, spec
ratio = spec["value"] / base["value"]
print(f"spec/paged repeat-suffix ratio: {ratio:.2f} "
      f"(accept {spec['acceptance_rate']:.2f})")
if ratio < 1.0:
    raise SystemExit("speculative decoding SLOWER than paged baseline — "
                     "check the gate (SpecConfig.gate_low) before shipping")
PY

echo "== 7b. overload smoke (scheduler + swap-preemption under pressure) =="
python tools/serving_benchmark.py --paged --pool-frac 0.35 --scheduler priority \
  --mixed-priority --arrival-rate 400 --burst 4 --seed 3 --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_overload.json \
  || { echo "overload serving pass FAILED (deadlock or crash)"; exit 1; }
python - <<'PY'
# overload gate: the starved pool must actually exercise swap-preemption,
# and priority scheduling must keep high-priority TTFT below low-priority
# (the whole point of the scheduler) with an absolute ceiling as a
# deadlock/livelock tripwire
import json
r = json.load(open("/tmp/tpu_runs/serving_overload.json"))
print(f"preemptions {r['preemptions']} aborts {r['prefill_aborts']} "
      f"swap {r['swap_out_blocks']}/{r['swap_in_blocks']} blocks, "
      f"ttft_p95 high {r['ttft_p95_s_high']:.3f}s low {r['ttft_p95_s_low']:.3f}s")
assert r["swap_out_blocks"] > 0, "pool never pressured — no swap exercised"
assert r["ttft_p95_s_high"] <= r["ttft_p95_s_low"], \
    "priority inversion: high-priority TTFT above low-priority"
if r["ttft_p95_s_high"] > 30.0:
    raise SystemExit("high-priority p95 TTFT unbounded under overload — "
                     "scheduler wedged or preemption not firing")
PY

echo "== 7c. multi-tenant LoRA smoke (adapter churn + per-tenant fairness) =="
python tools/serving_benchmark.py --paged --lora-adapters 8 --lora-rank 8 \
  --lora-live 4 --scheduler wfq --mixed-priority --guard-recompiles --json \
  2>/dev/null | tee /tmp/tpu_runs/serving_lora.json \
  || { echo "LoRA serving pass FAILED (recompile guard or crash)"; exit 1; }
python - <<'PY'
# LoRA gate: 8 adapters over a 4-page pool must churn (uploads beyond the
# first fill, evictions firing) WITHOUT recompiles (guard above), every
# tenant must complete work, and the multi-adapter path must hold >=80%
# of no-adapter paged throughput (BGMV delta cost bound)
import json
r = json.load(open("/tmp/tpu_runs/serving_lora.json"))
base = json.load(open("/tmp/tpu_runs/serving_paged.json"))
ratio = r["value"] / base["value"]
print(f"lora/paged tok/s ratio: {ratio:.2f} "
      f"(uploads {r['adapter_uploads']}, evictions "
      f"{r['adapter_evictions']}, hit-rate {r['adapter_hit_rate']:.2f}, "
      f"pool {r['adapter_pool_bytes']} B)")
assert r["lora_adapters"] == 8 and r["lora_live"] == 4, r
assert r["adapter_uploads"] >= 8, "every adapter should upload at least once"
assert r["adapter_evictions"] > 0, "8 adapters over 4 pages never evicted"
assert r["adapter_pool_bytes"] > 0, r
assert len(r["tenants"]) == 8 and all(
    t["completed"] > 0 for t in r["tenants"].values()), r["tenants"]
if ratio < 0.8:
    raise SystemExit("multi-adapter serving below 80% of paged baseline — "
                     "BGMV delta or adapter gather regressed")
PY

echo "== 7d. telemetry smoke (span trace + flight recorder under bursty LoRA+spec) =="
python tools/serving_benchmark.py --paged --spec 4 --repeat-suffix \
  --kv-quant int8 --lora-adapters 4 --lora-rank 4 --lora-live 2 \
  --scheduler wfq --arrival-rate 400 --burst 4 --seed 5 \
  --telemetry-out /tmp/tpu_runs/telemetry --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_telemetry.json \
  || { echo "telemetry serving pass FAILED (crash)"; exit 1; }
python tools/serving_benchmark.py --paged --spec 4 --repeat-suffix \
  --kv-quant int8 --lora-adapters 4 --lora-rank 4 --lora-live 2 \
  --scheduler wfq --arrival-rate 400 --burst 4 --seed 5 --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_telemetry_off.json
python - <<'PY'
# telemetry gate: the chrome trace must parse non-empty, the flight
# watchdog must report ZERO steady-state recompiles on the full stack
# (spec + int8 KV + LoRA + WFQ under bursty arrivals), and telemetry-on
# tok/s must hold >=95% of the telemetry-off run — the overhead contract
# (host-side spans/ring only, nothing inside compiled programs)
import json
on = json.load(open("/tmp/tpu_runs/serving_telemetry.json"))
off = json.load(open("/tmp/tpu_runs/serving_telemetry_off.json"))
trace = json.load(open("/tmp/tpu_runs/telemetry.trace.json"))
flight = json.load(open("/tmp/tpu_runs/telemetry.flight.json"))
assert trace["traceEvents"], "chrome trace empty — spans never recorded"
bad = [f for f in flight["watchdog"]
       if f["kind"] == "steady_state_recompile"]
assert not bad, f"steady-state recompiles under telemetry: {bad}"
ratio = on["value"] / off["value"]
print(f"telemetry-on/off tok/s ratio: {ratio:.3f} "
      f"({len(trace['traceEvents'])} trace events, "
      f"{len(flight['ticks'])} flight ticks, "
      f"watchdog findings: {[f['kind'] for f in flight['watchdog']]})")
if ratio < 0.95:
    raise SystemExit("telemetry overhead above 5% — the span/ring path is "
                     "leaking work into the measured drain")
PY

echo "== 7e. chaos soak (seeded fault plan vs fault-free twin, strict watchdog) =="
python tools/serving_benchmark.py --paged --chaos --strict --pool-frac 0.35 \
  --scheduler priority --mixed-priority --arrival-rate 400 --burst 4 \
  --seed 3 --telemetry-out /tmp/tpu_runs/chaos --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_chaos.json \
  || { echo "chaos soak FAILED (engine crash, chaos-pass recompiles above the"\
       "fault-free budget, or dirty watchdog after recovery)"; exit 1; }
python - <<'PY'
# chaos gate: the seeded plan must actually fire, every non-quarantined
# request must match its fault-free twin token-for-token, the chaos pass
# must not compile a single program beyond the fault-free reference
# (enforced in-process by the jit guard; re-checked here from the line),
# recovery must leave a clean watchdog (--strict already exits non-zero
# on findings), and degraded throughput must hold >=90% of the twin
import json
r = json.load(open("/tmp/tpu_runs/serving_chaos.json"))
print(f"faults {r['faults_injected']} at {r['fault_sites']}, "
      f"tick retries {r['tick_retries']}, quarantined {r['quarantined']}, "
      f"mismatches {r['token_mismatches']}, recompiles "
      f"{r['drain_recompiles']}/{r['ref_drain_recompiles']} (chaos/ref), "
      f"tok/s {r['value']} vs ref {r['ref_tok_s']}")
assert r["faults_injected"] > 0, "fault plan never fired — chaos soak vacuous"
assert r["token_mismatches"] == 0, \
    "surviving request diverged from its fault-free twin"
assert r["drain_recompiles"] <= r["ref_drain_recompiles"], \
    "fault handling compiled new programs during the soak"
assert r["watchdog_after_recovery"] == 0, \
    "watchdog findings after the plan was spent — degradation stuck on"
if r["value"] < 0.9 * r["ref_tok_s"]:
    raise SystemExit("chaos throughput below 90% of the fault-free twin — "
                     "retry/backoff ladder costs too much steady-state")
PY

echo "== 7f. fleet failover gate (2 replicas, seeded kill mid-decode vs undisturbed twin) =="
python tools/serving_benchmark.py --paged --fleet 2 --chaos --strict \
  --requests 24 --slots 4 --max-new 48 --tick-window 4 \
  --seed 3 --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_fleet.json \
  || { echo "fleet gate FAILED (failover drain above the twin's compile"\
       "budget, or dirty watchdog after recovery)"; exit 1; }
python - <<'PY'
# fleet gate: the seeded plan must kill exactly one of the two replicas
# mid-decode; every non-quarantined request must finish token-identical
# to the UNDISTURBED single-engine twin; the failover drain must stay
# within the twin's compile budget (enforced in-process by the jit
# guard; re-checked from the line); recovery on the survivor must leave
# a clean watchdog; and the line must carry the schema/fingerprint
# contract downstream tooling keys on
import json
r = json.load(open("/tmp/tpu_runs/serving_fleet.json"))
print(f"deaths {r['fleet_deaths']} (states {r['fleet_states']}), "
      f"salvaged {r['fleet_migrated_requests']} "
      f"(kv {r['fleet_migrated_kv']}), quarantined {r['quarantined']}, "
      f"mismatches {r['token_mismatches']}, recompiles "
      f"{r['drain_recompiles']}/{r['ref_drain_recompiles']} (fleet/ref), "
      f"tok/s {r['value']} vs twin {r['ref_tok_s']}")
assert r.get("schema_version") == 6, "benchmark schema drifted"
assert r.get("config_fingerprint"), "missing config fingerprint"
assert r["fleet_deaths"] == 1, "seeded kill never landed — gate vacuous"
assert r["fleet_states"]["dead"] == 1 and r["fleet_states"]["live"] == 1
assert r["fleet_migrated_requests"] >= 1, \
    "kill landed after the decode finished — nothing was salvaged"
assert r["token_mismatches"] == 0, \
    "non-quarantined request diverged from the undisturbed twin"
assert r["quarantined"] == 0, \
    "requests quarantined with a live survivor available"
assert r["drain_recompiles"] <= r["ref_drain_recompiles"], \
    "failover migration compiled beyond the twin's drain budget"
assert r["watchdog_after_recovery"] == 0, \
    "survivor watchdog dirty after the plan was spent"
assert len(r["replicas"]) == 2, "per-replica rows missing"
PY

echo "== 7g. Pallas serving-kernel gate (parity + mega-kernel tok/s vs jnp reference) =="
# interpret-mode parity first: same kernels the TPU runs, executed on the
# host interpreter — catches masking/dequant/LoRA-fusion bugs cheaply
JAX_PLATFORMS=cpu python -m pytest tests/test_paged_pallas.py -q \
  || { echo "kernel parity suite FAILED (Pallas diverged from the jnp"\
       "reference in interpret mode)"; exit 1; }
python tools/kernel_bench.py --json | tee /tmp/tpu_runs/kernel_bench.json \
  || { echo "kernel bench FAILED (per-op parity above tolerance)"; exit 1; }
python tools/serving_benchmark.py --paged --kv-quant int8 --kernels pallas \
  --guard-recompiles --requests 16 --slots 4 --max-new 32 --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_pallas.json \
  || { echo "pallas serving gate FAILED (recompile budget or tick"\
       "divergence with kernels=pallas)"; exit 1; }
python - <<'PY'
# kernel gate: every (op, quant, shape) combo must hold parity with the
# jnp reference; on real hardware the Mosaic kernels must also beat the
# gather-based reference per op AND end-to-end (kernel_tok_s from the
# serving line) — in interpret mode the speedup clause is skipped, the
# kernels run thousands of times slower by design
import json
rows = [json.loads(l) for l in open("/tmp/tpu_runs/kernel_bench.json")]
srv = json.load(open("/tmp/tpu_runs/serving_pallas.json"))
on_tpu = rows[0]["backend"] in ("tpu", "axon")
assert rows and all(r["parity"] for r in rows), "kernel parity FAILED"
assert srv.get("kernels") == "pallas" and "kernel_tok_s" in srv, srv
print(f"{len(rows)} kernel combos parity-clean "
      f"({rows[0]['pallas_mode']} mode); serving kernel "
      f"{srv['kernel_tok_s']} vs ref {srv['kernel_ref_tok_s']} tok/s, "
      f"dispatch {srv['kernel_dispatch_us']}us")
if on_tpu:
    slow = [r for r in rows if r["speedup"] < 1.0]
    assert not slow, f"Mosaic kernels slower than reference: {slow}"
    assert srv["kernel_tok_s"] >= srv["kernel_ref_tok_s"], \
        "fused decode attention lost to the gather reference on TPU"
PY

echo "== 7h. multi-chip serving gate (tp=2 dryrun token-equal to single-chip; disaggregated 1+1 fleet with seeded prefill kill) =="
# CPU dryrun mesh ON PURPOSE (JAX_PLATFORMS=cpu + forced host devices):
# the TP claim being gated is TOKEN equality + zero steady-state
# recompiles under GSPMD sharding, which the host backend proves without
# burning chip time; on-chip tp throughput is a pod-slice measurement,
# not a single-chip suite stage
JAX_PLATFORMS=cpu python -m pytest tests/test_tp_serving.py tests/test_fleet_disagg.py -q \
  || { echo "multi-chip serving suite FAILED (TP token divergence or"\
       "disagg handoff regression)"; exit 1; }
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 12 \
  --slots 4 --max-new 24 --guard-recompiles --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_tp1_dryrun.json \
  || { echo "tp=1 dryrun FAILED"; exit 1; }
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 12 \
  --slots 4 --max-new 24 --mesh tp=2 --guard-recompiles --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_tp2_dryrun.json \
  || { echo "tp=2 dryrun FAILED (recompile guard tripped or the mesh"\
       "path crashed)"; exit 1; }
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --fleet 2 \
  --disagg --chaos --strict --requests 24 --slots 4 --max-new 48 \
  --tick-window 4 --seed 3 --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_disagg.json \
  || { echo "disaggregated fleet gate FAILED (prefill-kill salvage drain"\
       "above the twin's compile budget, or dirty watchdog)"; exit 1; }
JAX_PLATFORMS=cpu python tools/kernel_bench.py --tp 2 --shapes 2,4,8 \
  --iters 2 --json | tee /tmp/tpu_runs/kernel_bench_tp.json \
  || { echo "sharded kernel parity FAILED (shard_map head-slice output"\
       "diverged from the unsharded reference)"; exit 1; }
python - <<'PY'
# multi-chip gate: the tp=2 line must be TOKEN-IDENTICAL to the tp=1
# line (same seed, same traffic — the fingerprint hashes every output
# sequence), carry the per-chip normalization, and hold the v4 schema;
# the disaggregated run must kill exactly the prefill replica, salvage
# every in-flight request onto the decode class token-exact, and come
# back watchdog-clean
import json
t1 = json.load(open("/tmp/tpu_runs/serving_tp1_dryrun.json"))
t2 = json.load(open("/tmp/tpu_runs/serving_tp2_dryrun.json"))
dg = json.load(open("/tmp/tpu_runs/serving_disagg.json"))
print(f"tp1 {t1['value']} tok/s vs tp2 {t2['value']} "
      f"({t2['tok_s_per_chip']}/chip), fingerprints "
      f"{t1['tokens_fingerprint']}/{t2['tokens_fingerprint']}; disagg "
      f"deaths {dg['fleet_deaths']} (states {dg['fleet_states']}), "
      f"handoffs {dg['handoffs']}, salvage lat p95 "
      f"{dg['migration_latency_p95_s']}s, mismatches "
      f"{dg['token_mismatches']}")
assert t1.get("schema_version") == t2.get("schema_version") == 6
assert t1["tp"] == 1 and t2["tp"] == 2 and t2["mesh"] == "tp2"
assert t1["tokens_fingerprint"] == t2["tokens_fingerprint"], \
    "tp=2 serving diverged from single-chip tokens"
assert abs(t2["tok_s_per_chip"] - t2["value"] / 2) < 0.1
assert dg["disagg"] is True and dg["fleet_deaths"] == 1
assert dg["fleet_states"]["dead"] == 1 and dg["fleet_states"]["live"] == 1
assert dg["prefill_replicas"] == 0, \
    "the seeded kill missed the prefill class"
assert dg["decode_replicas"] == 1
assert dg["token_mismatches"] == 0 and dg["quarantined"] == 0, \
    "prefill-kill salvage lost or diverged a request"
assert dg["migration_latency_samples"] >= 1
assert dg["migration_latency_p95_s"] >= dg["migration_latency_p50_s"] >= 0
assert dg["watchdog_after_recovery"] == 0, \
    "decode-class survivor dirty after recovery"
PY

echo "== 7i. long-context serving gate (cp=2 prefill token-equal to cp=1; tiered hot/warm/cold KV token-exact under forced demotion) =="
# CPU dryrun ON PURPOSE (same rationale as 7h): the claims gated here
# are token equality + zero steady-state recompiles under the cp mesh
# and the tier ladder, which the host backend proves without chip time
JAX_PLATFORMS=cpu python -m pytest tests/test_tiered_kv.py -q \
  || { echo "tiered-KV / context-parallel suite FAILED"; exit 1; }
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 12 \
  --slots 4 --max-new 24 --long-context --lc-min 128 --lc-max 512 \
  --shared-prefix 0.5 --guard-recompiles --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_cp1_dryrun.json \
  || { echo "cp=1 long-context dryrun FAILED"; exit 1; }
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 12 \
  --slots 4 --max-new 24 --long-context --lc-min 128 --lc-max 512 \
  --shared-prefix 0.5 --mesh cp=2 --guard-recompiles --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_cp2_dryrun.json \
  || { echo "cp=2 long-context dryrun FAILED (recompile guard tripped or"\
       "the cp mesh path crashed)"; exit 1; }
# int8 KV + LoRA over the cp axis: sharded chunked prefill must stay
# token-exact when fused dequant + adapter deltas ride the same program
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 8 \
  --slots 4 --max-new 16 --kv-quant int8 --lora-adapters 2 --lora-rank 4 \
  --guard-recompiles --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_cp1_int8lora.json \
  || { echo "cp=1 int8+LoRA dryrun FAILED"; exit 1; }
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 8 \
  --slots 4 --max-new 16 --kv-quant int8 --lora-adapters 2 --lora-rank 4 \
  --mesh cp=2 --guard-recompiles --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_cp2_int8lora.json \
  || { echo "cp=2 int8+LoRA dryrun FAILED"; exit 1; }
# forced-demotion pass: a pool too small for the shared-prefix workload
# must spill through the warm tier (and cold re-prefill) yet finish
# token-identical to the big-pool cp=1 twin above, recompile-clean
# (--guard-recompiles) and watchdog-clean (--strict)
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 12 \
  --slots 4 --max-new 24 --long-context --lc-min 128 --lc-max 512 \
  --shared-prefix 0.5 --num-blocks 48 --tier-demote 0.2:0.45 \
  --guard-recompiles --strict --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_tiered_dryrun.json \
  || { echo "tiered-KV dryrun FAILED (steady-state recompile, watchdog"\
       "finding, or crash under forced demotion)"; exit 1; }
python - <<'PY'
# long-context gate: the cp=2 lines must be TOKEN-IDENTICAL to their
# cp=1 twins (fp, and int8+LoRA — the fingerprint hashes every output
# sequence) and carry the cp-aware mesh/per-chip normalization; the
# starved-pool run must actually exercise the tier ladder (demotions,
# warm-tier prefix hits, cold re-prefills all > 0) and still match the
# big-pool twin token-for-token — the hierarchy is a capacity ladder,
# never a semantics change
import json
c1 = json.load(open("/tmp/tpu_runs/serving_cp1_dryrun.json"))
c2 = json.load(open("/tmp/tpu_runs/serving_cp2_dryrun.json"))
q1 = json.load(open("/tmp/tpu_runs/serving_cp1_int8lora.json"))
q2 = json.load(open("/tmp/tpu_runs/serving_cp2_int8lora.json"))
td = json.load(open("/tmp/tpu_runs/serving_tiered_dryrun.json"))
print(f"cp1 {c1['value']} tok/s vs cp2 {c2['value']} "
      f"(prefill {c2['prefill_tok_s_per_chip']}/chip), fingerprints "
      f"{c1['tokens_fingerprint']}/{c2['tokens_fingerprint']}; tiered "
      f"dem {td['tier_demotions']} pro {td['tier_promotions']}, "
      f"hit rates {td['tier_hit_rate']}")
assert all(x.get("schema_version") == 6 for x in (c1, c2, q1, q2, td)), \
    "benchmark schema drifted"
assert c1["cp"] == 1 and c2["cp"] == 2 and c2["mesh"] == "tp1cp2"
assert c1["tokens_fingerprint"] == c2["tokens_fingerprint"], \
    "cp=2 chunked prefill diverged from single-chip tokens"
assert q1["tokens_fingerprint"] == q2["tokens_fingerprint"], \
    "cp=2 int8+LoRA serving diverged from single-chip tokens"
assert c2["prefill_tok_s_per_chip"] > 0
assert abs(c2["tok_s_per_chip"] - c2["value"] / 2) < 0.1
assert td["tokens_fingerprint"] == c1["tokens_fingerprint"], \
    "tier ladder changed tokens vs the all-HBM big-pool twin"
assert td["tier_demotions"] > 0, \
    "starved pool never demoted — tier gate vacuous"
assert td["tier_hit_rate"]["warm"] > 0, \
    "shared prefix never re-hit the warm tier"
assert td["tier_hit_rate"]["cold"] > 0, \
    "no cold re-prefill exercised — shrink the pool or the warm budget"
assert td["tier_promotions"] > 0, "warm hits never promoted back to HBM"
PY

echo "== 7j. whole-tick megakernel gate (tick parity + one-program serving token-equal to reference at zero recompiles) =="
# interpret-mode parity first (same rationale as 7g): the whole-tick
# program vs the model's own per-layer loop, on the host interpreter
JAX_PLATFORMS=cpu python -m pytest tests/test_megakernel.py -q \
  || { echo "megakernel parity suite FAILED (whole-tick program diverged"\
       "from the per-layer loop in interpret mode)"; exit 1; }
python tools/kernel_bench.py --ops tick --shapes 2,4,8 --iters 3 --json \
  | tee /tmp/tpu_runs/kernel_bench_tick.json \
  || { echo "whole-tick bench FAILED (tick parity above tolerance)"; exit 1; }
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 12 \
  --slots 4 --max-new 24 --kernels reference --guard-recompiles --json \
  2>/dev/null | tee /tmp/tpu_runs/serving_mk_ref.json \
  || { echo "reference twin for the megakernel gate FAILED"; exit 1; }
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 12 \
  --slots 4 --max-new 24 --kernels megakernel --guard-recompiles --json \
  2>/dev/null | tee /tmp/tpu_runs/serving_mk.json \
  || { echo "megakernel serving gate FAILED (recompile budget tripped or"\
       "the whole-tick path crashed)"; exit 1; }
python - <<'PY'
# megakernel gate: every tick-bench combo must hold parity with the
# per-layer reference AND actually engage the megakernel (a ladder that
# silently fell to pallas would make the row vacuous — off-TPU the tiny
# CI geometry is interpret-legal, on-TPU the default head geometry is
# Mosaic-aligned); the serving line must be TOKEN-IDENTICAL to its
# reference twin (same seed, same traffic) with the rung recorded; on
# real hardware the one-program trip must also beat the jnp reference
# end-to-end — in interpret mode the speed clause is skipped (same
# rationale as 7g)
import json
rows = [json.loads(l) for l in open("/tmp/tpu_runs/kernel_bench_tick.json")]
ref = json.load(open("/tmp/tpu_runs/serving_mk_ref.json"))
srv = json.load(open("/tmp/tpu_runs/serving_mk.json"))
on_tpu = rows[0]["backend"] in ("tpu", "axon")
assert rows and all(r["parity"] for r in rows), "tick parity FAILED"
assert all(r["megakernel_active"] for r in rows), \
    "megakernel never engaged in the tick bench — gate vacuous"
assert ref.get("kernels") == "reference"
assert srv.get("kernels") == "megakernel" and srv.get("megakernel_active"), \
    srv.get("megakernel_reason")
assert srv["tokens_fingerprint"] == ref["tokens_fingerprint"], \
    "megakernel serving diverged from reference tokens"
print(f"{len(rows)} tick combos parity-clean ({rows[0]['pallas_mode']} "
      f"mode), dispatch {rows[0].get('tick_dispatch_us')}us/trip vs "
      f"layered {rows[0]['ref_dispatch_us']}us; serving "
      f"{srv['megakernel_tok_s']} tok/s whole-tick vs per-op "
      f"{srv['kernel_tok_s']}, tokens fingerprint-equal to reference")
if on_tpu:
    slow = [r for r in rows if r.get("mk_speedup", 0) < 1.0]
    assert not slow, f"megakernel slower than jnp reference on TPU: {slow}"
    assert srv["megakernel_tok_s"] >= srv["kernel_ref_tok_s"], \
        "whole-tick program lost to the gather reference on TPU"
PY

echo "== 7k. kernel-geometry gate (per-op schedule sweep: bit-exact candidates, deterministic winners, swept serving token-equal to default) =="
# interpret-mode parity first (same rationale as 7g): every supported
# geometry must be BIT-exact vs the default schedule, fp and int8
JAX_PLATFORMS=cpu python -m pytest tests/test_kernel_geometry.py -q \
  || { echo "kernel-geometry parity suite FAILED (a schedule candidate"\
       "diverged bitwise from the default kernel)"; exit 1; }
# determinism: two sweeps at one seed under the injectable counting
# clock must be byte-identical — rows AND the emitted winner cache
python tools/kernel_bench.py --shapes 2,4,8 --ops decode --quant fp,int8 \
  --iters 2 --sweep-geometry --seed 11 --clock counting --json \
  --emit-cache /tmp/tpu_runs/geometry_cache_a.json \
  | tee /tmp/tpu_runs/kernel_bench_sweep_a.json \
  || { echo "geometry sweep FAILED (candidate crashed or parity reject"\
       "took the winner slot)"; exit 1; }
python tools/kernel_bench.py --shapes 2,4,8 --ops decode --quant fp,int8 \
  --iters 2 --sweep-geometry --seed 11 --clock counting --json \
  --emit-cache /tmp/tpu_runs/geometry_cache_b.json \
  > /tmp/tpu_runs/kernel_bench_sweep_b.json \
  || { echo "geometry sweep rerun FAILED"; exit 1; }
cmp /tmp/tpu_runs/kernel_bench_sweep_a.json \
    /tmp/tpu_runs/kernel_bench_sweep_b.json \
  || { echo "geometry sweep NONDETERMINISTIC (two runs at one seed under"\
       "the counting clock differ)"; exit 1; }
cmp /tmp/tpu_runs/geometry_cache_a.json /tmp/tpu_runs/geometry_cache_b.json \
  || { echo "geometry winner cache NONDETERMINISTIC across reruns"; exit 1; }
# real-clock sweep: the row the speed clauses read (winner + speedup
# vs default; Mosaic clauses gated on_tpu below, same rationale as 7g)
python tools/kernel_bench.py --shapes 2,4,8 --ops decode --quant fp,int8 \
  --iters 3 --sweep-geometry --seed 11 --json \
  | tee /tmp/tpu_runs/kernel_bench_sweep.json \
  || { echo "real-clock geometry sweep FAILED"; exit 1; }
# serving twin: same seed + traffic, default geometry vs a swept cache
# installed before the server builds — tokens must be IDENTICAL (the
# whole point: geometry moves the schedule, never the math). CPU dryrun
# on purpose (token equality is backend-independent, 7h rationale), so
# the cache is keyed to the CPU stand-in model dims.
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 12 \
  --slots 4 --max-new 24 --guard-recompiles --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_geo_ref.json \
  || { echo "default-geometry twin FAILED"; exit 1; }
JAX_PLATFORMS=cpu python - <<'PY'
# non-default winners for the CPU stand-in serving model (hidden 128,
# 4 heads -> head_dim 32, float32): exercises the swept source end to
# end without depending on what the real sweep above happened to pick
import json
from paddle_tpu.autotune.kernel_geometry import (CEGeometry, GeometryCache,
                                                 NormGeometry,
                                                 PagedAttentionGeometry,
                                                 local_device_kind)
c = GeometryCache()
kind = local_device_kind()
c.put("paged_attention", "float32", 32, kind,
      PagedAttentionGeometry(kv_block_depth=2, grid_order="gbm"))
c.put("fused_norm", "float32", 128, kind, NormGeometry(rows=8))
c.put("fused_ce", "float32", 128, kind, CEGeometry(rows=64))
with open("/tmp/tpu_runs/serving_geometry_cache.json", "w") as f:
    json.dump(c.to_dict(), f)
PY
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --paged --requests 12 \
  --slots 4 --max-new 24 --guard-recompiles --json \
  --geometry-cache /tmp/tpu_runs/serving_geometry_cache.json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_geo.json \
  || { echo "swept-geometry serving FAILED (recompile budget tripped or"\
       "a non-default schedule crashed the tick)"; exit 1; }
python - <<'PY'
# geometry gate: every sweep row must be parity-clean, with ZERO
# rejected candidates for the families whose schedules are bit-exact
# by design (paged attention, LoRA, norm, CE) and >= 3 candidates (a
# 1-candidate sweep is vacuous). Flash block_q is row-independent but
# its bitwise equality is backend-dependent (host BLAS may regroup the
# contraction by tile shape), so flash rejects are legal — the bitwise
# gate rejecting them IS the mechanism, and the winner stays exact.
# The swept serving line must actually engage the cache (source
# 'swept') and be TOKEN-IDENTICAL to the default twin; on real
# hardware the winner must not lose to the default it was picked over
import json
rows = [json.loads(l)
        for l in open("/tmp/tpu_runs/kernel_bench_sweep.json")]
ref = json.load(open("/tmp/tpu_runs/serving_geo_ref.json"))
srv = json.load(open("/tmp/tpu_runs/serving_geo.json"))
on_tpu = rows[0]["backend"] in ("tpu", "axon")
swept = [r for r in rows if "winner_geometry" in r]
assert swept, "no sweep rows emitted — gate vacuous"
assert all(r["parity"] for r in rows), "geometry sweep parity FAILED"
strict = [r for r in swept if r.get("op") != "flash_attention"]
assert all(r["geometry_parity_rejects"] == 0 for r in strict), \
    "a bit-exact-by-design geometry candidate diverged from default"
assert all(r["geometry_candidates"] >= 3 for r in swept), \
    "sweep ran with fewer than 3 candidates — gate vacuous"
fams = {r["op"] for r in rows if r.get("metric") == "geometry_sweep"}
assert {"fused_lora", "fused_norm", "fused_ce",
        "flash_attention"} <= fams, f"family rungs missing: {fams}"
src = srv.get("kernel_geometry_source") or {}
assert any(s == "swept" for s in src.values()), \
    "swept cache never engaged in serving — twin vacuous"
assert srv["tokens_fingerprint"] == ref["tokens_fingerprint"], \
    "swept geometry CHANGED serving tokens (schedule leaked into math)"
print(f"{len(swept)} sweep rows parity-clean "
      f"({rows[0]['pallas_mode']} mode), "
      f"{sum(r['geometry_candidates'] for r in swept)} candidates, "
      f"0 parity rejects; swept serving token-equal to default twin "
      f"(sources {src})")
if on_tpu:
    slow = [r for r in swept if r["geometry_speedup"] < 1.0]
    assert not slow, f"geometry winner slower than default on TPU: {slow}"
PY

echo "== 7l. fleet-at-scale gate (2-process socket fleet, fast-time slice, mid-run kill vs in-process twin; 1M-session simulated day) =="
# deliberately pinned to CPU: cross-process token-exactness needs both
# sides of the twin on one backend, and the gate must not serialize on
# the chip lock — the fleet layer under test is backend-agnostic
JAX_PLATFORMS=cpu python tools/fleet_sim.py --execute-slice 10 \
  --transport subprocess --kill-tick 3 --seed 0 --json 2>/dev/null \
  | tee /tmp/tpu_runs/fleet_slice.json \
  || { echo "fleet slice FAILED (transport, salvage, or twin divergence)"; exit 1; }
JAX_PLATFORMS=cpu python tools/fleet_sim.py --execute-slice 10 \
  --transport subprocess --kill-tick 3 --seed 0 --json 2>/dev/null \
  > /tmp/tpu_runs/fleet_slice_2.json
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --sim \
  --sim-sessions 1000000 --seed 0 --json 2>/dev/null \
  | tee /tmp/tpu_runs/fleetsim_day.json
JAX_PLATFORMS=cpu python tools/serving_benchmark.py --sim \
  --sim-sessions 1000000 --seed 0 --json 2>/dev/null \
  > /tmp/tpu_runs/fleetsim_day_2.json
python - <<'PY'
# fleet-at-scale gate: the measured fleet (real OS processes over the
# socket transport, one SIGKILL mid-decode, autoscaler forced through a
# scale-up and a drain) must be token-exact against the undisturbed
# in-process twin, watchdog-clean, and byte-identical across two
# same-seed runs; the simulated day must clear 1M sessions with the
# elastic fleet beating the static peak-sized fleet on replica-hours
# while every tenant holds its SLO
import json
a = open("/tmp/tpu_runs/fleet_slice.json").read()
b = open("/tmp/tpu_runs/fleet_slice_2.json").read()
assert a == b, "same-seed fleet slice runs are not byte-identical"
r = json.loads(a)
day = json.load(open("/tmp/tpu_runs/fleetsim_day.json"))
assert r["transport"] == "subprocess"
assert r["deaths"] == 1, "scripted kill never landed — gate vacuous"
assert r["token_mismatches"] == 0, \
    "process kill / autoscale drain changed tokens vs twin"
assert r["migrated_requests"] >= 1, "kill salvaged nothing — vacuous"
assert r["scale_ups"] >= 1 and r["scale_downs"] >= 1, \
    "autoscaler never exercised both directions"
assert r["watchdog_findings"] == 0, "watchdog not clean after slice"
assert r["heartbeat_stalls"] == 0, \
    "transport round-trips tripped the heartbeat"
assert day["schema_version"] == 6 and day["sim_sessions"] == 1000000
# the day line is byte-identical per seed modulo the two documented
# wall-time keys (value = simulator wall throughput, wall_s)
day2 = json.load(open("/tmp/tpu_runs/fleetsim_day_2.json"))
strip = lambda d: json.dumps(
    {k: v for k, v in d.items() if k not in ("value", "wall_s")},
    sort_keys=True)
assert strip(day) == strip(day2), \
    "same-seed simulated days diverged beyond wall-time keys"
assert day["slo_attained"], "simulated day violated a tenant SLO"
assert day["elastic_beats_static"], \
    "elastic fleet used more replica-hours than the static peak fleet"
print(f"slice: {r['sessions']} sessions over {r['transport']}, "
      f"mismatches {r['token_mismatches']}, deaths {r['deaths']}, "
      f"salvaged {r['migrated_requests']}, ups {r['scale_ups']} / "
      f"downs {r['scale_downs']}, watchdog {r['watchdog_findings']}; "
      f"day: {day['sim_sessions']} sessions in {day['wall_s']}s wall, "
      f"elastic {day['replica_hours']}h vs static "
      f"{day['static_replica_hours']}h ({day['scale_ups']} ups, "
      f"{day['scale_downs']} downs)")
PY

echo "== 8. training chaos gate (seeded kills + torn writes + bit-flip reads vs unkilled twin) =="
python tools/train_chaos.py --steps 12 --kills 2 --seed 3 --json 2>/dev/null \
  | tee /tmp/tpu_runs/train_chaos.json \
  || { echo "training chaos gate FAILED (resume diverged from the twin,"\
       "a corruption went undetected, or a kill was never recovered)"; exit 1; }
python - <<'PY'
# training chaos gate: every scripted kill must be DETECTED by the
# elastic monitor (lease expiry -> RESTART) and recovered via
# restore-latest-valid; every replayed + continued step loss and the
# final params/opt-state must be bit-exact against the unkilled
# fault-free twin; every injected on-disk corruption must be caught by
# the CRC32 manifest and absorbed by generation fallback (zero
# undetected corruptions); torn writes must be absorbed by the retry
# rung without a single dropped save
import json
r = json.load(open("/tmp/tpu_runs/train_chaos.json"))
print(f"faults {r['faults_injected']} at {r['fault_sites']}, "
      f"kills {r['detected_kills']}/{r['restarts']} restarts, "
      f"mismatches {r['loss_mismatches']}, bitexact {r['params_bitexact']}, "
      f"corrupt reads {r['corrupt_reads_detected']}/{r['ckpt_read_fired']}, "
      f"torn-write retries {r['save_retries']} "
      f"(dropped {r['save_failures']})")
assert r["faults_injected"] > 0, "fault plan never fired — gate vacuous"
assert r["completed"], "chaos run never reached the final step"
assert r["detected_kills"] == r["restarts"] >= 1, \
    "a kill was missed by the elastic monitor or never injected"
assert r["loss_mismatches"] == 0, \
    "resumed trajectory diverged from the unkilled twin"
assert r["params_bitexact"], \
    "final params/opt-state differ from the unkilled twin"
assert r["corrupt_reads_detected"] >= r["ckpt_read_fired"], \
    "an injected on-disk corruption went UNDETECTED by the manifest"
assert r["ckpt_read_fired"] >= 1 and r["generation_fallbacks"] >= 1, \
    "corrupt-read rung never exercised — gate vacuous"
assert r["save_failures"] == 0, \
    "a torn write exhausted its retries and dropped the generation"
# goodput accounting riding the same artifact: the unkilled twin books
# every step productive (exactly 1.0 — integer step indices, no float
# residue) while the chaos run must dip below 1.0 IFF a kill forced
# replayed steps + a recovery segment
assert r["twin_goodput_ratio"] == 1.0, \
    "fault-free twin booked lost work — goodput ledger is broken"
assert (r["train_goodput_ratio"] < 1.0) == (r["detected_kills"] >= 1), \
    "goodput ratio disagrees with the kill count"
PY

echo "== 8b. train-telemetry overhead gate (instrumented vs bare step time; fault-free goodput + clean watchdog) =="
JAX_PLATFORMS=cpu python tools/train_telemetry_bench.py --json \
  --out /tmp/tpu_runs/train_telemetry 2>/dev/null \
  | tee /tmp/tpu_runs/train_telemetry.json \
  || { echo "train telemetry bench FAILED (missing spans or non-unit"\
       "fault-free goodput)"; exit 1; }
python - <<'PY'
# overhead gate: recording AROUND the compiled step (GL010) must cost
# at most ~5% even on a model small enough that the hooks are maximally
# visible; the instrumented fault-free run must leave a full train
# timeline (one train_step span per step), a clean watchdog and a
# goodput ledger of exactly 1.0
import json
r = json.load(open("/tmp/tpu_runs/train_telemetry.json"))
print(f"overhead ratio {r['overhead_ratio']:.3f} "
      f"(bare {r['median_step_bare_s'] * 1e3:.2f}ms vs instrumented "
      f"{r['median_step_instrumented_s'] * 1e3:.2f}ms), "
      f"{r['train_step_spans']} train_step spans, "
      f"{r['watchdog_findings']} watchdog findings, "
      f"goodput {r['train_goodput_ratio']}")
assert r["overhead_ratio"] >= 0.95, \
    f"train telemetry overhead above 5%: ratio {r['overhead_ratio']:.3f}"
assert r["train_step_spans"] == r["steps"] > 0, \
    "train timeline is missing steps — spans were dropped or never cut"
assert r["flight_ticks"] == r["steps"], "flight ring missed steps"
assert r["watchdog_findings"] == 0, \
    f"fault-free run tripped the watchdog: {r['watchdog']}"
assert r["train_goodput_ratio"] == 1.0, \
    "fault-free goodput is not exactly 1.0 — phantom lost work"
ev = json.load(open("/tmp/tpu_runs/train_telemetry.trace.json"))
ev = ev["traceEvents"] if isinstance(ev, dict) else ev
kinds = {e["name"] for e in ev if e.get("ph") == "X"}
assert {"train_step", "host_to_device", "dispatch",
        "device_wait"} <= kinds, f"train trace missing phases: {kinds}"
PY
# artifact tooling smoke: the dump CLI must render both artifact kinds
python tools/telemetry_dump.py /tmp/tpu_runs/train_telemetry.metrics.json \
  > /dev/null || { echo "telemetry_dump FAILED on metrics artifact"; exit 1; }
python tools/telemetry_dump.py /tmp/tpu_runs/train_telemetry.flight.json \
  > /dev/null || { echo "telemetry_dump FAILED on flight artifact"; exit 1; }

echo "== 9. serving autotune gate (short-budget search; tuned profile must hold the default's throughput on identical traffic, recompile-clean) =="
python tools/serving_benchmark.py --paged --repeat-suffix --requests 16 \
  --slots 4 --max-new 24 --seed 7 --tune 8 \
  --profile /tmp/tpu_runs/tuned_profile.json --json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_tune.json \
  || { echo "autotune search FAILED (trial crash or profile save)"; exit 1; }
python tools/serving_benchmark.py --paged --repeat-suffix --requests 16 \
  --slots 4 --max-new 24 --seed 7 --guard-recompiles --strict --json \
  2>/dev/null | tee /tmp/tpu_runs/serving_default_replay.json \
  || { echo "default replay FAILED (recompile guard or watchdog)"; exit 1; }
python tools/serving_benchmark.py --paged --repeat-suffix --requests 16 \
  --slots 4 --max-new 24 --seed 7 --guard-recompiles --strict --json \
  --profile /tmp/tpu_runs/tuned_profile.json 2>/dev/null \
  | tee /tmp/tpu_runs/serving_tuned_replay.json \
  || { echo "tuned replay FAILED (steady-state recompile or watchdog"\
       "finding under the tuned config)"; exit 1; }
python - <<'PY'
# autotune gate: the search line must record a real multi-trial search
# whose winner beat its own measured baseline; the tuned replay must see
# BYTE-IDENTICAL traffic to the default replay (the decoupling contract)
# and produce IDENTICAL tokens (greedy serving is config-invariant);
# --strict/--guard-recompiles above already enforce clean watchdog +
# zero steady-state recompiles; and tuned throughput must hold the
# default's within the chip's drift margin (the search already proved
# winner >= default on its own measured traffic)
import json
tune = json.load(open("/tmp/tpu_runs/serving_tune.json"))
dft = json.load(open("/tmp/tpu_runs/serving_default_replay.json"))
tuned = json.load(open("/tmp/tpu_runs/serving_tuned_replay.json"))
prof = json.load(open("/tmp/tpu_runs/tuned_profile.json"))
ratio = tuned["value"] / dft["value"]
print(f"tuned {tuned['value']} vs default {dft['value']} tok/s "
      f"(ratio {ratio:.2f}); search: {tune['tune_trials']} trials, "
      f"winner cfg {tune['profile_fingerprint']} "
      f"{prof['metrics']['tok_s']:.1f} vs baseline "
      f"{tune['tune_baseline_tok_s']} tok/s, "
      f"{len(prof['search']['rejected'])} rejected")
assert tune["tuned"] is True and tune["tune_budget"] == 8, tune
assert tune["tune_trials"] >= 4, "search never ran its trial plan"
assert tuned["profile_fingerprint"] == prof["config_fingerprint"]
assert tuned["profile_workload_match"] is True, \
    "replay workload drifted from the one the profile was tuned on"
assert tuned["traffic_fingerprint"] == dft["traffic_fingerprint"], \
    "tuned replay saw different traffic — config leaked into the draw"
assert tuned["tokens_fingerprint"] == dft["tokens_fingerprint"], \
    "tuned config changed the tokens — a reject gate is leaking"
assert prof["metrics"]["tok_s"] >= prof["baseline"]["tok_s"], \
    "search crowned a winner below its own measured baseline"
if ratio < 0.95:
    raise SystemExit("tuned profile below 95% of the default replay — "
                     "tuning regressed throughput beyond drift margin")
PY

echo "== done: paste the JSON lines + sweep winners into BASELINE.md =="
