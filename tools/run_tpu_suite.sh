#!/bin/bash
# One-command on-hardware sequence (VERDICT r2 items 2/3/6) — run from the
# repo root on a host that can reach a TPU chip.  Each stage is independent;
# results land in BASELINE.md-ready form on stdout and under /tmp/tpu_runs.
set -u
mkdir -p /tmp/tpu_runs
cd "$(dirname "$0")/.."

echo "== 1. probe =="
timeout 120 python -c "import jax; ds=jax.devices(); print('DEVOK', ds[0].platform, len(ds))" \
  || { echo "TPU unreachable — aborting"; exit 1; }

echo "== 2. compiled-Mosaic kernel tier (tests_tpu/) =="
python -m pytest tests_tpu/ -q 2>&1 | tee /tmp/tpu_runs/tests_tpu.log | tail -3

echo "== 3. flash block-size sweep (fwd, headline shape) =="
python tools/bench_flash_sweep.py --shapes small 2>&1 | tee /tmp/tpu_runs/sweep_small.log | tail -12
echo "== 3b. long-context sweep =="
python tools/bench_flash_sweep.py --shapes long 2>&1 | tee /tmp/tpu_runs/sweep_long.log | tail -12
echo "== 3c. fwd+bwd sweep (headline) =="
python tools/bench_flash_sweep.py --shapes small --bwd 2>&1 | tee /tmp/tpu_runs/sweep_bwd.log | tail -12
echo "adopt the winner via PT_FLASH_BLOCK_Q/PT_FLASH_BLOCK_K, then:"

echo "== 4. headline bench (509M MFU + 1.3B extra) =="
python bench.py 2>/tmp/tpu_runs/bench_err.log | tee /tmp/tpu_runs/bench.json

echo "== 5. long-context rows =="
BENCH_SKIP_LARGE=1 BENCH_B=2 BENCH_S=8192 python bench.py 2>/dev/null | tee /tmp/tpu_runs/bench_s8192.json
BENCH_SKIP_LARGE=1 BENCH_B=1 BENCH_S=16384 python bench.py 2>/dev/null | tee /tmp/tpu_runs/bench_s16384.json

echo "== done: paste the JSON lines + sweep winners into BASELINE.md =="
