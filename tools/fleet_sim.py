"""Fleet-at-scale driver: the simulated day, and the real-fleet slice.

Two modes, both deterministic at a seed:

**Pure simulation** (default): draw a seeded day of traffic
(``paddle_tpu.fleetsim.draw_day`` — a million sessions by default) and
run it through the discrete-event :class:`FleetSimulation` under the
elastic autoscaler, entirely in virtual time. Emits the FULL report
(including the journaled ``autoscale_events``, which are replay-verified
before printing) as one JSON document. Two runs at one seed are
byte-identical.

**Execute-slice** (``--execute-slice N``): materialize the first N
sessions of the SAME trace into real prompts and push them through a
real :class:`FleetRouter` of engine replicas in fast-time — in-process
handles by default, real OS processes over the socket transport with
``--transport subprocess``. The measured fleet takes a scripted
mid-run process kill (``--kill-tick``) and an autoscaler that is forced
through at least one scale-up (a third replica spawns mid-run) and one
token-exact drain; an UNDISTURBED in-process twin runs the identical
slice, and the report carries per-session token mismatches (must be 0:
journal salvage after the kill and evacuate-based drain are both
token-exact), watchdog findings, and a results fingerprint over the
submit-order token streams. This is suite stage 7l's engine.

Wall time appears nowhere in the reports — fleet time is the virtual
clock, engine time is the counting clock — so ``--json`` output
byte-compares across same-seed runs.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

MODEL_CFG = dict(vocab_size=128, hidden_size=64, intermediate_size=128,
                 num_hidden_layers=2, num_attention_heads=4,
                 num_key_value_heads=2, max_position_embeddings=160,
                 dtype="float32", use_flash_attention=False)
SERVER_KW = dict(max_batch=2, max_len=96, cache="paged", block_size=8,
                 prefill_chunk=16)


def sim_day(args) -> dict:
    from paddle_tpu.fleetsim import (DayTrafficSpec, FleetSimulation,
                                     ReplicaServiceModel, draw_day)
    from paddle_tpu.inference.autoscale import (AutoscalePolicy,
                                                ElasticAutoscaler,
                                                verify_replay)

    spec = DayTrafficSpec(sessions=args.sessions, seed=args.seed)
    policy = AutoscalePolicy(min_replicas=1,
                             max_replicas=args.max_replicas,
                             up_cooldown_s=120.0, down_cooldown_s=1200.0)
    engine = ElasticAutoscaler(args.capacity, policy=policy)
    model = ReplicaServiceModel(decode_tok_s=args.capacity,
                                prefill_tok_s=8.0 * args.capacity,
                                slots=16, spawn_delay_s=30.0)
    report = FleetSimulation(draw_day(spec), model, autoscaler=engine,
                             initial_replicas=2,
                             control_interval_s=60.0,
                             forecast_horizon_s=900.0).run()
    verify_replay(report["autoscale_events"], args.capacity,
                  policy=policy)
    report["mode"] = "sim"
    report["seed"] = args.seed
    report["traffic"] = spec.to_dict()
    return report


def _make_inproc_server():
    import paddle_tpu as paddle
    from paddle_tpu.inference.serving import GenerationServer
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(**MODEL_CFG)
    paddle.seed(7)
    return GenerationServer(LlamaForCausalLM(cfg), **SERVER_KW)


def _make_handle(transport: str):
    if transport == "subprocess":
        from paddle_tpu.inference.transport import SubprocessReplica

        spec = {"model": {"config": dict(MODEL_CFG), "seed": 7},
                "server": dict(SERVER_KW, clock="counting")}
        return SubprocessReplica(spec)
    from paddle_tpu.inference.transport import InProcessReplica

    return InProcessReplica(_make_inproc_server())


def execute_slice(args) -> dict:
    from paddle_tpu.fleetsim import (DayTrafficSpec, VirtualClock,
                                     draw_day, replay_slice)
    from paddle_tpu.inference.autoscale import (AutoscalePolicy,
                                                ElasticAutoscaler,
                                                FleetAutoscaler)
    from paddle_tpu.inference.fleet import FleetRouter

    spec = DayTrafficSpec(sessions=max(64, args.execute_slice),
                          seed=args.seed, shared_prefix_tokens=8,
                          prompt_ladder=(12, 16, 20), longtail_frac=0.0,
                          max_new_ladder=(4, 6, 8))
    trace = draw_day(spec)
    n = args.execute_slice

    # measured fleet: 2 replicas on the chosen transport, an autoscaler
    # scripted through >=1 up and >=1 drain, one mid-run process kill
    clock = VirtualClock()
    handles = [_make_handle(args.transport) for _ in range(2)]
    fleet = FleetRouter(handles, clock=clock)
    engine = ElasticAutoscaler(
        400.0, policy=AutoscalePolicy(max_replicas=4, up_cooldown_s=0.0,
                                      down_cooldown_s=0.0))
    scaler = FleetAutoscaler(fleet, engine,
                             spawn=lambda: _make_handle(args.transport))
    killed = []

    def on_tick(tick, now, submitted):
        if tick == args.kill_tick and not killed:
            h = handles[0]
            if hasattr(h, "kill_process"):
                h.kill_process()   # real SIGKILL mid-decode
            else:
                h.fail("scripted mid-run kill")
            killed.append(tick)
        elif tick == args.kill_tick + 2:
            # diurnal ramp, compressed: demand spikes -> scale-up
            scaler.control(now, demand_tok_s=1e6)
        elif tick == args.kill_tick + 6:
            # ...and falls off -> one token-exact drain
            scaler.control(now, demand_tok_s=1.0)

    out = replay_slice(trace, fleet, sessions=n, clock=clock,
                       compress=20000.0, tick_s=1.0, max_len=96,
                       on_tick=on_tick)

    # undisturbed twin: same slice, in-process, no kill, no autoscaler
    tclock = VirtualClock()
    twin = FleetRouter([_make_inproc_server() for _ in range(2)],
                       clock=tclock)
    tout = replay_slice(trace, twin, sessions=n, clock=tclock,
                        compress=20000.0, tick_s=1.0, max_len=96)

    # per-session comparison in submit order: placement (and therefore
    # rid) legitimately differs once the autoscaler reshapes the fleet,
    # but the TOKENS of session i may not
    mismatches = sum(
        1 for i in range(n)
        if out["results"].get(out["rids"][i])
        != tout["results"].get(tout["rids"][i]))
    fingerprint = hashlib.sha256(json.dumps(
        [out["results"].get(r) for r in out["rids"]]).encode()
    ).hexdigest()[:16]

    fm = fleet.fleet_metrics()
    watchdog = []
    for rep in fleet._replicas:
        if rep.state in ("live", "degraded"):
            watchdog.extend(rep.server.watchdog_findings())
    ups = sum(1 for d in engine.events if d.action == "up")
    downs = sum(1 for d in engine.events if d.action == "down")
    events = [d.as_dict() for d in engine.events]
    for ev in events:
        for k in ("t", "demand_tok_s", "forecast_tok_s", "burn_rate"):
            ev[k] = round(ev[k], 6)
    report = {"mode": "execute-slice", "transport": args.transport,
              "sessions": n, "ticks": out["ticks"],
              "twin_ticks": tout["ticks"],
              "token_mismatches": mismatches,
              "results_fingerprint": fingerprint,
              "fleet_states": fm["states"],
              "deaths": fm["deaths"],
              "migrated_requests": fm["migrated_requests"],
              "heartbeat_stalls": fm["heartbeat_stalls"],
              "watchdog_findings": len(watchdog),
              "scale_ups": ups, "scale_downs": downs,
              "autoscale_events": events,
              "kill_tick": args.kill_tick,
              "seed": args.seed,
              "traffic_signature": trace.signature()}
    # tear down every process (added replicas included)
    for rep in fleet._replicas:
        close = getattr(rep.server, "close", None)
        if close is not None:
            close()
    return report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sessions", type=int, default=1_000_000,
                    help="sessions in the simulated day (sim mode)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--capacity", type=float, default=400.0,
                    help="analytic per-replica decode tok/s (sim mode)")
    ap.add_argument("--max-replicas", type=int, default=12)
    ap.add_argument("--execute-slice", type=int, default=0, metavar="N",
                    help="replay the first N sessions through a REAL "
                         "fleet in fast-time instead of simulating")
    ap.add_argument("--transport", choices=("inproc", "subprocess"),
                    default="inproc",
                    help="replica backend for --execute-slice: "
                         "in-process servers or real OS processes over "
                         "the socket transport")
    ap.add_argument("--kill-tick", type=int, default=3,
                    help="router tick at which the scripted kill lands "
                         "on replica 0 (--execute-slice)")
    ap.add_argument("--json", action="store_true",
                    help="emit exactly one JSON document on stdout")
    args = ap.parse_args()

    report = execute_slice(args) if args.execute_slice else sim_day(args)
    print(json.dumps(report, sort_keys=True))
    if not args.json:
        if report["mode"] == "sim":
            print(f"[sim] {report['sim_sessions']} sessions, elastic "
                  f"{report['replica_hours']}h vs static "
                  f"{report['static_replica_hours']}h, SLO "
                  f"{report['slo_attained']}", file=sys.stderr)
        else:
            print(f"[slice/{report['transport']}] {report['sessions']} "
                  f"sessions, mismatches {report['token_mismatches']}, "
                  f"deaths {report['deaths']}, ups {report['scale_ups']} "
                  f"downs {report['scale_downs']}, watchdog "
                  f"{report['watchdog_findings']}", file=sys.stderr)


if __name__ == "__main__":
    main()
