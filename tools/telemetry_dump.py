#!/usr/bin/env python
"""Pretty-print and diff telemetry artifacts.

Reads the ``.metrics.json`` / ``.flight.json`` blobs that
``serving_benchmark --telemetry-out`` and ``train_telemetry_bench
--out`` write, and renders them as tables a human can scan: counters,
gauges, histogram p50/p95, flight-ring census (ticks, program keys,
warm programs) and watchdog findings.

With two files of the same kind, prints a diff instead: counter/gauge
deltas and histogram percentile shifts — the quick answer to "what
changed between these two runs" the suite gates and autotuner debugging
need::

    python tools/telemetry_dump.py run.metrics.json
    python tools/telemetry_dump.py a.metrics.json b.metrics.json
    python tools/telemetry_dump.py run.flight.json

Autotune trial artifacts (``"kind": "autotune_trial"``, written by
``tools/autotune.py --trials-out``) get a comparison table instead: any
number of them at once, one row per trial (config fingerprint ->
tok/s, TTFT/TPOT p95, acceptance, predicted tok/s), sorted by the
search objective with rejected trials sunk to the bottom::

    python tools/telemetry_dump.py trials/trial_*.json

Stdlib + the repo only; no display dependencies.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _labels(d: Dict[str, Any]) -> str:
    if not d:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in sorted(d.items())) + "}"


def _metrics_tree(blob: Dict[str, Any]) -> Dict[str, Any]:
    """Accept either a raw ``MetricsRegistry.to_json()`` tree or a
    ``snapshot()`` wrapper that nests it under ``metrics``."""
    return blob.get("metrics", blob) if "counters" not in blob else blob


def _scalar_series(tree: Dict[str, Any], kind: str) \
        -> Dict[Tuple[str, str], float]:
    out: Dict[Tuple[str, str], float] = {}
    for name, entry in tree.get(kind, {}).items():
        for row in entry.get("series", []):
            out[(name, _labels(row.get("labels", {})))] = row["value"]
    return out


def _hist_rows(tree: Dict[str, Any]) \
        -> Dict[Tuple[str, str], Dict[str, Any]]:
    out: Dict[Tuple[str, str], Dict[str, Any]] = {}
    for name, entry in tree.get("histograms", {}).items():
        for row in entry.get("series", []):
            out[(name, _labels(row.get("labels", {})))] = row
    return out


def _print_table(title: str, rows: List[Tuple[str, ...]],
                 header: Tuple[str, ...]) -> None:
    if not rows:
        return
    print(f"\n== {title}")
    widths = [max(len(str(r[i])) for r in [header] + rows)
              for i in range(len(header))]
    for r in [header] + rows:
        print("  " + "  ".join(str(c).ljust(w) for c, w in zip(r, widths)))


def dump_metrics(blob: Dict[str, Any]) -> None:
    tree = _metrics_tree(blob)
    rows = [(f"{n}{lb}", _fmt(v))
            for (n, lb), v in sorted(_scalar_series(tree, "counters").items())]
    _print_table("counters", rows, ("counter", "value"))
    rows = [(f"{n}{lb}", _fmt(v))
            for (n, lb), v in sorted(_scalar_series(tree, "gauges").items())]
    _print_table("gauges", rows, ("gauge", "value"))
    rows = [(f"{n}{lb}", str(r["count"]), _fmt(r.get("p50", "")),
             _fmt(r.get("p95", "")), _fmt(r["sum"]))
            for (n, lb), r in sorted(_hist_rows(tree).items())]
    _print_table("histograms", rows, ("histogram", "count", "p50", "p95",
                                     "sum"))
    for key in ("watchdog", "goodput"):
        if blob.get(key):
            print(f"\n== {key}")
            print(json.dumps(blob[key], indent=1))


def diff_metrics(a: Dict[str, Any], b: Dict[str, Any]) -> None:
    ta, tb = _metrics_tree(a), _metrics_tree(b)
    for kind in ("counters", "gauges"):
        sa, sb = _scalar_series(ta, kind), _scalar_series(tb, kind)
        rows = []
        for key in sorted(set(sa) | set(sb)):
            va, vb = sa.get(key), sb.get(key)
            if va == vb:
                continue
            delta = "" if None in (va, vb) else _fmt(vb - va)
            rows.append((f"{key[0]}{key[1]}",
                         _fmt(va) if va is not None else "-",
                         _fmt(vb) if vb is not None else "-", delta))
        _print_table(f"{kind} (changed)", rows, (kind[:-1], "a", "b", "Δ"))
    ha, hb = _hist_rows(ta), _hist_rows(tb)
    rows = []
    for key in sorted(set(ha) | set(hb)):
        ra, rb = ha.get(key), hb.get(key)
        if ra == rb:
            continue
        fmt_p = lambda r, p: _fmt(r.get(p, "")) if r else "-"
        rows.append((f"{key[0]}{key[1]}",
                     str(ra["count"]) if ra else "-",
                     str(rb["count"]) if rb else "-",
                     fmt_p(ra, "p50"), fmt_p(rb, "p50"),
                     fmt_p(ra, "p95"), fmt_p(rb, "p95")))
    _print_table("histograms (changed)", rows,
                 ("histogram", "n:a", "n:b", "p50:a", "p50:b",
                  "p95:a", "p95:b"))


def dump_flight(blob: Dict[str, Any]) -> None:
    ticks = blob.get("ticks", [])
    print(f"flight: {len(ticks)} tick(s)")
    census: Dict[str, int] = {}
    compiles = 0
    for t in ticks:
        prog = t.get("prog")
        if prog is not None:
            census[prog] = census.get(prog, 0) + 1
        compiles += int(t.get("recompiles", 0))
    _print_table("program census", sorted(census.items()),
                 ("prog", "ticks"))
    print(f"\nbackend compiles across ring: {compiles}")
    if blob.get("warm_progs"):
        print(f"warm programs (pre-boundary): {blob['warm_progs']}")
    findings = blob.get("watchdog", [])
    print(f"watchdog findings: {len(findings)}")
    for f in findings:
        print(f"  [{f.get('kind')}] {f.get('detail')}")


def diff_flight(a: Dict[str, Any], b: Dict[str, Any]) -> None:
    for label, blob in (("a", a), ("b", b)):
        print(f"--- {label} ---")
        dump_flight(blob)
        print()


def dump_trials(blobs: List[Dict[str, Any]]) -> None:
    """N autotune trials side by side, best objective (tok/s) first,
    rejects at the bottom — the "why did THIS config win" table."""

    def _f(v: Any, nd: int = 1) -> str:
        return "-" if v is None else f"{float(v):.{nd}f}"

    def _key(b: Dict[str, Any]):
        feats = b.get("features", {})
        return (0 if b.get("accepted") else 1,
                -(feats.get("tok_s") or 0.0),
                b.get("index", 0))

    rows = []
    for b in sorted(blobs, key=_key):
        feats = b.get("features", {})
        status = "ok" if b.get("accepted") else \
            f"REJECT {(b.get('reject_reason') or '?').split(':')[0]}"
        rows.append((str(b.get("index", "?")), b.get("rung", "?"),
                     b.get("fingerprint", "?"),
                     _f(feats.get("tok_s")),
                     _f(feats.get("ttft_p95_s"), 4),
                     _f(feats.get("tpot_p95_ms"), 3),
                     _f(feats.get("acceptance"), 3),
                     _f(b.get("predicted_tok_s")),
                     status))
    _print_table(f"autotune trials ({len(rows)})", rows,
                 ("trial", "rung", "config", "tok/s", "ttft_p95_s",
                  "tpot_p95_ms", "accept", "predicted", "status"))


def _kind(blob: Dict[str, Any]) -> str:
    if blob.get("kind") == "autotune_trial":
        return "trial"
    return "flight" if "ticks" in blob else "metrics"


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("paths", nargs="+",
                   help="one artifact to pretty-print, or two of the "
                        "same kind to diff")
    args = p.parse_args(argv)
    blobs = []
    for path in args.paths:
        try:
            with open(path) as f:
                blobs.append(json.load(f))
        except (OSError, ValueError) as e:
            print(f"error: cannot read {path}: {e}", file=sys.stderr)
            return 2
    if all(_kind(b) == "trial" for b in blobs):
        dump_trials(blobs)
        return 0
    if any(_kind(b) == "trial" for b in blobs):
        print("error: cannot mix autotune trials with other artifact "
              "kinds", file=sys.stderr)
        return 2
    if len(blobs) > 2:
        p.error("pass one file to dump or two to diff (any number of "
                "autotune trials)")
    if len(blobs) == 1:
        (dump_flight if _kind(blobs[0]) == "flight"
         else dump_metrics)(blobs[0])
        return 0
    if _kind(blobs[0]) != _kind(blobs[1]):
        print("error: cannot diff a metrics artifact against a flight "
              "artifact", file=sys.stderr)
        return 2
    (diff_flight if _kind(blobs[0]) == "flight" else diff_metrics)(*blobs)
    return 0


if __name__ == "__main__":
    sys.exit(main())
