"""Optimizer-state host offload: fit a ~2.4B AdamW config on one 16 GB chip.

VERDICT r3 item 4 second half: full AdamW state is 10 B/param without
master weights (bf16 param + f32 m + v), capping the in-HBM fit near 0.9B.
With ``offload_opt_state=True`` (engine; moments parked in pinned_host
between steps, streamed over PCIe inside the compiled step) the device
holds only params + grads + activations, so a ~2.4B model trains on one
chip. Ref: group_sharded_stage3.py:60 cpu_offload semantics, done as XLA
memory kinds.

Reports tokens/s + step ms with honest sync (dispatch-chain differencing).
Usage: python tools/bench_offload.py [--layers 28] [--steps 3]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=28)
    ap.add_argument("--hidden", type=int, default=2560)
    ap.add_argument("--inter", type=int, default=6912)
    ap.add_argument("--batch", type=int, default=2)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--accum", type=int, default=1,
                    help="grad-accumulation microbatches per optimizer "
                         "step (batch is the MICRObatch size; the step "
                         "consumes batch*accum examples)")
    ap.add_argument("--window", type=int, default=None,
                    help="PT_OFFLOAD_WINDOW override")
    ap.add_argument("--order", default=None,
                    help="PT_OFFLOAD_ORDER override (backward|forward)")
    ap.add_argument("--remat", default="dots",
                    help="remat policy (dots|offload_attn|none)")
    args = ap.parse_args()
    if args.window is not None:
        os.environ["PT_OFFLOAD_WINDOW"] = str(args.window)
    if args.order is not None:
        os.environ["PT_OFFLOAD_ORDER"] = args.order

    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import ParallelEngine
    from paddle_tpu.utils.bench_timing import (device_time_ms, peak_flops,
                                               tpu_lock)

    assert any(d.platform in ("tpu", "axon") for d in jax.devices()), \
        "host offload requires the TPU backend (pinned_host memory)"
    cfg = LlamaConfig(vocab_size=32000, hidden_size=args.hidden,
                      intermediate_size=args.inter,
                      num_hidden_layers=args.layers,
                      num_attention_heads=args.hidden // 128,
                      num_key_value_heads=max(args.hidden // 128 // 4, 1),
                      max_position_embeddings=args.seq, dtype="bfloat16",
                      use_flash_attention=True)
    paddle.seed(0)
    with tpu_lock(timeout_s=900.0) as locked:
        model = LlamaForCausalLM(cfg)
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        opt = AdamW(learning_rate=1e-4, parameters=model.parameters())
        engine = ParallelEngine(model, optimizer=opt, loss_fn=None,
                                remat=args.remat != "none",
                                remat_policy=args.remat,
                                offload_opt_state=True,
                                alias_model_params=True,
                                grad_accum=args.accum)
        engine.build_train_step()
        rng = np.random.RandomState(0)
        B = args.batch * args.accum
        ids = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (B, args.seq))
            .astype("int32"))
        labels = paddle.to_tensor(
            rng.randint(0, cfg.vocab_size, (B, args.seq))
            .astype("int64"))
        ms = device_time_ms(lambda: engine.train_batch(ids, labels),
                            reps=args.steps, repeats=2, warmup=1)
        loss = float(np.asarray(engine.train_batch(ids, labels).value))
        kinds = {v.sharding.memory_kind
                 for slots in engine.opt_state.values()
                 for v in slots.values()}
    tps = args.batch * args.accum * args.seq / (ms / 1e3)
    mfu = tps * 6.0 * n_params / peak_flops()
    line = {"metric": "llama_offload_opt_tokens_per_sec_1chip",
            "value": round(tps, 1),
            "unit": f"tok/s ({n_params/1e9:.2f}B params, B={args.batch}, "
                    f"S={args.seq}, m/v in {sorted(kinds)}, loss={loss:.3f})",
            "ms_per_step": round(ms, 1), "mfu": round(mfu, 4),
            "params_b": round(n_params / 1e9, 3)}
    assert kinds == {"pinned_host"}, kinds
    if not locked:
        line["lock_contended"] = True
    print(json.dumps(line))


if __name__ == "__main__":
    main()
