"""PCIe roofline probe for the host-offload path.

Measures, through the same mechanism the offloaded optimizer compiles
(jitted device_put between memory kinds + optimization_barrier chains):

- ``h2d``: pinned_host→device bandwidth alone.
- ``roundtrip``: d2h then a barrier-chained h2d of the same payload —
  the serialized cost of one param's moment traffic.
- ``chain_w1`` / ``chain_w2``: an 8-block offload-pattern chain (h2d_i
  gated on h2d_{i-1} and on "update"_{i-W}; d2h_i after each tiny
  update) at window 1 (round-4 strict chain) vs window 2 (double
  buffered) — the directly decision-relevant number: if w2 beats w1,
  h2d/d2h overlap on the wire.

The offload ladder's floor: step_floor ≈ moment_bytes / chain_BW, with
moment traffic = 8 B/param EACH WAY for AdamW m+v (f32).

Usage: python tools/bench_pcie.py [--mb 256] [--blocks 8]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mb", type=int, default=256,
                    help="payload PER BLOCK, MiB")
    ap.add_argument("--blocks", type=int, default=8)
    ap.add_argument("--reps", type=int, default=3)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import SingleDeviceSharding

    from paddle_tpu.utils.bench_timing import device_time_ms, tpu_lock

    assert any(d.platform in ("tpu", "axon") for d in jax.devices()), \
        "PCIe probe needs the TPU backend (pinned_host memory)"
    dev = jax.devices()[0]
    dev_s = SingleDeviceSharding(dev, memory_kind="device")
    host_s = SingleDeviceSharding(dev, memory_kind="pinned_host")
    n = args.mb * (1 << 20) // 4

    def token(v):
        return jax.lax.convert_element_type(v.ravel()[0], jnp.float32) * 0.0

    def h2d_fn(h):
        return token(jax.device_put(h, dev_s))

    def roundtrip_fn(d):
        h = jax.device_put(d, host_s)
        # gate the return h2d on the d2h having happened
        back = jax.device_put(
            jax.lax.optimization_barrier(h), dev_s)
        return token(back)

    def chain_fn(hosts, window):
        """The _offloaded_update schedule shape over k blocks."""
        h2d_tok = jnp.zeros((), jnp.float32)
        upd_toks = []
        outs = []
        for i, h in enumerate(hosts):
            gate = h2d_tok
            if i >= window:
                gate = gate + upd_toks[i - window]
            d = jax.device_put(
                jax.lax.optimization_barrier((h, gate))[0], dev_s)
            h2d_tok = token(d)
            upd = d * 1.0001 + 1.0  # stand-in elementwise optimizer math
            upd_toks.append(token(upd))
            outs.append(jax.device_put(upd, host_s))
        return sum(upd_toks), outs

    with tpu_lock(timeout_s=900.0) as locked:
        x_host = jax.device_put(np.zeros((n,), np.float32), host_s)
        x_dev = jax.device_put(jnp.zeros((n,), jnp.float32), dev_s)
        hosts = [jax.device_put(np.full((n,), float(i), np.float32), host_s)
                 for i in range(args.blocks)]
        for a in (x_host, x_dev, *hosts):
            a.block_until_ready()

        h2d = jax.jit(h2d_fn)
        rt = jax.jit(roundtrip_fn)
        # the d2h outs MUST be jit OUTPUTS (host shardings): returning only
        # the scalar lets XLA dead-code-eliminate every d2h and the "chain"
        # measures h2d alone (r5 code-review catch — the first "full
        # duplex" rows were unsupported)
        chains = {}
        for w in (1, 2, 4):
            jitted = jax.jit(lambda hs, w=w: chain_fn(hs, w),
                             out_shardings=(dev_s, [host_s] * args.blocks))

            def run(jitted=jitted):
                s, _outs = jitted(hosts)
                return s

            chains[w] = run

        gib = args.mb / 1024.0
        res = {}
        ms = device_time_ms(lambda: h2d(x_host), reps=args.reps,
                            repeats=2, warmup=2)
        res["h2d"] = {"ms": round(ms, 2),
                      "gib_s": round(gib / (ms / 1e3), 2)}
        ms = device_time_ms(lambda: rt(x_dev), reps=args.reps,
                            repeats=2, warmup=2)
        res["roundtrip"] = {"ms": round(ms, 2),
                            "gib_s_each_way": round(2 * gib / (ms / 1e3), 2)}
        chain_gib = 2 * gib * args.blocks  # both directions, k blocks
        for w, fn in chains.items():
            ms = device_time_ms(fn, reps=args.reps, repeats=2, warmup=2)
            res[f"chain_w{w}"] = {
                "ms": round(ms, 2),
                "gib_s_total": round(chain_gib / (ms / 1e3), 2)}
    line = {"metric": "pcie_bandwidth_gib_s", "payload_mib": args.mb,
            "blocks": args.blocks, **res}
    if not locked:
        line["lock_contended"] = True
    print(json.dumps(line))


if __name__ == "__main__":
    main()
