"""Per-op microbenchmark: reference jnp paged attention vs the Pallas
kernels (ops/paged_attention_pallas.py), decode / verify / prefill, fp and
int8, across (B, M, bs) shapes.

Each combo times BOTH dispatch paths on identical inputs, checks parity
(max abs diff — the online softmax is ~1e-6 off the two-pass reference),
and reports tokens/s plus the speedup. On a TPU backend the Pallas numbers
are the real Mosaic kernels; elsewhere they run in interpret mode (slower
than the reference — the point there is parity and plumbing, not speed,
which is why the suite's perf gate only reads the speedup on hardware).

``--tp N`` additionally runs every combo under ``jax.jit`` +
``shard_map`` over an N-way "tp" mesh with the SERVING shard layout
(q/KV pools split on the head axis, int8 scales with their heads,
tables/pos replicated — parallel/serving_mesh.py's pool_spec): each
shard executes the same Pallas kernel on its head slice, exactly what
the multi-chip serving tick lowers to. The row gains ``tp_tok_s`` /
``tp_max_abs_diff`` / ``tp_parity``, and the parity gate covers the
sharded output against the unsharded reference too (attention has no
cross-head reduction, so sharding must not move the result). On CPU
(JAX_PLATFORMS=cpu) the tool forces N XLA host devices for the dryrun.

Usage:
    python tools/kernel_bench.py [--json] [--iters 10]
        [--shapes 2,4,8;4,8,16] [--window 4] [--heads 8] [--kv-heads 2]
        [--head-dim 128] [--ops decode,verify,prefill] [--quant fp,int8]
        [--tp N]

One JSON line per (op, quant, B, M, bs) combo under --json (bench.py
style); a human table otherwise.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def parse_shapes(spec):
    out = []
    for part in spec.split(";"):
        b, m, bs = (int(x) for x in part.split(","))
        out.append((b, m, bs))
    return out


def make_inputs(rng, jnp, B, M, bs, H, KV, D, W, quant):
    """Block pools + tables + pos with realistic structure: partial final
    blocks (pos mid-block), scratch block 0 on table tails."""
    import numpy as np

    N = max(B * M + 1, 2)
    pos = np.minimum(M * bs - W, np.maximum(
        0, rng.randint(bs // 2, M * bs - W + 1, (B,)))).astype(np.int32)
    tables = np.zeros((B, M), np.int32)
    free = rng.permutation(np.arange(1, N))
    took = 0
    for b in range(B):
        nblk = (pos[b] + W - 1) // bs + 1
        tables[b, :nblk] = free[took:took + nblk]
        took += nblk
    q = jnp.asarray(rng.randn(B, W, H, D).astype(np.float32))
    kv = rng.randn(2, N, bs, KV, D).astype(np.float32)
    tables = jnp.asarray(tables)
    pos = jnp.asarray(pos)
    if quant == "int8":
        from paddle_tpu.ops.paged_attention import quantize_block_kv

        kq, ks = quantize_block_kv(jnp.asarray(kv[0]))
        vq, vs = quantize_block_kv(jnp.asarray(kv[1]))
        return q, (kq, ks, vq, vs), tables, pos
    return q, (jnp.asarray(kv[0]), jnp.asarray(kv[1])), tables, pos


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shapes", default="2,4,8;4,8,16;8,16,16",
                    help="semicolon list of B,M,bs (batch, table width, "
                         "block size)")
    ap.add_argument("--window", type=int, default=4,
                    help="verify window W (decode is W=1; prefill chunk is "
                         "2 blocks)")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--ops", default="decode,verify,prefill")
    ap.add_argument("--quant", default="fp,int8")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--tp", type=int, default=1,
                    help="also run every combo sharded over an N-way "
                         "'tp' mesh (shard_map, serving shard layout) "
                         "and gate parity vs the unsharded reference")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    if args.tp > 1:
        if args.heads % args.tp or args.kv_heads % args.tp:
            ap.error("--tp must divide --heads and --kv-heads (the mesh "
                     "shards the head axis)")
        if os.environ.get("JAX_PLATFORMS", "") == "cpu" \
                and "xla_force_host_platform_device_count" \
                not in os.environ.get("XLA_FLAGS", ""):
            # CPU dryrun mesh needs tp host devices; only effective
            # before the jax import below
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count"
                  f"={args.tp}").strip()

    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu import ops
    from paddle_tpu.ops import paged_attention as pa
    from paddle_tpu.utils.bench_timing import tpu_lock

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")

    mesh = None
    if args.tp > 1:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        if len(jax.devices()) < args.tp:
            sys.exit(f"--tp {args.tp} needs {args.tp} devices, have "
                     f"{len(jax.devices())}")
        mesh = Mesh(np.array(jax.devices()[:args.tp]), ("tp",))
        # the serving shard layout (parallel/serving_mesh.pool_spec):
        # 4-D pool/q tensors split on the kv-/q-head axis, 2-D int8
        # scale tensors with their heads, block tables and positions
        # replicated
        _HEADS = P(None, None, "tp", None)
        _SCALES = P(None, "tp")

        def tp_specs(op, quant):
            if quant == "int8":
                pool = (_HEADS, _SCALES, _HEADS, _SCALES)
            else:
                pool = (_HEADS, _HEADS)
            if op == "prefill":                  # (q, *pools, table)
                return (_HEADS, *pool, P())
            return (_HEADS, *pool, P(), P())     # (q, *pools, tables, pos)

    def timed(fn, fn_args):
        # fresh lambda: jax's tracing cache is keyed on function identity,
        # so re-jitting `fn` itself after a kernel-mode flip would silently
        # reuse the other mode's jaxpr
        jf = jax.jit(lambda *a: fn(*a))
        out = jf(*fn_args)
        out.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = jf(*fn_args)
        out.block_until_ready()
        return (time.perf_counter() - t0) / args.iters, out

    rows = []
    with tpu_lock(timeout_s=900.0) as locked:
        for B, M, bs in parse_shapes(args.shapes):
            for quant in args.quant.split(","):
                rng = np.random.RandomState(0)
                for op in args.ops.split(","):
                    W = {"decode": 1, "verify": args.window,
                         "prefill": 2 * bs}[op]
                    if op == "prefill":
                        # prefill is the verify kernel at B=1, W=chunk
                        q, pools, tables, pos = make_inputs(
                            rng, jnp, 1, M, bs, args.heads, args.kv_heads,
                            args.head_dim, W, quant)
                        tbl, start = tables[0], int(pos[0]) // bs * bs
                        if quant == "int8":
                            fn = lambda qq, kq, ks, vq, vs, t: \
                                pa.paged_prefill_attention_q(
                                    qq, kq, ks, vq, vs, t, start)
                        else:
                            fn = lambda qq, kp, vp, t: \
                                pa.paged_prefill_attention(
                                    qq, kp, vp, t, start)
                        fn_args = (q, *pools, tbl)
                        tok = W
                    else:
                        q, pools, tables, pos = make_inputs(
                            rng, jnp, B, M, bs, args.heads, args.kv_heads,
                            args.head_dim, W, quant)
                        fn = (pa.paged_verify_attention_q if quant == "int8"
                              else pa.paged_verify_attention)
                        fn_args = (q, *pools, tables, pos)
                        tok = B * W
                    mode = ops.kernel_mode()
                    tp_s, tp_out = None, None
                    try:
                        ops.set_kernel_mode("reference")
                        ref_s, ref_out = timed(fn, fn_args)
                        ops.set_kernel_mode("pallas")
                        pal_s, pal_out = timed(fn, fn_args)
                        if mesh is not None:
                            # same kernel, per-shard head slices: jit a
                            # fresh shard_map lambda (cache is keyed on
                            # function identity — see timed) over
                            # explicitly sharded inputs so the GSPMD
                            # lowering is what gets measured
                            specs = tp_specs(op, quant)
                            sfn = shard_map(fn, mesh=mesh,
                                            in_specs=specs,
                                            out_specs=_HEADS,
                                            check_rep=False)
                            sargs = tuple(
                                jax.device_put(a, NamedSharding(mesh, s))
                                for a, s in zip(fn_args, specs))
                            tp_s, tp_out = timed(sfn, sargs)
                    finally:
                        ops.set_kernel_mode(mode)
                    diff = float(jnp.max(jnp.abs(
                        ref_out.astype(jnp.float32) -
                        pal_out.astype(jnp.float32))))
                    rows.append({
                        "metric": f"paged_{op}_kernel_tok_s",
                        "op": op, "quant": quant,
                        "B": B, "M": M, "bs": bs, "W": W,
                        "heads": args.heads, "kv_heads": args.kv_heads,
                        "head_dim": args.head_dim,
                        "backend": backend,
                        "pallas_mode": "mosaic" if on_tpu else "interpret",
                        "ref_tok_s": round(tok / ref_s, 1),
                        "pallas_tok_s": round(tok / pal_s, 1),
                        "speedup": round(ref_s / pal_s, 3),
                        "max_abs_diff": diff,
                        "parity": diff < 2e-5,
                    })
                    if tp_out is not None:
                        tp_diff = float(jnp.max(jnp.abs(
                            ref_out.astype(jnp.float32) -
                            tp_out.astype(jnp.float32))))
                        rows[-1].update({
                            "tp": args.tp,
                            "tp_tok_s": round(tok / tp_s, 1),
                            "tp_max_abs_diff": tp_diff,
                            "tp_parity": tp_diff < 2e-5,
                        })
        if not locked:
            for r in rows:
                r["lock_contended"] = True

    ok = all(r["parity"] and r.get("tp_parity", True) for r in rows)
    if args.json:
        for r in rows:
            print(json.dumps(r))
    else:
        hdr = (f"{'op':8} {'quant':5} {'B':>3} {'M':>3} {'bs':>3} "
               f"{'ref tok/s':>12} {'pallas tok/s':>13} {'speedup':>8} "
               f"{'max|diff|':>10}")
        print(hdr)
        print("-" * len(hdr))
        for r in rows:
            print(f"{r['op']:8} {r['quant']:5} {r['B']:>3} {r['M']:>3} "
                  f"{r['bs']:>3} {r['ref_tok_s']:>12} "
                  f"{r['pallas_tok_s']:>13} {r['speedup']:>8} "
                  f"{r['max_abs_diff']:>10.2e}")
        print(f"\nbackend={backend} "
              f"({'mosaic' if on_tpu else 'interpret'} pallas), "
              f"parity={'OK' if ok else 'FAIL'}")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
