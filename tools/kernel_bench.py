"""Per-op microbenchmark: reference jnp paged attention vs the Pallas
kernels (ops/paged_attention_pallas.py), decode / verify / prefill, fp and
int8, across (B, M, bs) shapes.

Each combo times BOTH dispatch paths on identical inputs, checks parity
(max abs diff — the online softmax is ~1e-6 off the two-pass reference),
and reports tokens/s plus the speedup. On a TPU backend the Pallas numbers
are the real Mosaic kernels; elsewhere they run in interpret mode (slower
than the reference — the point there is parity and plumbing, not speed,
which is why the suite's perf gate only reads the speedup on hardware).

``--tp N`` additionally runs every combo under ``jax.jit`` +
``shard_map`` over an N-way "tp" mesh with the SERVING shard layout
(q/KV pools split on the head axis, int8 scales with their heads,
tables/pos replicated — parallel/serving_mesh.py's pool_spec): each
shard executes the same Pallas kernel on its head slice, exactly what
the multi-chip serving tick lowers to. The row gains ``tp_tok_s`` /
``tp_max_abs_diff`` / ``tp_parity``, and the parity gate covers the
sharded output against the unsharded reference too (attention has no
cross-head reduction, so sharding must not move the result). On CPU
(JAX_PLATFORMS=cpu) the tool forces N XLA host devices for the dryrun.

``--ops tick`` adds the WHOLE-TICK row: a full decode trip (embed +
every layer's attention + FFN) through three dispatch paths on one tiny
Llama built from the head geometry — the reference jnp layer loop, the
per-layer Pallas loop, and the ``ops/decode_megakernel.py`` persistent
program — fp and int8, with and without LoRA. Each path reports tok/s
AND ``*_dispatch_us`` (host time to ISSUE the jitted call, before
blocking — the megakernel's whole premise is collapsing per-layer
dispatches into one program launch), plus ``hbm_bytes_megakernel`` /
``hbm_bytes_layered`` per-trip traffic estimates from
``hbm_bytes_per_trip`` and a token-level parity gate across all three.
When the eager guard rejects the geometry the row carries
``megakernel_active: false`` with the reason and still benches the
other two rungs — the ladder degrading is a result, not an error.

Usage:
    python tools/kernel_bench.py [--json] [--iters 10]
        [--shapes 2,4,8;4,8,16] [--window 4] [--heads 8] [--kv-heads 2]
        [--head-dim 128] [--layers 2] [--ops decode,verify,prefill,tick]
        [--quant fp,int8] [--tp N]

One JSON line per (op, quant, B, M, bs) combo under --json (bench.py
style); a human table otherwise.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def parse_shapes(spec):
    out = []
    for part in spec.split(";"):
        b, m, bs = (int(x) for x in part.split(","))
        out.append((b, m, bs))
    return out


def make_inputs(rng, jnp, B, M, bs, H, KV, D, W, quant):
    """Block pools + tables + pos with realistic structure: partial final
    blocks (pos mid-block), scratch block 0 on table tails."""
    import numpy as np

    N = max(B * M + 1, 2)
    pos = np.minimum(M * bs - W, np.maximum(
        0, rng.randint(bs // 2, M * bs - W + 1, (B,)))).astype(np.int32)
    tables = np.zeros((B, M), np.int32)
    free = rng.permutation(np.arange(1, N))
    took = 0
    for b in range(B):
        nblk = (pos[b] + W - 1) // bs + 1
        tables[b, :nblk] = free[took:took + nblk]
        took += nblk
    q = jnp.asarray(rng.randn(B, W, H, D).astype(np.float32))
    kv = rng.randn(2, N, bs, KV, D).astype(np.float32)
    tables = jnp.asarray(tables)
    pos = jnp.asarray(pos)
    if quant == "int8":
        from paddle_tpu.ops.paged_attention import quantize_block_kv

        kq, ks = quantize_block_kv(jnp.asarray(kv[0]))
        vq, vs = quantize_block_kv(jnp.asarray(kv[1]))
        return q, (kq, ks, vq, vs), tables, pos
    return q, (jnp.asarray(kv[0]), jnp.asarray(kv[1])), tables, pos


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shapes", default="2,4,8;4,8,16;8,16,16",
                    help="semicolon list of B,M,bs (batch, table width, "
                         "block size)")
    ap.add_argument("--window", type=int, default=4,
                    help="verify window W (decode is W=1; prefill chunk is "
                         "2 blocks)")
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=2)
    ap.add_argument("--head-dim", type=int, default=128)
    ap.add_argument("--ops", default="decode,verify,prefill",
                    help="comma list of decode,verify,prefill,tick "
                         "(tick = whole-trip megakernel row, opt-in)")
    ap.add_argument("--layers", type=int, default=2,
                    help="decoder layers for the whole-tick row")
    ap.add_argument("--quant", default="fp,int8")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--tp", type=int, default=1,
                    help="also run every combo sharded over an N-way "
                         "'tp' mesh (shard_map, serving shard layout) "
                         "and gate parity vs the unsharded reference")
    ap.add_argument("--sweep-geometry", action="store_true",
                    help="per-op kernel-geometry tier: sweep the "
                         "bit-exact schedule candidates on every paged "
                         "row (plus one rung per fused-op family), "
                         "hard-reject parity mismatches, report each "
                         "row's winner + speedup vs default, and collect "
                         "winners into a GeometryCache (--emit-cache)")
    ap.add_argument("--seed", type=int, default=0,
                    help="input rng seed (sweeps under --clock counting "
                         "are byte-reproducible per seed)")
    ap.add_argument("--clock", default="real",
                    choices=("real", "counting"),
                    help="counting = deterministic injectable clock "
                         "(autotuner discipline): timings count calls, "
                         "so two runs at the same seed are byte-identical")
    ap.add_argument("--emit-cache", default=None, metavar="PATH",
                    help="write the swept GeometryCache JSON (the "
                         "artifact TunedProfile v3 / serving_benchmark "
                         "--geometry-cache consume)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    if args.emit_cache and not args.sweep_geometry:
        ap.error("--emit-cache requires --sweep-geometry")
    if args.tp > 1:
        if args.heads % args.tp or args.kv_heads % args.tp:
            ap.error("--tp must divide --heads and --kv-heads (the mesh "
                     "shards the head axis)")
        if os.environ.get("JAX_PLATFORMS", "") == "cpu" \
                and "xla_force_host_platform_device_count" \
                not in os.environ.get("XLA_FLAGS", ""):
            # CPU dryrun mesh needs tp host devices; only effective
            # before the jax import below
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count"
                  f"={args.tp}").strip()

    import numpy as np
    import jax
    import jax.numpy as jnp

    from paddle_tpu import ops
    from paddle_tpu.autotune.kernel_geometry import resolve_geometry
    from paddle_tpu.ops import paged_attention as pa
    from paddle_tpu.utils.bench_timing import tpu_lock

    backend = jax.default_backend()
    on_tpu = backend in ("tpu", "axon")

    mesh = None
    if args.tp > 1:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        if len(jax.devices()) < args.tp:
            sys.exit(f"--tp {args.tp} needs {args.tp} devices, have "
                     f"{len(jax.devices())}")
        mesh = Mesh(np.array(jax.devices()[:args.tp]), ("tp",))
        # the serving shard layout (parallel/serving_mesh.pool_spec):
        # 4-D pool/q tensors split on the kv-/q-head axis, 2-D int8
        # scale tensors with their heads, block tables and positions
        # replicated
        _HEADS = P(None, None, "tp", None)
        _SCALES = P(None, "tp")

        def tp_specs(op, quant):
            if quant == "int8":
                pool = (_HEADS, _SCALES, _HEADS, _SCALES)
            else:
                pool = (_HEADS, _HEADS)
            if op == "prefill":                  # (q, *pools, table)
                return (_HEADS, *pool, P())
            return (_HEADS, *pool, P(), P())     # (q, *pools, tables, pos)

    if args.clock == "counting":
        # injectable counting clock (GL012 discipline, same as the
        # autotuner's TrialRunner): every read advances by one, so a
        # "duration" is a call count — two sweeps at one seed produce
        # byte-identical rows and winner tables
        _count = [0.0]

        def clk():
            _count[0] += 1.0
            return _count[0]
    else:
        clk = time.perf_counter

    def timed(fn, fn_args):
        # fresh lambda: jax's tracing cache is keyed on function identity,
        # so re-jitting `fn` itself after a kernel-mode flip (any rung of
        # the auto/pallas/megakernel/reference enum) OR a kernel-geometry
        # re-bind (installing a different winner cache is invisible to the
        # cache key, exactly like the mode flag) would silently reuse the
        # other configuration's jaxpr
        jf = jax.jit(lambda *a: fn(*a))
        out = jf(*fn_args)
        out.block_until_ready()
        t0 = clk()
        for _ in range(args.iters):
            out = jf(*fn_args)
        out.block_until_ready()
        return (clk() - t0) / args.iters, out

    def timed_tick(fn, fn_args):
        # like timed(), but also splits out the host-side ISSUE time of
        # each call (returns before the device finishes) — the dispatch
        # overhead the megakernel collapses
        jf = jax.jit(lambda *a: fn(*a))
        out = jf(*fn_args)
        out.block_until_ready()
        disp = 0.0
        t0 = clk()
        for _ in range(args.iters):
            t1 = clk()
            out = jf(*fn_args)
            disp += clk() - t1
            out.block_until_ready()
        total = clk() - t0
        return total / args.iters, disp / args.iters, out

    # ---------------------------------------------- kernel-geometry tier
    sweep_cache = None
    if args.sweep_geometry:
        from paddle_tpu.autotune import GeometryCache
        from paddle_tpu.autotune.kernel_geometry import local_device_kind

        sweep_cache = GeometryCache()
        device_kind = local_device_kind()

    def run_sweep(measure, op, dtype, key, **kw):
        """One deterministic sweep rung: measure every candidate under a
        fresh jit (geometry re-binds MUST re-trace — see timed), bitwise
        parity-gate vs the default's output, cache the winner."""
        from paddle_tpu.autotune import sweep_kernel_geometry

        return sweep_kernel_geometry(measure, op, dtype=dtype, key=key,
                                     device_kind=device_kind,
                                     cache=sweep_cache, **kw)

    def installed_measure(fn, fn_args, op, dtype, key):
        """measure() for ops whose geometry rides the process-wide seam
        (paged attention, flash): install a one-entry cache, fresh-jit,
        restore. The restore matters — the sweep must not leak its last
        candidate into the next row's timing."""
        from paddle_tpu.autotune import GeometryCache, install_geometry_cache
        from paddle_tpu.autotune.kernel_geometry import (
            active_geometry_cache, active_geometry_source)

        def measure(geom):
            prev, prev_src = active_geometry_cache(), \
                active_geometry_source()
            c = GeometryCache()
            c.put(op, dtype, key, device_kind, geom)
            install_geometry_cache(c, "swept")
            try:
                secs, out = timed(fn, fn_args)
            finally:
                install_geometry_cache(
                    prev, prev_src if prev is not None else "swept")
            return np.asarray(out), secs
        return measure

    def sweep_summary(res):
        return {
            "winner_geometry": res.winner,
            "geometry_speedup": round(res.speedup, 3),
            "geometry_candidates": len(res.trials),
            "geometry_parity_rejects": sum(
                1 for t in res.trials if not t.accepted),
        }

    def family_sweep_rows():
        """One sweep rung per fused-op family (fp, fixed microbench
        shapes) — the per-op tier beyond the paged rows. The LoRA/norm/
        CE candidates are bit-exact by design, so a parity reject there
        fails the run like a paged parity failure would; flash block_q
        is row-independent but its BITWISE equality is backend-dependent
        (host BLAS may regroup the contraction by tile shape), so flash
        rejects are a graceful result — the reject count is reported and
        the rejected schedule simply never wins the cell."""
        from paddle_tpu.autotune.kernel_geometry import geometry_candidates
        from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy
        from paddle_tpu.ops.fused_norm import _rms_pallas
        from paddle_tpu.ops.paged_attention_pallas import fused_lora_matmul
        from paddle_tpu.ops.flash_attention import flash_attention

        rng = np.random.RandomState(args.seed)
        out_rows = []

        def add_row(fam, key, res):
            strict = fam != "flash_attention"   # see docstring above
            out_rows.append({
                "metric": "geometry_sweep", "op": fam, "quant": "fp",
                "dtype": "float32", "key": key, "backend": backend,
                "pallas_mode": "mosaic" if on_tpu else "interpret",
                "parity": (all(t.accepted for t in res.trials) if strict
                           else res.trials[res.winner_index].exact),
                **sweep_summary(res)})

        mode = ops.kernel_mode()
        try:
            ops.set_kernel_mode("pallas")
            # fused LoRA: geometry is a direct trace-time argument
            B, S, IN, OUT, R = 2, 8, 256, 256, 8
            x = jnp.asarray(rng.randn(B, S, IN).astype(np.float32))
            w = jnp.asarray(rng.randn(IN, OUT).astype(np.float32) * 0.05)
            a = jnp.asarray(rng.randn(B, IN, R).astype(np.float32) * 0.05)
            b = jnp.asarray(rng.randn(B, R, OUT).astype(np.float32) * 0.05)
            s = jnp.asarray(np.array([0.5, 0.0], np.float32))

            def lora_measure(geom):
                secs, out = timed(
                    lambda *t: fused_lora_matmul(*t, geometry=geom),
                    (x, w, a, b, s))
                return np.asarray(out), secs

            add_row("fused_lora", R, run_sweep(
                lora_measure, "fused_lora", "float32", R,
                shape={"seq": S, "in_dim": IN, "out_dim": OUT, "rank": R}))

            # fused norm: direct geometry, interpret off-TPU
            xr = jnp.asarray(rng.randn(256, 512).astype(np.float32))
            wr = jnp.asarray(rng.randn(512).astype(np.float32))

            def norm_measure(geom):
                secs, out = timed(
                    lambda *t: _rms_pallas(*t, 1e-6, geometry=geom,
                                           interpret=not on_tpu),
                    (xr, wr))
                return np.asarray(out), secs

            add_row("fused_norm", 512, run_sweep(
                norm_measure, "fused_norm", "float32", 512,
                shape={"rows_total": 256, "width": 512}))

            # fused CE: jnp composition, geometry sub-tiles the forward
            h = jnp.asarray(rng.randn(64, 128).astype(np.float32))
            wv = jnp.asarray(rng.randn(128, 512).astype(np.float32) * 0.1)
            lab = jnp.asarray(rng.randint(0, 512, (64,)).astype(np.int32))

            def ce_measure(geom):
                secs, out = timed(
                    lambda *t: fused_linear_cross_entropy(
                        *t, chunk_size=32, geometry=geom),
                    (h, wv, lab))
                return np.asarray(out), secs

            add_row("fused_ce", 128, run_sweep(
                ce_measure, "fused_ce", "float32", 128,
                shape={"rows_total": 64, "hidden": 128, "vocab": 512}))

            # flash attention: rides the seam like paged attention;
            # block_kv stays excluded from candidates (not parity-exact)
            D = args.head_dim
            qf = jnp.asarray(rng.randn(1, 2, 256, D).astype(np.float32))
            kf = jnp.asarray(rng.randn(1, 2, 256, D).astype(np.float32))
            vf = jnp.asarray(rng.randn(1, 2, 256, D).astype(np.float32))
            add_row("flash_attention", D, run_sweep(
                installed_measure(
                    lambda *t: flash_attention(*t, causal=True),
                    (qf, kf, vf), "flash_attention", "float32", D),
                "flash_attention", "float32", D,
                shape={"head_dim": D, "seq_q": 256, "seq_k": 256}))
        finally:
            ops.set_kernel_mode(mode)
        return out_rows

    def bench_tick(B, M, bs, quant, lora_on):
        """Whole decode trip (W=1): embed + all layers, three rungs."""
        import paddle_tpu as paddle
        from paddle_tpu.framework.core import Tensor
        from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.ops import decode_megakernel as mk

        H, KV, D, L = args.heads, args.kv_heads, args.head_dim, args.layers
        cfg = LlamaConfig(
            vocab_size=128, hidden_size=H * D, intermediate_size=2 * H * D,
            num_hidden_layers=L, num_attention_heads=H,
            num_key_value_heads=KV, max_position_embeddings=M * bs + 8,
            dtype="float32", use_flash_attention=False)
        paddle.seed(7)
        model = LlamaForCausalLM(cfg)
        m = model.model
        W = 1
        rng = np.random.RandomState(args.seed)
        _, _, tables, pos = make_inputs(rng, jnp, B, M, bs, H, KV, D, W,
                                        "fp")
        N = max(B * M + 1, 2)
        st = 4 if quant == "int8" else 2
        flat = []
        for _ in range(L):
            for kvp in range(2):
                p = rng.randn(N, bs, KV, D).astype(np.float32) * 0.5
                p[0] = 0.0
                if quant == "int8":
                    pq, ps = pa.quantize_block_kv(jnp.asarray(p))
                    flat += [pq, ps]
                else:
                    flat.append(jnp.asarray(p))
        tokens = jnp.asarray(
            rng.randint(1, cfg.vocab_size, (B, W)).astype(np.int32))
        lora = None
        if lora_on:
            Hd, KVD, I = H * D, KV * D, 2 * H * D
            dims = {"q": (Hd, Hd), "k": (Hd, KVD), "v": (Hd, KVD),
                    "o": (Hd, Hd), "gate": (Hd, I), "up": (Hd, I),
                    "down": (I, Hd)}
            scale = jnp.asarray(
                [0.5 if b % 2 == 0 else 0.0 for b in range(B)], jnp.float32)
            lora = []
            for _ in range(L):
                lora.append({t: (
                    jnp.asarray(rng.normal(0, 0.05, (B, fi, 4)),
                                jnp.float32),
                    jnp.asarray(rng.normal(0, 0.05, (B, 4, fo)),
                                jnp.float32),
                    scale) for t, (fi, fo) in dims.items()})

        def layered(tok, tbl, ps, *fl):
            x = m.embed_tokens(Tensor(tok))
            for i, layer in enumerate(m.layers):
                pool = tuple(Tensor(fl[st * i + j]) for j in range(st))
                x, _ = layer.paged_verify(
                    x, m._cos, m._sin, pool, tbl, ps,
                    lora=None if lora is None else lora[i])
            return x.value

        stk_w = mk.stack_layer_weights(model)
        stk_l = mk.stack_lora(lora)

        def megakernel(tok, tbl, ps, *fl):
            x = m.embed_tokens(Tensor(tok)).value
            cosr, sinr = mk.gather_rope_rows(m._cos, m._sin, ps, W)
            xo, _ = mk.decode_tick(x, list(fl), tbl, ps, stk_w, cosr,
                                   sinr, block_size=bs,
                                   eps=cfg.rms_norm_eps, lora=stk_l)
            return xo

        fn_args = (tokens, tables, pos, *flat)
        mode = ops.kernel_mode()
        mk_s = mk_disp = mk_out = None
        try:
            ops.set_kernel_mode("reference")
            ref_s, ref_disp, ref_out = timed_tick(layered, fn_args)
            ops.set_kernel_mode("pallas")
            pal_s, pal_disp, pal_out = timed_tick(layered, fn_args)
            # guard under megakernel mode — interpret-vs-Mosaic shape
            # rules depend on the active mode, exactly as at executor
            # construction
            ops.set_kernel_mode("megakernel")
            reason = mk.megakernel_supported(model, cfg, block_size=bs,
                                             lora=lora_on)
            if reason is None:
                mk_s, mk_disp, mk_out = timed_tick(megakernel, fn_args)
        finally:
            ops.set_kernel_mode(mode)
        tok = B * W
        ref32 = ref_out.astype(jnp.float32)
        diff = float(jnp.max(jnp.abs(ref32 - pal_out.astype(jnp.float32))))
        acb = float(np.mean((np.asarray(pos) + W - 1) // bs + 1))
        kvq = "int8" if quant == "int8" else "none"
        row = {
            "metric": "whole_tick_tok_s",
            "op": "tick", "quant": quant, "lora": lora_on,
            "B": B, "M": M, "bs": bs, "W": W, "layers": L,
            "heads": H, "kv_heads": KV, "head_dim": D,
            "backend": backend,
            "pallas_mode": "mosaic" if on_tpu else "interpret",
            "ref_tok_s": round(tok / ref_s, 1),
            "pallas_tok_s": round(tok / pal_s, 1),
            "speedup": round(ref_s / pal_s, 3),
            "max_abs_diff": diff,
            "ref_dispatch_us": round(ref_disp * 1e6, 1),
            "pallas_dispatch_us": round(pal_disp * 1e6, 1),
            "megakernel_active": reason is None,
            "hbm_bytes_megakernel": mk.hbm_bytes_per_trip(
                cfg, batch=B, window=W, block_size=bs, avg_ctx_blocks=acb,
                kv_quant=kvq, megakernel=True),
            "hbm_bytes_layered": mk.hbm_bytes_per_trip(
                cfg, batch=B, window=W, block_size=bs, avg_ctx_blocks=acb,
                kv_quant=kvq, megakernel=False),
        }
        if reason is None:
            mk_diff = float(jnp.max(jnp.abs(
                ref32 - mk_out.astype(jnp.float32))))
            diff = max(diff, mk_diff)
            row.update({
                "megakernel_tok_s": round(tok / mk_s, 1),
                "tick_dispatch_us": round(mk_disp * 1e6, 1),
                "mk_speedup": round(ref_s / mk_s, 3),
                "mk_max_abs_diff": mk_diff,
            })
        else:
            row["megakernel_reason"] = reason
        row["parity"] = diff < 2e-4
        return row

    rows = []
    with tpu_lock(timeout_s=900.0) as locked:
        for B, M, bs in parse_shapes(args.shapes):
            for quant in args.quant.split(","):
                rng = np.random.RandomState(args.seed)
                for op in args.ops.split(","):
                    if op == "tick":
                        for lora_on in (False, True):
                            rows.append(
                                bench_tick(B, M, bs, quant, lora_on))
                        continue
                    W = {"decode": 1, "verify": args.window,
                         "prefill": 2 * bs}[op]
                    if op == "prefill":
                        # prefill is the verify kernel at B=1, W=chunk
                        q, pools, tables, pos = make_inputs(
                            rng, jnp, 1, M, bs, args.heads, args.kv_heads,
                            args.head_dim, W, quant)
                        tbl, start = tables[0], int(pos[0]) // bs * bs
                        if quant == "int8":
                            fn = lambda qq, kq, ks, vq, vs, t: \
                                pa.paged_prefill_attention_q(
                                    qq, kq, ks, vq, vs, t, start)
                        else:
                            fn = lambda qq, kp, vp, t: \
                                pa.paged_prefill_attention(
                                    qq, kp, vp, t, start)
                        fn_args = (q, *pools, tbl)
                        tok = W
                    else:
                        q, pools, tables, pos = make_inputs(
                            rng, jnp, B, M, bs, args.heads, args.kv_heads,
                            args.head_dim, W, quant)
                        fn = (pa.paged_verify_attention_q if quant == "int8"
                              else pa.paged_verify_attention)
                        fn_args = (q, *pools, tables, pos)
                        tok = B * W
                    mode = ops.kernel_mode()
                    tp_s, tp_out = None, None
                    sweep_res = None
                    try:
                        ops.set_kernel_mode("reference")
                        ref_s, ref_out = timed(fn, fn_args)
                        ops.set_kernel_mode("pallas")
                        pal_s, pal_out = timed(fn, fn_args)
                        if args.sweep_geometry:
                            pa_dtype = ("int8" if quant == "int8"
                                        else "float32")
                            sweep_res = run_sweep(
                                installed_measure(
                                    fn, fn_args, "paged_attention",
                                    pa_dtype, args.head_dim),
                                "paged_attention", pa_dtype,
                                args.head_dim,
                                quantized=quant == "int8",
                                shape={"head_dim": args.head_dim,
                                       "block_size": bs, "window": W,
                                       "rep": args.heads // args.kv_heads,
                                       "blocks": M})
                        if mesh is not None:
                            # same kernel, per-shard head slices: jit a
                            # fresh shard_map lambda (cache is keyed on
                            # function identity — see timed) over
                            # explicitly sharded inputs so the GSPMD
                            # lowering is what gets measured
                            specs = tp_specs(op, quant)
                            sfn = shard_map(fn, mesh=mesh,
                                            in_specs=specs,
                                            out_specs=_HEADS,
                                            check_rep=False)
                            sargs = tuple(
                                jax.device_put(a, NamedSharding(mesh, s))
                                for a, s in zip(fn_args, specs))
                            tp_s, tp_out = timed(sfn, sargs)
                    finally:
                        ops.set_kernel_mode(mode)
                    diff = float(jnp.max(jnp.abs(
                        ref_out.astype(jnp.float32) -
                        pal_out.astype(jnp.float32))))
                    rows.append({
                        "metric": f"paged_{op}_kernel_tok_s",
                        "op": op, "quant": quant,
                        "B": B, "M": M, "bs": bs, "W": W,
                        "heads": args.heads, "kv_heads": args.kv_heads,
                        "head_dim": args.head_dim,
                        "backend": backend,
                        "pallas_mode": "mosaic" if on_tpu else "interpret",
                        "ref_tok_s": round(tok / ref_s, 1),
                        "pallas_tok_s": round(tok / pal_s, 1),
                        "speedup": round(ref_s / pal_s, 3),
                        "max_abs_diff": diff,
                        "parity": diff < 2e-5,
                    })
                    # which schedule the pallas timing above actually
                    # ran (the trace-time resolution, not a guess)
                    g_act, g_src = resolve_geometry(
                        "paged_attention",
                        "int8" if quant == "int8" else "float32",
                        args.head_dim)
                    rows[-1]["geometry"] = g_act.asdict()
                    rows[-1]["geometry_source"] = g_src
                    if sweep_res is not None:
                        rows[-1]["parity"] = bool(
                            rows[-1]["parity"] and all(
                                t.accepted for t in sweep_res.trials))
                        rows[-1].update(sweep_summary(sweep_res))
                    if tp_out is not None:
                        tp_diff = float(jnp.max(jnp.abs(
                            ref_out.astype(jnp.float32) -
                            tp_out.astype(jnp.float32))))
                        rows[-1].update({
                            "tp": args.tp,
                            "tp_tok_s": round(tok / tp_s, 1),
                            "tp_max_abs_diff": tp_diff,
                            "tp_parity": tp_diff < 2e-5,
                        })
        if args.sweep_geometry:
            rows += family_sweep_rows()
        if not locked:
            for r in rows:
                r["lock_contended"] = True

    ok = all(r["parity"] and r.get("tp_parity", True) for r in rows)
    if args.json:
        for r in rows:
            print(json.dumps(r))
    else:
        hdr = (f"{'op':8} {'quant':5} {'B':>3} {'M':>3} {'bs':>3} "
               f"{'ref tok/s':>12} {'pallas tok/s':>13} {'speedup':>8} "
               f"{'max|diff|':>10}")
        print(hdr)
        print("-" * len(hdr))
        for r in rows:
            if r["metric"] == "geometry_sweep":
                print(f"{r['op']:8} sweep  winner="
                      f"{json.dumps(r['winner_geometry'], sort_keys=True)} "
                      f"x{r['geometry_speedup']} "
                      f"({r['geometry_candidates']} candidates, "
                      f"{r['geometry_parity_rejects']} parity rejects)")
                continue
            line = (f"{r['op']:8} {r['quant']:5} {r['B']:>3} {r['M']:>3} "
                    f"{r['bs']:>3} {r['ref_tok_s']:>12} "
                    f"{r['pallas_tok_s']:>13} {r['speedup']:>8} "
                    f"{r['max_abs_diff']:>10.2e}")
            if "winner_geometry" in r:
                line += (f"  winner="
                         f"{json.dumps(r['winner_geometry'], sort_keys=True)}"
                         f" x{r['geometry_speedup']}")
            print(line)
        print(f"\nbackend={backend} "
              f"({'mosaic' if on_tpu else 'interpret'} pallas), "
              f"parity={'OK' if ok else 'FAIL'}")
    if args.emit_cache:
        with open(args.emit_cache, "w") as f:
            json.dump(sweep_cache.to_dict(), f, sort_keys=True, indent=2)
            f.write("\n")
        if not args.json:
            print(f"geometry cache ({len(sweep_cache)} entries) -> "
                  f"{args.emit_cache}")
    if not ok:
        sys.exit(1)


if __name__ == "__main__":
    main()
