#!/usr/bin/env python
"""Training chaos-twin gate: seeded kills, torn writes and bit-flipped
reads against an unkilled fault-free twin.

Drives the elastic chaos harness
(``paddle_tpu/distributed/fleet/chaos.py``) through a
``FaultPlan.train_chaos`` script over a small-but-real
``ParallelEngine`` run with a complete-state ``TrainCheckpointer``, then
replays the same trajectory with no faults and compares:

- every step loss recorded by the chaos run (including replayed steps
  after each restart) must equal the twin's loss at that step bit-for-bit;
- the final params/opt-state must be byte-identical to the twin's;
- every injected on-disk corruption must have been DETECTED by the CRC32
  manifest (``train_checkpoint_corrupt_reads`` >= ``ckpt_read`` firings)
  and absorbed by generation fallback — zero undetected corruptions;
- every torn write must have been absorbed by the retry rung
  (``save_retries``/``save_failures`` accounting).

Suite stage 8 (``tools/run_tpu_suite.sh``) runs this with ``--json`` and
asserts on the emitted line; it is CPU-runnable too (the same command
under ``JAX_PLATFORMS=cpu``) so the gate also rides the quick tier via
``tests/test_train_checkpoint.py``.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def build_factories(args):
    import paddle_tpu as paddle
    from paddle_tpu import nn, optimizer
    from paddle_tpu.parallel.engine import ParallelEngine

    def make_model():
        paddle.seed(args.model_seed)
        m = nn.Sequential(nn.Linear(args.width, args.width),
                          nn.ReLU(), nn.Linear(args.width, 4))
        o = optimizer.AdamW(learning_rate=0.01, parameters=m.parameters())
        return m, o

    def make_batch(cursor):
        rng = np.random.RandomState(args.data_seed + cursor)
        return (rng.randn(args.batch, args.width).astype("float32"),
                rng.randn(args.batch, 4).astype("float32"))

    def make_engine(injector=None, telemetry=None):
        m, o = make_model()
        return ParallelEngine(m, o, loss_fn=nn.functional.mse_loss,
                              donate=False, injector=injector,
                              telemetry=telemetry)

    return make_engine, make_batch


class ChaosTrainRun:
    """One incarnation: fresh engine + feed + shared-dir checkpointer.

    ``step`` owns the train_step retry (same batch — the feed cursor
    must NOT re-advance on a dispatch-side fault, or the resumed stream
    diverges); the harness owns the data_feed retry (fires before the
    cursor moves, so a re-fetch is identical).
    """

    def __init__(self, injector, ckpt_dir, metrics, make_engine, make_batch,
                 save_every=1, telemetry=None):
        import paddle_tpu as paddle
        from paddle_tpu.distributed.train_checkpoint import (
            CheckpointableDataFeed, TrainCheckpointer)

        self._paddle = paddle
        self.eng = make_engine(injector, telemetry=telemetry)
        self.feed = CheckpointableDataFeed(make_batch, injector=injector,
                                           telemetry=telemetry)
        self.ck = TrainCheckpointer(ckpt_dir, injector=injector,
                                    metrics=metrics, save_retries=2,
                                    backoff_s=0.01, telemetry=telemetry)
        self.save_every = save_every

    def restore(self) -> int:
        host = self.ck.restore(engine=self.eng, data_feed=self.feed)
        return (host["step"] + 1) if host else 0

    def step(self, i: int) -> float:
        from paddle_tpu.faults import StepFault

        X, y = self.feed.next_batch()
        for attempt in range(4):
            try:
                loss = self.eng.train_batch(self._paddle.to_tensor(X),
                                            self._paddle.to_tensor(y))
                return float(np.asarray(loss.value))
            except StepFault:
                if attempt == 3:
                    raise
        raise AssertionError("unreachable")

    def save(self, i: int) -> None:
        if (i + 1) % self.save_every == 0:
            self.ck.save(i, engine=self.eng, data_feed=self.feed)


def run_twin(args, make_engine, make_batch, telemetry=None):
    """The unkilled fault-free reference trajectory."""
    import paddle_tpu as paddle
    from paddle_tpu.distributed.train_checkpoint import CheckpointableDataFeed

    eng = make_engine(telemetry=telemetry)
    feed = CheckpointableDataFeed(make_batch, telemetry=telemetry)
    losses = {}
    for i in range(args.steps):
        X, y = feed.next_batch()
        losses[i] = float(np.asarray(eng.train_batch(
            paddle.to_tensor(X), paddle.to_tensor(y)).value))
    return losses, eng.engine_state_dict()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--seed", type=int, default=3, help="fault-plan seed")
    p.add_argument("--kills", type=int, default=2)
    p.add_argument("--width", type=int, default=8)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--model-seed", type=int, default=5)
    p.add_argument("--data-seed", type=int, default=100)
    p.add_argument("--max-restarts", type=int, default=6)
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)

    from paddle_tpu.distributed.fleet.chaos import ElasticChaosHarness
    from paddle_tpu.faults import FaultInjector, FaultPlan
    from paddle_tpu.telemetry import MetricsRegistry, TrainTelemetry

    make_engine, make_batch = build_factories(args)
    # the twin's goodput ledger must come out EXACTLY 1.0 — no replayed
    # step indices, no recovery segments — which is half of what the
    # goodput gate pins (the chaos run's < 1.0 is the other half)
    twin_tel = TrainTelemetry()
    twin_losses, twin_state = run_twin(args, make_engine, make_batch,
                                       telemetry=twin_tel)

    plan = FaultPlan.train_chaos(args.seed, horizon=args.steps,
                                 kills=args.kills)
    injector = FaultInjector(plan)
    metrics = MetricsRegistry()
    tel = TrainTelemetry(registry=metrics)
    final_state = {}

    with tempfile.TemporaryDirectory() as ckpt_dir:
        def build(inj):
            run = ChaosTrainRun(inj, ckpt_dir, metrics, make_engine,
                                make_batch, telemetry=tel)
            final_state["engine"] = run.eng
            return run

        harness = ElasticChaosHarness(
            build, total_steps=args.steps, injector=injector,
            max_restarts=args.max_restarts, telemetry=tel)
        report = harness.run()
        chaos_state = final_state["engine"].engine_state_dict()

    loss_mismatches = sum(
        1 for i, v in report.losses.items() if v != twin_losses[i])
    params_bitexact = all(
        np.array_equal(twin_state["params"][n], chaos_state["params"][n])
        for n in twin_state["params"]) and all(
        np.array_equal(twin_state["opt_state"][n][k],
                       chaos_state["opt_state"][n][k])
        for n in twin_state["opt_state"]
        for k in twin_state["opt_state"][n])

    fired = injector.stats()
    ckpt_read_fired = sum(1 for s, _ in injector.fired if s == "ckpt_read")
    ckpt_write_fired = sum(1 for s, _ in injector.fired if s == "ckpt_write")
    ctr = lambda n: metrics.counter("train_checkpoint_" + n, "").total()
    result = {
        "bench": "train_chaos",
        "schema_version": 1,
        "steps": args.steps,
        "plan_seed": args.seed,
        "completed": report.completed,
        "restarts": report.restarts,
        "detected_kills": report.detected_kills,
        "steps_run": report.steps_run,
        "transient_retries": report.transient_retries,
        "faults_injected": fired["fired"],
        "fault_sites": fired["fired_sites"],
        "loss_mismatches": loss_mismatches,
        "params_bitexact": bool(params_bitexact),
        "ckpt_read_fired": ckpt_read_fired,
        "ckpt_write_fired": ckpt_write_fired,
        "corrupt_reads_detected": ctr("corrupt_reads"),
        "generation_fallbacks": ctr("generation_fallbacks"),
        "save_retries": ctr("save_retries"),
        "save_failures": ctr("save_failures"),
        "saves": ctr("saves"),
        "restores": ctr("restores"),
        "train_goodput_ratio": tel.goodput.ratio(),
        "twin_goodput_ratio": twin_tel.goodput.ratio(),
        "goodput": tel.goodput.snapshot(),
        "train_watchdog": tel.watchdog(),
    }
    print(json.dumps(result) if args.as_json else
          f"train_chaos: completed={result['completed']} "
          f"restarts={result['restarts']} faults={result['faults_injected']} "
          f"at {result['fault_sites']} mismatches={result['loss_mismatches']} "
          f"bitexact={result['params_bitexact']} "
          f"corrupt_reads={result['corrupt_reads_detected']}/"
          f"{result['ckpt_read_fired']}")
    kills = result["detected_kills"]
    ok = (result["completed"] and result["loss_mismatches"] == 0
          and result["params_bitexact"]
          and result["corrupt_reads_detected"] >= result["ckpt_read_fired"]
          and result["detected_kills"] == result["restarts"]
          and result["faults_injected"] > 0
          # goodput accounting: the fault-free twin is exactly 1.0; the
          # chaos run dips below 1.0 exactly when a kill forced replay
          and result["twin_goodput_ratio"] == 1.0
          and ((result["train_goodput_ratio"] < 1.0) == (kills > 0)))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
