"""MoE/EP performance existence: on-chip train row + dispatch-cost
breakdown (VERDICT r4 missing 2).

The reference ships fused MoE kernels + dedicated dispatch ops
(paddle/phi/kernels/fusion/moe_kernel.h, operators/collective/
global_scatter_op.cu). Our GShard dense-dispatch formulation (einsum over
one-hots, moe_layer.py) instead rides the MXU and lets GSPMD insert the
all_to_all. This tool measures, on one chip (expert axis degenerate):

- a 4-layer MoE-FFN train step (B=8, S=2048, d=1024, E=8, top-2):
  ms/step, tok/s, MFU over ACTIVE FLOPs (experts see E*C tokens);
- the step decomposed: gate+dispatch/combine einsums vs experts-only —
  the dense dispatch is O(T*E*C*d), so its share decides whether a fused
  (sorted-scatter) Pallas dispatch is worth building [go/no-go].

Usage: python tools/bench_moe.py [--d_model 1024] [--experts 8]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--d_model", type=int, default=1024)
    ap.add_argument("--d_hidden", type=int, default=2816)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--top_k", type=int, default=2)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.incubate.distributed.models.moe import MoELayer
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import ParallelEngine
    from paddle_tpu.utils.bench_timing import (device_time_ms, peak_flops,
                                               tpu_lock)

    on_tpu = any(d.platform in ("tpu", "axon") for d in jax.devices())
    assert on_tpu, "MoE bench wants the real chip"

    D, H, E, K = args.d_model, args.d_hidden, args.experts, args.top_k
    B, S, L = args.batch, args.seq, args.layers
    T = B * S
    cf = 1.25
    C = max(int(cf * T * K / E), 1)

    class MoEStack(nn.Layer):
        def __init__(self):
            super().__init__()
            from paddle_tpu.nn import LayerList

            self.norms = LayerList([nn.LayerNorm(D) for _ in range(L)])
            self.moes = LayerList([
                MoELayer(d_model=D, num_experts=E, d_hidden=H, top_k=K)
                for _ in range(L)])
            self.head = nn.Linear(D, D)

        def forward(self, x):
            for norm, moe in zip(self.norms, self.moes):
                x = x + moe(norm(x))
            return self.head(x)

    paddle.seed(0)
    with tpu_lock(timeout_s=900.0) as locked:
        model = MoEStack()
        n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
        opt = AdamW(learning_rate=1e-4, parameters=model.parameters())

        def loss_fn(out, y):
            aux = sum((m.gate.loss for m in model.moes
                       if m.gate.loss is not None), 0.0)
            return paddle.mean((out - y) ** 2) + 0.01 * aux

        eng = ParallelEngine(model, optimizer=opt, loss_fn=loss_fn)
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(B, S, D).astype("float32") * 0.1)
        y = paddle.to_tensor(rng.randn(B, S, D).astype("float32") * 0.1)
        step_ms = device_time_ms(lambda: eng.train_batch(x, y),
                                 reps=5, warmup=2)
        loss = float(np.asarray(eng.train_batch(x, y).value))

        # ---- decomposition (forward-only, jitted pieces, same shapes) ----
        moe = model.moes[0]
        gate_w = jnp.asarray(moe.gate.weight.value)
        w1 = jnp.asarray(moe.experts.w1.value)
        w2 = jnp.asarray(moe.experts.w2.value)
        b1 = jnp.asarray(moe.experts.b1.value)
        b2 = jnp.asarray(moe.experts.b2.value)
        flat = jnp.asarray(rng.randn(T, D).astype("float32") * 0.1)
        buckets = jnp.asarray(rng.randn(E, C, D).astype("float32") * 0.1)

        # decomposition runs BOTH dispatch modes explicitly (the env is
        # read at trace time): full_moe in the current default mode plus a
        # forced-dense full pass, so dispatch_share always compares the
        # dense dispatch against the DENSE step it is part of
        cur_mode = os.environ.get("PT_MOE_DISPATCH", "sparse")

        def full_moe_fn(xv):
            out = moe(paddle.to_tensor(xv)).value
            return out.ravel()[0]

        full_moe = jax.jit(full_moe_fn)
        _ = full_moe(flat)  # trace in cur_mode
        os.environ["PT_MOE_DISPATCH"] = "dense"
        full_moe_dense = jax.jit(lambda xv: full_moe_fn(xv) + 0.0)
        _ = full_moe_dense(flat)  # trace in dense mode
        os.environ["PT_MOE_DISPATCH"] = cur_mode

        @jax.jit
        def experts_only(bk):
            out = moe.experts.run_experts(bk, w1, w2, b1, b2)
            return out.ravel()[0]

        @jax.jit
        def gate_dispatch_only(xv):
            topv, topi, aux = moe.gate.routing(xv, gate_w)
            onehot = jax.nn.one_hot(topi, E, dtype=jnp.int32)
            flat_oh = onehot.reshape(T * K, E)
            pos_in_e = jnp.cumsum(flat_oh, axis=0) - flat_oh
            pos = jnp.sum(pos_in_e * flat_oh, axis=-1).reshape(T, K)
            keep = pos < C
            oh_e = jax.nn.one_hot(topi, E, dtype=xv.dtype)
            oh_c = jax.nn.one_hot(jnp.where(keep, pos, C), C, dtype=xv.dtype)
            dispatch = jnp.einsum("tke,tkc->tec", oh_e, oh_c)
            bk = jnp.einsum("tec,td->ecd", dispatch, xv)
            combine = jnp.einsum("tke,tkc,tk->tec", oh_e, oh_c,
                                 topv.astype(xv.dtype))
            out = jnp.einsum("tec,ecd->td", combine, bk)
            return out.ravel()[0]

        moe_ms = device_time_ms(lambda: full_moe(flat), reps=5, warmup=2)
        moe_dense_ms = device_time_ms(lambda: full_moe_dense(flat), reps=5,
                                      warmup=2)
        exp_ms = device_time_ms(lambda: experts_only(buckets), reps=5,
                                warmup=2)
        disp_ms = device_time_ms(lambda: gate_dispatch_only(flat), reps=5,
                                 warmup=2)

    tok_s = T / (step_ms / 1e3)
    # active FLOPs: experts compute on E*C token slots (fwd+bwd 3x). The
    # dense one-hot dispatch adds T*E*C*D einsums; the sparse path moves
    # the same tokens with gathers (no MXU FLOPs) — count dispatch FLOPs
    # only when they are actually executed
    expert_flops = 2 * E * C * (2 * D * H) * 3 * L
    dispatch_flops = (2 * (2 * T * E * C * D) * 3 * L
                      if os.environ.get("PT_MOE_DISPATCH",
                                        "sparse") == "dense" else 0)
    mfu = (expert_flops + dispatch_flops) / (step_ms / 1e3) / peak_flops()
    line = {
        "metric": "moe_train_tokens_per_sec_1chip",
        "value": round(tok_s, 1),
        "unit": f"tok/s ({L}L MoE-FFN d{D} E{E} top{K} C{C}, "
                f"{n_params/1e6:.0f}M params, loss={loss:.4f})",
        "ms_per_step": round(step_ms, 2),
        "dispatch_mode": os.environ.get("PT_MOE_DISPATCH", "sparse"),
        "mfu_active": round(mfu, 4),
        "decomp_ms": {"full_moe_fwd": round(moe_ms, 2),
                      "full_moe_fwd_dense": round(moe_dense_ms, 2),
                      "experts_only_fwd": round(exp_ms, 2),
                      "dense_gate_dispatch_combine_fwd": round(disp_ms, 2)},
        "dense_dispatch_share": (round(disp_ms / moe_dense_ms, 3)
                                 if moe_dense_ms else None),
    }
    if not locked:
        line["lock_contended"] = True
    print(json.dumps(line))


if __name__ == "__main__":
    main()
