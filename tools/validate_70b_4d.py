"""AOT-validate the Llama-3-70B 4D-hybrid training program (BASELINE config 4).

Builds the full 70B config (80 layers, 8192 hidden, GQA-8) sharded over a
virtual dp×sharding×tensor×pipe-capable mesh and LOWERS the complete train
step (fwd + bwd + AdamW) with abstract inputs — no parameter memory is
allocated, so this runs on any host. A successful lowering proves the GSPMD
program (with all TP/ZeRO collectives) type-checks and partitions end to end;
the driver's `dryrun_multichip` covers the execute path on a tiny model.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python tools/validate_70b_4d.py [--layers N] [--seq 4096]

--layers trims the depth (the sharding structure is per-layer identical, so
8 layers exercises the same program shapes ~10x faster; pass 80 for the
full model).
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--compile", action="store_true",
                    help="run GSPMD partitioning too (slower) and report "
                         "collective counts in the partitioned HLO")
    args = ap.parse_args()

    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    # the axon TPU plugin overrides the env var; force the config knob before
    # any backend query (conftest.py pattern)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama3_70b_config
    from paddle_tpu.parallel.engine import ParallelEngine, param_specs

    devs = np.asarray(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "sharding", "tensor"))
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    cfg = llama3_70b_config(num_hidden_layers=args.layers,
                            max_position_embeddings=args.seq)
    t0 = time.time()
    paddle.seed(0)
    # zero-fill initializers: at 70B scale random init dominates build time
    # and the lowering never reads values — only shapes/dtypes matter here
    from paddle_tpu.nn import initializer as I

    def _zeros_init(self, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)

    for cls in (I.Normal, I.Uniform, I.XavierNormal, I.XavierUniform,
                I.KaimingNormal, I.KaimingUniform, I.TruncatedNormal):
        cls.__call__ = _zeros_init
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"model built: {n_params/1e9:.2f}B params ({args.layers} layers) "
          f"in {time.time()-t0:.0f}s")

    from paddle_tpu.optimizer import AdamW

    opt = AdamW(learning_rate=1e-4, parameters=model.parameters())
    eng = ParallelEngine(model, optimizer=opt, loss_fn=None, mesh=mesh,
                         fsdp=True, remat=True, abstract=True)
    step = eng.build_train_step()

    ids = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32,
                               sharding=NamedSharding(mesh, P("data", None)))
    lbl = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int64,
                               sharding=NamedSharding(mesh, P("data", None)))
    p_abs = {k: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding)
             for k, v in eng.params.items()}
    st_abs = jax.tree.map(
        lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype, sharding=v.sharding),
        eng.opt_state)
    sc = jax.ShapeDtypeStruct((), jnp.int32)

    t0 = time.time()
    lowered = step.lower(p_abs, st_abs, sc, 1e-4, (ids, lbl))
    txt = lowered.as_text()
    n_shard = txt.count("sdy.sharding") + txt.count("mhlo.sharding")
    print(f"lowered in {time.time()-t0:.0f}s; {len(txt) // 1024}kB StableHLO, "
          f"{n_shard} sharding annotations")
    assert n_shard > 0, "no sharding annotations in lowered program"
    if args.compile:
        t0 = time.time()
        compiled = lowered.compile()
        hlo = compiled.as_text()
        print(f"GSPMD-compiled in {time.time()-t0:.0f}s")
        for coll in ("all-gather", "reduce-scatter", "all-reduce",
                     "collective-permute"):
            print(f"  {coll}: {hlo.count(coll)} sites")
    print("70B 4D-hybrid validation OK")


if __name__ == "__main__":
    main()
