"""AOT-validate the Llama-3-70B 4D-hybrid training program (BASELINE config 4).

TRUE 4D: dp × ZeRO-sharding × tensor × PIPE over a 16-virtual-device mesh
(2×2×2×2), with the block stack pipelined through the compiled GPipe scan
(`parallel.PipelineEngine`) — ref fleet.py:385 `_init_hybrid_parallel_env`
(dp×pp×sharding×mp all at once). The full train step (fwd + bwd + AdamW) is
lowered with ABSTRACT engine params/opt-state (no 70B optimizer memory), but
the eager model build itself does materialize zero-filled fp32 host arrays:
~5.5GB/layer — default --layers 4 needs ~22GB host RAM; --layers 80 would
need a ~300GB host. With --compile the partitioned HLO must contain
collective-permute (pipe ppermute) alongside the TP all-reduce and ZeRO
all-gather sites.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=16 JAX_PLATFORMS=cpu \
        python tools/validate_70b_4d.py [--layers N] [--seq 4096] [--compile]

--layers trims the depth (the sharding structure is per-layer identical, so
8 layers exercises the same program shapes ~10x faster; pass 80 for the
full model). Must stay divisible by the 2 pipeline stages.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N_DEV = 16  # 2 data × 2 sharding × 2 tensor × 2 pipe


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--seq", type=int, default=4096)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--compile", action="store_true",
                    help="run GSPMD partitioning too (slower) and report "
                         "collective counts in the partitioned HLO")
    args = ap.parse_args()

    os.environ.setdefault(
        "XLA_FLAGS", f"--xla_force_host_platform_device_count={N_DEV}")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    # the axon TPU plugin overrides the env var; force the config knob before
    # any backend query (conftest.py pattern)
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding
    from jax.sharding import PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaForCausalLM, llama3_70b_config

    assert jax.device_count() >= N_DEV, \
        f"need {N_DEV} devices (run with XLA_FLAGS=" \
        f"--xla_force_host_platform_device_count={N_DEV})"
    devs = np.asarray(jax.devices()[:N_DEV]).reshape(2, 2, 2, 2)
    mesh = Mesh(devs, ("data", "sharding", "tensor", "pipe"))
    print(f"mesh: {dict(zip(mesh.axis_names, mesh.devices.shape))}")

    # float32: XLA's CPU backend crashes in AllReducePromotion cloning a
    # bf16 all-reduce ("Invalid binary instruction opcode copy"); the
    # partitioning/collective structure being validated is dtype-independent
    cfg = llama3_70b_config(num_hidden_layers=args.layers,
                            max_position_embeddings=args.seq,
                            dtype="float32")
    t0 = time.time()
    paddle.seed(0)
    # zero-fill initializers: at 70B scale random init dominates build time
    # and the lowering never reads values — only shapes/dtypes matter here
    from paddle_tpu.nn import initializer as I

    def _zeros_init(self, shape, dtype=jnp.float32):
        return jnp.zeros(shape, dtype)

    for cls in (I.Normal, I.Uniform, I.XavierNormal, I.XavierUniform,
                I.KaimingNormal, I.KaimingUniform, I.TruncatedNormal):
        cls.__call__ = _zeros_init
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    print(f"model built: {n_params/1e9:.2f}B params ({args.layers} layers) "
          f"in {time.time()-t0:.0f}s")

    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import llama_pipeline_engine

    opt = AdamW(learning_rate=1e-4, parameters=model.parameters())
    eng = llama_pipeline_engine(model, optimizer=opt, mesh=mesh,
                                num_micro=args.micro, remat=True,
                                abstract=True, fsdp=True)
    # stage-sharded + ZeRO: every stacked leaf carries pipe and most carry
    # the sharding axis too
    piped = [s for s in eng.stacked_specs.values() if "pipe" in tuple(s)]
    zeroed = [s for s in eng.stacked_specs.values()
              if "sharding" in tuple(s)]
    print(f"stacked specs: {len(piped)}/{len(eng.stacked_specs)} pipe-sharded,"
          f" {len(zeroed)} ZeRO-sharded (e.g. "
          f"{eng.stacked_specs['self_attn.q_proj.weight']})")
    assert len(piped) == len(eng.stacked_specs)
    assert len(zeroed) > 0, "ZeRO sharding axis missing from stacked specs"

    ids = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int32,
                               sharding=NamedSharding(mesh, P("data", None)))
    lbl = jax.ShapeDtypeStruct((args.batch, args.seq), jnp.int64,
                               sharding=NamedSharding(mesh, P("data", None)))

    t0 = time.time()
    lowered = eng.lower_train_step((ids,), (lbl,))
    txt = lowered.as_text()
    n_shard = txt.count("sdy.sharding") + txt.count("mhlo.sharding")
    print(f"lowered in {time.time()-t0:.0f}s; {len(txt) // 1024}kB StableHLO, "
          f"{n_shard} sharding annotations")
    assert n_shard > 0, "no sharding annotations in lowered program"
    if args.compile:
        t0 = time.time()
        compiled = lowered.compile()
        hlo = compiled.as_text()
        print(f"GSPMD-compiled in {time.time()-t0:.0f}s")
        counts = {coll: hlo.count(coll)
                  for coll in ("all-gather", "reduce-scatter", "all-reduce",
                               "collective-permute")}
        for coll, n in counts.items():
            print(f"  {coll}: {n} sites")
        assert counts["collective-permute"] > 0, \
            "pipeline ppermute missing from partitioned HLO"
        assert counts["all-reduce"] > 0
        assert counts["all-gather"] > 0, "ZeRO all-gathers missing"
    # staggered interleaved 1F1B over the same 4D mesh: the new schedule
    # must also lower at scale (loss-inside-pipe, traced chunk gather).
    # abstract=True only reads shapes/dtypes — reuse the SAME model/opt
    # (a second eager build would double peak host RAM and build time)
    if args.layers % 4 == 0:
        t0 = time.time()
        eng2 = llama_pipeline_engine(model, optimizer=opt, mesh=mesh,
                                     num_micro=args.micro, remat=True,
                                     abstract=True, fsdp=True,
                                     num_chunks=2, schedule="1f1b")
        txt2 = eng2.lower_train_step((ids,), (lbl,)).as_text()
        n_shard2 = txt2.count("sdy.sharding") + txt2.count("mhlo.sharding")
        print(f"1f1b-interleaved (C=2) lowered in {time.time()-t0:.0f}s; "
              f"{len(txt2) // 1024}kB StableHLO, {n_shard2} annotations")
        assert n_shard2 > 0, "no sharding annotations in 1f1b lowering"
    else:
        print(f"1f1b-interleaved (C=2) leg skipped: --layers {args.layers} "
              f"not divisible by 2 stages x 2 chunks")
    print("70B 4D-hybrid (dp×sharding×tensor×pipe) validation OK")


if __name__ == "__main__":
    main()
