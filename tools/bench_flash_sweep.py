"""Flash-attention kernel block-size sweep — run on a REAL TPU chip.

Round-1 measurements (BASELINE.md) left the forward kernel ~15% behind the
stock jax reference at B=8 S=2048 GQA and fwd+bwd at 41.6% of peak at S=16k;
this tool is the measurement harness for closing that gap: it times every
(block_q, block_k) combination for each shape in its own SUBPROCESS (the
block size is baked into the compiled kernel, so same-process env flips
would silently reuse the first compilation) and prints a ranked table plus
the current-default comparison.

Usage (TPU):
    python tools/bench_flash_sweep.py [--shapes small|mid|long|mha|all] [--bwd]
"""
import argparse
import json
import os
import subprocess
import sys

SHAPES = {
    "small": [(8, 2048, 16, 8, 128)],          # the B=8 S=2048 GQA headline
    "mid": [(2, 8192, 16, 8, 128)],            # loop-kernel upper boundary
    "mha": [(8, 2048, 16, 16, 128)],           # KV=H (GPT-family attention)
    "long": [(1, 16384, 16, 8, 128)],          # S=16k streaming target
    "all": [(8, 2048, 16, 8, 128), (2, 8192, 16, 8, 128),
            (1, 16384, 16, 8, 128), (8, 2048, 16, 16, 128)],
}
BLOCKS = [(256, 256), (256, 512), (512, 256), (512, 512),
          (512, 1024), (1024, 512), (1024, 1024)]

_CHILD = r"""
import json, os, sys
import numpy as np
sys.path.insert(0, %(repo)r)
import jax, jax.numpy as jnp
from paddle_tpu.ops.flash_attention import flash_attention

B, S, H, KV, D = %(shape)s
do_bwd = %(bwd)s
rng = np.random.RandomState(0)
q = jnp.asarray(rng.randn(B, H, S, D).astype("float32")).astype(jnp.bfloat16)
k = jnp.asarray(rng.randn(B, KV, S, D).astype("float32")).astype(jnp.bfloat16)
v = jnp.asarray(rng.randn(B, KV, S, D).astype("float32")).astype(jnp.bfloat16)

fwd = jax.jit(lambda a, b, c: flash_attention(a, b, c, True))
loss = jax.jit(jax.grad(lambda a, b, c: jnp.sum(
    flash_attention(a, b, c, True).astype(jnp.float32)), argnums=(0, 1, 2)))

fn = loss if do_bwd else fwd
from paddle_tpu.utils.bench_timing import device_time_ms
# tunnel jitter is tens of ms; keep the differencing signal (reps x kernel
# time) well above it, and take enough repeats that both chains hit their
# latency floor
reps = (60 if S <= 4096 else 16) if not do_bwd else (20 if S <= 4096 else 8)
ms = device_time_ms(lambda: fn(q, k, v), reps=reps, repeats=5)
# causal attention flops: ~0.5 * 4 * B*H*S^2*D fwd (x2.5 for fwd+bwd)
flops = 0.5 * 4.0 * B * H * S * S * D * (2.5 if do_bwd else 1.0)
print(json.dumps({"ms": ms, "tflops": flops / ms / 1e9}))
"""


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)


def run_config(shape, bq, bk, bwd):
    repo = _REPO
    from paddle_tpu.utils.bench_timing import tpu_lock

    env = dict(os.environ)
    env["PT_FLASH_BLOCK_Q"] = str(bq)
    env["PT_FLASH_BLOCK_K"] = str(bk)
    code = _CHILD % {"repo": repo, "shape": tuple(shape), "bwd": bwd}
    try:
        # bounded wait + contended samples dropped, same policy as the
        # pairwise driver: corrupted timings must not become winners
        with tpu_lock(timeout_s=900.0) as locked:
            if not locked:
                print("  [sweep] chip lock contended; sample dropped")
                return None
            out = subprocess.run([sys.executable, "-c", code], env=env,
                                 capture_output=True, text=True, timeout=600)
        if out.returncode != 0:
            return None
        return json.loads(out.stdout.strip().splitlines()[-1])
    except (subprocess.TimeoutExpired, ValueError, IndexError):
        return None


def _peak_tflops():
    from paddle_tpu.utils.bench_timing import peak_flops

    return peak_flops() / 1e12


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--shapes", default="small", choices=list(SHAPES))
    ap.add_argument("--bwd", action="store_true",
                    help="time grad (fwd+bwd) instead of forward only")
    args = ap.parse_args()
    peak = _peak_tflops()

    winners = {}  # seq_len -> (bq, bk)
    for shape in SHAPES[args.shapes]:
        print(f"\n== shape B,S,H,KV,D = {shape} "
              f"({'fwd+bwd' if args.bwd else 'fwd'}) ==")
        rows = []
        for bq, bk in BLOCKS:
            r = run_config(shape, bq, bk, args.bwd)
            tag = f"bq={bq:4d} bk={bk:4d}"
            if r is None:
                print(f"  {tag}: FAILED/OOM")
                continue
            if r["tflops"] > peak:
                # physically impossible (> chip peak): the differencing
                # signal was below the tunnel jitter — never let such a row
                # become the winner
                print(f"  {tag}: {r['ms']:7.3f} ms  {r['tflops']:6.1f} "
                      f"TFLOP/s  SUSPECT (> {peak:.0f} peak, excluded)")
                continue
            rows.append((r["ms"], bq, bk, tag, r["tflops"]))
            print(f"  {tag}: {r['ms']:7.3f} ms  {r['tflops']:6.1f} TFLOP/s")
        if rows:
            rows.sort()
            ms, bq, bk, tag, tflops = rows[0]
            print(f"  BEST: {tag} at {ms:.3f} ms ({tflops:.1f} TFLOP/s)")
            winners[shape[1]] = (bq, bk)
    if winners:
        # ready-to-adopt regime map for the PT_FLASH_BLOCKS(_BWD) env
        # override / ops/flash_attention._BLOCK_REGIMES_FWD/_BWD tables
        adopt = ",".join(f"{s}:{bq}x{bk}"
                         for s, (bq, bk) in sorted(winners.items()))
        var = "PT_FLASH_BLOCKS_BWD" if args.bwd else "PT_FLASH_BLOCKS"
        table = "_BLOCK_REGIMES_BWD" if args.bwd else "_BLOCK_REGIMES_FWD"
        print(f"\nADOPT: {var}=\"{adopt}\"  (or fold into {table})")
        if args.bwd:
            # this sweep forces ONE uniform block for both directions and
            # times fwd+bwd together, so a "bwd winner" can encode a
            # suboptimal bwd-only choice when the fwd kernel dominates
            print("NOTE: --bwd times fwd+bwd with a uniform block; confirm "
                  "close winners with tools/bench_flash_pairwise.py (which "
                  "varies fwd and bwd blocks independently) before folding "
                  "into _BLOCK_REGIMES_BWD")


if __name__ == "__main__":
    main()
