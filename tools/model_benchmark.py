"""Model-level benchmark harness (ref tools/ci_model_benchmark.sh — relative
model-perf gate). Runs a quick train-step benchmark for each flagship model
family and writes JSON {model: {"ms_per_step": ..., "tokens_or_imgs_per_s"}}.

Usage: python tools/model_benchmark.py [-o out.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def bench_llama():
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import ParallelEngine

    on_tpu = jax.default_backend() in ("tpu", "axon")
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5632, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048, dtype="bfloat16",
                          use_flash_attention=True)
        B, S, iters = 8, 2048, 6
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=256, dtype="float32",
                          use_flash_attention=False)
        B, S, iters = 2, 128, 3
    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters())
    engine = ParallelEngine(model, optimizer=opt, loss_fn=model.loss_fn,
                            remat=False)
    engine.build_train_step()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype("int32"))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype("int64"))
    from paddle_tpu.utils.bench_timing import device_time_ms

    ms = device_time_ms(lambda: engine.train_batch(ids, labels),
                        reps=iters, warmup=2)
    return {"ms_per_step": round(ms, 2),
            "tokens_per_s": round(B * S / (ms / 1e3), 1)}


def bench_resnet50():
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50

    on_tpu = jax.default_backend() in ("tpu", "axon")
    B = 32 if on_tpu else 4
    model = resnet50(num_classes=10)
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=model.parameters())
    from paddle_tpu.parallel import ParallelEngine

    def loss_fn(logits, labels):
        return paddle.nn.functional.cross_entropy(logits, labels)

    engine = ParallelEngine(model, optimizer=opt, loss_fn=loss_fn, remat=False)
    engine.build_train_step()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(B, 3, 224, 224).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 10, (B,)).astype("int64"))
    from paddle_tpu.utils.bench_timing import device_time_ms

    ms = device_time_ms(lambda: engine.train_batch(x, y), reps=5, warmup=2)
    return {"ms_per_step": round(ms, 2),
            "imgs_per_s": round(B / (ms / 1e3), 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default=None)
    ap.add_argument("--models", default="llama,resnet50")
    args = ap.parse_args()
    from paddle_tpu.utils.bench_timing import tpu_lock

    table = {"llama": bench_llama, "resnet50": bench_resnet50}
    results = {}
    for name in args.models.split(","):
        with tpu_lock(timeout_s=900.0) as locked:
            results[name] = table[name.strip()]()
        if not locked:
            results[name]["lock_contended"] = True
        print(name, results[name])
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=2)
        print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
