"""Model-level benchmark harness (ref tools/ci_model_benchmark.sh — relative
model-perf gate). Runs a quick train-step benchmark for each flagship model
family and writes JSON {model: {"ms_per_step": ..., "tokens_or_imgs_per_s"}}.

Usage: python tools/model_benchmark.py [-o out.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def bench_llama():
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import ParallelEngine

    on_tpu = jax.default_backend() in ("tpu", "axon")
    if on_tpu:
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=5632, num_hidden_layers=8,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048, dtype="bfloat16",
                          use_flash_attention=True)
        B, S, iters = 8, 2048, 6
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=256, dtype="float32",
                          use_flash_attention=False)
        B, S, iters = 2, 128, 3
    model = LlamaForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters())
    engine = ParallelEngine(model, optimizer=opt, loss_fn=model.loss_fn,
                            remat=False)
    engine.build_train_step()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype("int32"))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S)).astype("int64"))
    from paddle_tpu.utils.bench_timing import device_time_ms

    ms = device_time_ms(lambda: engine.train_batch(ids, labels),
                        reps=iters, warmup=2)
    return {"ms_per_step": round(ms, 2),
            "tokens_per_s": round(B * S / (ms / 1e3), 1)}


def bench_llama_moe():
    """Mixtral-proxy train step (model-level MoE, r5): 8 SwiGLU experts
    top-2 in every FFN, sparse dispatch, aux loss in the LM objective.
    Active params/token ~= dense 509M-proxy's shape at E/K = 4x total."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import ParallelEngine
    from paddle_tpu.utils.bench_timing import device_time_ms, peak_flops

    on_tpu = jax.default_backend() in ("tpu", "axon")
    if on_tpu:
        # 8 experts of width 2816: ~700M total params (fits full AdamW
        # on 16 GB), ~330M active/token — the single-chip Mixtral proxy
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048,
                          intermediate_size=2816, num_hidden_layers=4,
                          num_attention_heads=16, num_key_value_heads=8,
                          max_position_embeddings=2048, dtype="bfloat16",
                          use_flash_attention=True, moe_num_experts=8,
                          moe_top_k=2)
        B, S, iters = 4, 2048, 5
    else:
        cfg = LlamaConfig(vocab_size=512, hidden_size=128,
                          intermediate_size=384, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=256, dtype="float32",
                          use_flash_attention=False, moe_num_experts=4,
                          moe_top_k=2)
        B, S, iters = 2, 128, 3
    model = LlamaForCausalLM(cfg)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    # active params/token: dense non-FFN + K/E of the expert stacks
    n_active = sum(
        int(np.prod(p.shape)) * (cfg.moe_top_k / cfg.moe_num_experts
                                 if ".moe.experts." in name else 1.0)
        for name, p in model.named_parameters())
    opt = AdamW(learning_rate=1e-4, parameters=model.parameters())
    engine = ParallelEngine(model, optimizer=opt, loss_fn=None, remat=False)
    engine.build_train_step()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S))
                           .astype("int32"))
    labels = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S))
                              .astype("int64"))
    ms = device_time_ms(lambda: engine.train_batch(ids, labels),
                        reps=iters, warmup=2)
    toks = B * S / (ms / 1e3)
    return {"ms_per_step": round(ms, 2),
            "tokens_per_s": round(toks, 1),
            "params_m": round(n_params / 1e6, 1),
            "active_params_m": round(n_active / 1e6, 1),
            "mfu_active_6nd": round(toks * 6.0 * n_active / peak_flops(), 4)}


def bench_resnet50():
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet50

    on_tpu = jax.default_backend() in ("tpu", "axon")
    B = 32 if on_tpu else 4
    model = resnet50(num_classes=10)
    opt = paddle.optimizer.Momentum(learning_rate=0.1,
                                    parameters=model.parameters())
    from paddle_tpu.parallel import ParallelEngine

    def loss_fn(logits, labels):
        return paddle.nn.functional.cross_entropy(logits, labels)

    engine = ParallelEngine(model, optimizer=opt, loss_fn=loss_fn, remat=False)
    engine.build_train_step()
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(B, 3, 224, 224).astype("float32"))
    y = paddle.to_tensor(rng.randint(0, 10, (B,)).astype("int64"))
    from paddle_tpu.utils.bench_timing import device_time_ms

    ms = device_time_ms(lambda: engine.train_batch(x, y), reps=5, warmup=2)
    return {"ms_per_step": round(ms, 2),
            "imgs_per_s": round(B / (ms / 1e3), 1)}


def bench_ernie():
    """BASELINE config 2: ERNIE-3.0 base finetune (12L H768 A12, seq-cls,
    B=32 S=128 — the canonical PaddleNLP finetune recipe shape). MFU uses
    ~6·N·tokens like the llama bench (encoder fwd+bwd matmul estimate)."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.models.ernie import (ErnieConfig,
                                         ErnieForSequenceClassification,
                                         ernie_tiny_config)
    from paddle_tpu.optimizer import AdamW
    from paddle_tpu.parallel import ParallelEngine

    on_tpu = jax.default_backend() in ("tpu", "axon")
    if on_tpu:
        cfg = ErnieConfig(vocab_size=40000, hidden_size=768,
                          num_hidden_layers=12, num_attention_heads=12,
                          intermediate_size=3072, hidden_dropout_prob=0.1,
                          attention_probs_dropout_prob=0.1,
                          max_position_embeddings=2048)
        B, S, iters = 32, 128, 8
    else:
        cfg = ernie_tiny_config()
        B, S, iters = 4, 32, 3
    model = ErnieForSequenceClassification(cfg, num_classes=2)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = AdamW(learning_rate=5e-5, parameters=model.parameters())
    engine = ParallelEngine(model, optimizer=opt, loss_fn=model.loss_fn,
                            remat=False)
    engine.build_train_step()
    rng = np.random.RandomState(0)
    ids = paddle.to_tensor(rng.randint(0, cfg.vocab_size, (B, S))
                           .astype("int32"))
    labels = paddle.to_tensor(rng.randint(0, 2, (B,)).astype("int64"))
    from paddle_tpu.utils.bench_timing import device_time_ms, peak_flops

    ms = device_time_ms(lambda: engine.train_batch(ids, labels),
                        reps=iters, warmup=2)
    toks = B * S / (ms / 1e3)
    return {"ms_per_step": round(ms, 2),
            "tokens_per_s": round(toks, 1),
            "examples_per_s": round(B / (ms / 1e3), 1),
            "mfu_6nd": round(toks * 6.0 * n_params / peak_flops(), 4),
            "params_m": round(n_params / 1e6, 1)}


def bench_ocr_rec():
    """BASELINE config 5 (rec side): the CRNN+CTC recipe from
    examples/ocr_recognition.py — conv tower + BiLSTM + CTC, the actual
    PP-OCRv4-style rec training step, not a ResNet proxy."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.optimizer import Adam
    from paddle_tpu.parallel import ParallelEngine
    from paddle_tpu.vision.models import CRNN, crnn_ctc_loss
    from paddle_tpu.nn import Layer

    class CRNNWithLoss(Layer):
        def __init__(self, rec):
            super().__init__()
            self.rec = rec

        def forward(self, imgs, labels, lengths):
            return crnn_ctc_loss(self.rec(imgs), labels, lengths)

    on_tpu = jax.default_backend() in ("tpu", "axon")
    B, iters = (64, 8) if on_tpu else (8, 3)
    model = CRNNWithLoss(CRNN(num_classes=10, in_channels=1))
    opt = Adam(learning_rate=1e-3, parameters=model.parameters())
    engine = ParallelEngine(model, optimizer=opt, loss_fn=None, remat=False)
    engine.build_train_step()
    rng = np.random.RandomState(0)
    imgs = paddle.to_tensor(rng.rand(B, 1, 32, 96).astype("float32"))
    labels = paddle.to_tensor(rng.randint(1, 11, (B, 5)).astype("int32"))
    lengths = paddle.to_tensor(np.full((B,), 5, np.int32))
    from paddle_tpu.utils.bench_timing import device_time_ms

    ms = device_time_ms(lambda: engine.train_batch(imgs, labels, lengths),
                        reps=iters, warmup=2)
    return {"ms_per_step": round(ms, 2),
            "imgs_per_s": round(B / (ms / 1e3), 1)}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-o", "--output", default=None)
    ap.add_argument("--models", default="llama,llama_moe,resnet50,ernie,ocr_rec")
    args = ap.parse_args()
    from paddle_tpu.utils.bench_timing import tpu_lock

    table = {"llama": bench_llama, "llama_moe": bench_llama_moe,
             "resnet50": bench_resnet50,
             "ernie": bench_ernie, "ocr_rec": bench_ocr_rec}
    results = {}
    for name in args.models.split(","):
        with tpu_lock(timeout_s=900.0) as locked:
            results[name] = table[name.strip()]()
        if not locked:
            results[name]["lock_contended"] = True
        print(name, results[name])
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=2)
        print(f"wrote {args.output}")


if __name__ == "__main__":
    main()
