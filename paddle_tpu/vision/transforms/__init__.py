"""Vision transforms (ref: python/paddle/vision/transforms/) — numpy-based
host-side preprocessing (HWC uint8 in, CHW float out by ToTensor)."""
from __future__ import annotations

import numbers
import random
from typing import List, Sequence

import numpy as np

from ...framework.core import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def _to_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img.value)
    return np.asarray(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_np(img).astype(np.float32)
        if self.data_format == "CHW":
            mean = self.mean.reshape(-1, 1, 1)
            std = self.std.reshape(-1, 1, 1)
        else:
            mean = self.mean
            std = self.std
        out = (arr - mean) / std
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = _to_np(img)
        import jax
        import jax.numpy as jnp

        h, w = self.size
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3)
        if chw:
            out_shape = (arr.shape[0], h, w)
        elif arr.ndim == 3:
            out_shape = (h, w, arr.shape[2])
        else:
            out_shape = (h, w)
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), out_shape, "linear")
        return np.asarray(out).astype(arr.dtype)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            arr = _to_np(img)
            return np.ascontiguousarray(arr[..., ::-1]) if arr.ndim == 3 and \
                arr.shape[0] in (1, 3) else np.ascontiguousarray(np.fliplr(arr))
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.ascontiguousarray(np.flipud(_to_np(img)))
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_np(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else \
                [self.padding] * 4
            arr = np.pad(arr, [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2))
        h, w = arr.shape[0], arr.shape[1]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = _to_np(img)
        h, w = arr.shape[0], arr.shape[1]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _to_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.ascontiguousarray(np.fliplr(_to_np(img)))


def vflip(img):
    return np.ascontiguousarray(np.flipud(_to_np(img)))


class Pad(BaseTransform):
    """ref transforms.Pad — pad HWC images on each border."""

    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        if isinstance(padding, numbers.Number):
            padding = [padding] * 4
        elif len(padding) == 2:
            padding = [padding[0], padding[1], padding[0], padding[1]]
        self.padding = padding  # left, top, right, bottom
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        arr = _to_np(img)
        l, t, r, b = self.padding
        pads = [(t, b), (l, r)] + [(0, 0)] * (arr.ndim - 2)
        if self.padding_mode == "constant":
            return np.pad(arr, pads, constant_values=self.fill)
        return np.pad(arr, pads, mode=self.padding_mode)


class Grayscale(BaseTransform):
    """ref transforms.Grayscale — ITU-R 601-2 luma transform."""

    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        raw = _to_np(img)
        arr = raw.astype(np.float32)
        if arr.ndim == 2:
            g = arr
        else:
            g = _rgb_to_gray(arr)
        g = g[..., None]
        if self.num_output_channels == 3:
            g = np.repeat(g, 3, axis=-1)
        return g.astype(raw.dtype)


def _rgb_to_gray(arr):
    """ITU-R 601-2 luma; arr float HWC-3."""
    return arr[..., 0] * 0.299 + arr[..., 1] * 0.587 + arr[..., 2] * 0.114


def _clip_to_dtype(out, dtype):
    return np.clip(out, 0, 255 if dtype == np.uint8 else np.inf).astype(dtype)


def _inverse_warp(arr, sy, sx, fill, out_shape=None):
    """Nearest-neighbor gather at source coords (sy, sx); out-of-bounds
    pixels get ``fill``. Shared by rotation/affine/perspective."""
    h, w = arr.shape[0], arr.shape[1]
    syi = np.round(sy).astype(np.int64)
    sxi = np.round(sx).astype(np.int64)
    valid = (syi >= 0) & (syi < h) & (sxi >= 0) & (sxi < w)
    shape = (out_shape or sy.shape) + arr.shape[2:]
    out = np.full(shape, fill, dtype=arr.dtype)
    out[valid] = arr[np.clip(syi, 0, h - 1), np.clip(sxi, 0, w - 1)][valid]
    return out


def _jitter_range(value, center=1.0):
    """Accept the reference's scalar-or-(min,max) forms: scalar v means
    U(center-v, center+v) clamped at 0; a sequence is used as-is."""
    if isinstance(value, (list, tuple)):
        lo, hi = float(value[0]), float(value[1])
    else:
        v = float(value)
        lo, hi = max(0.0, center - v), center + v
    return lo, hi


class BrightnessTransform(BaseTransform):
    """ref transforms.BrightnessTransform — scale by U(1-v, 1+v)."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value)

    def _apply_image(self, img):
        if self.range == (1.0, 1.0):
            return img
        arr = _to_np(img)
        f = random.uniform(*self.range)
        return _clip_to_dtype(arr.astype(np.float32) * f, arr.dtype)


class ContrastTransform(BaseTransform):
    """ref transforms.ContrastTransform — blend with the mean GRAY level
    (luma mean, matching adjust_contrast)."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value)

    def _apply_image(self, img):
        if self.range == (1.0, 1.0):
            return img
        raw = _to_np(img)
        arr = raw.astype(np.float32)
        f = random.uniform(*self.range)
        if arr.ndim == 3 and arr.shape[-1] == 3:
            pivot = _rgb_to_gray(arr).mean()
        else:
            pivot = arr.mean()
        out = pivot + f * (arr - pivot)
        return _clip_to_dtype(out, raw.dtype)


class SaturationTransform(BaseTransform):
    """ref transforms.SaturationTransform — blend with the grayscale image."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.range = _jitter_range(value)

    def _apply_image(self, img):
        if self.range == (1.0, 1.0):
            return img
        raw = _to_np(img)
        if raw.ndim != 3 or raw.shape[-1] != 3:
            return img  # saturation undefined off 3-channel RGB
        arr = raw.astype(np.float32)
        f = random.uniform(*self.range)
        gray = _rgb_to_gray(arr)[..., None]
        out = gray + f * (arr - gray)
        return _clip_to_dtype(out, raw.dtype)


class HueTransform(BaseTransform):
    """ref transforms.HueTransform — shift hue in HSV space by U(-v, v),
    v in [0, 0.5]."""

    def __init__(self, value, keys=None):
        super().__init__(keys)
        if isinstance(value, (list, tuple)):
            self.range = (float(value[0]), float(value[1]))
        else:
            v = float(value)
            self.range = (-v, v)

    def _apply_image(self, img):
        if self.range == (0.0, 0.0):
            return img
        arr = _to_np(img)
        if arr.ndim != 3 or arr.shape[-1] != 3:
            return img  # hue rotation is only defined on 3-channel RGB
        f = random.uniform(*self.range)
        x = arr.astype(np.float32) / (255.0 if arr.dtype == np.uint8 else 1.0)
        # RGB->HSV hue rotation (vectorized)
        mx, mn = x.max(-1), x.min(-1)
        diff = mx - mn + 1e-12
        h = np.zeros_like(mx)
        r, g, b = x[..., 0], x[..., 1], x[..., 2]
        h = np.where(mx == r, ((g - b) / diff) % 6, h)
        h = np.where(mx == g, (b - r) / diff + 2, h)
        h = np.where(mx == b, (r - g) / diff + 4, h)
        h = (h / 6.0 + f) % 1.0
        s = np.where(mx > 0, diff / (mx + 1e-12), 0)
        v = mx
        # HSV->RGB
        i = np.floor(h * 6.0)
        ff = h * 6.0 - i
        p = v * (1 - s)
        q = v * (1 - s * ff)
        t = v * (1 - s * (1 - ff))
        i = (i.astype(np.int32) % 6)[..., None]
        out = np.select(
            [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
            [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
             np.stack([p, v, t], -1), np.stack([p, q, v], -1),
             np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
        if arr.dtype == np.uint8:
            out = np.clip(out * 255.0, 0, 255)
        return out.astype(arr.dtype)


class ColorJitter(BaseTransform):
    """ref transforms.ColorJitter — random brightness/contrast/saturation/hue
    in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        super().__init__(keys)
        self._ts = [BrightnessTransform(brightness), ContrastTransform(contrast),
                    SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        ts = list(self._ts)
        random.shuffle(ts)
        for t in ts:
            img = t(img)
        return img


class RandomResizedCrop(BaseTransform):
    """ref transforms.RandomResizedCrop — random area/aspect crop, resized."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.scale = scale
        self.ratio = ratio
        self._resize = Resize(self.size, interpolation)

    def _apply_image(self, img):
        arr = _to_np(img)
        h, w = arr.shape[0], arr.shape[1]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = random.randint(0, h - th)
                j = random.randint(0, w - tw)
                return self._resize(arr[i:i + th, j:j + tw])
        # fallback (ref behavior): clamp aspect ratio, center crop
        in_ratio = w / h
        if in_ratio < self.ratio[0]:
            tw, th = w, int(round(w / self.ratio[0]))
        elif in_ratio > self.ratio[1]:
            th, tw = h, int(round(h * self.ratio[1]))
        else:
            th, tw = h, w
        i = (h - th) // 2
        j = (w - tw) // 2
        return self._resize(arr[i:i + th, j:j + tw])


class RandomRotation(BaseTransform):
    """ref transforms.RandomRotation — rotate by U(-degrees, degrees) about
    the center (nearest-neighbor resample, constant fill)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        arr = _to_np(img)
        angle = np.deg2rad(random.uniform(*self.degrees))
        h, w = arr.shape[0], arr.shape[1]
        ca, sa = np.cos(angle), np.sin(angle)
        if self.expand:
            oh = int(np.ceil(abs(h * ca) + abs(w * sa)))
            ow = int(np.ceil(abs(w * ca) + abs(h * sa)))
        else:
            oh, ow = h, w
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        ocy, ocx = (oh - 1) / 2.0, (ow - 1) / 2.0
        if self.center is not None and not self.expand:
            cx, cy = self.center
            ocy, ocx = cy, cx
        yy, xx = np.meshgrid(np.arange(oh), np.arange(ow), indexing="ij")
        # inverse map: source = R(-angle) · (dst - oc) + c
        sy = ca * (yy - ocy) - sa * (xx - ocx) + cy
        sx = sa * (yy - ocy) + ca * (xx - ocx) + cx
        return _inverse_warp(arr, sy, sx, self.fill, out_shape=(oh, ow))


class RandomErasing(BaseTransform):
    """ref transforms.RandomErasing — erase a random rectangle (value or
    per-pixel noise)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        was_tensor = isinstance(img, Tensor)
        arr = np.array(_to_np(img))
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and \
            arr.shape[-1] not in (1, 3)
        h, w = (arr.shape[1], arr.shape[2]) if chw else (arr.shape[0], arr.shape[1])
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = np.exp(random.uniform(np.log(self.ratio[0]),
                                       np.log(self.ratio[1])))
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                if isinstance(self.value, str) and self.value == "random":
                    # seed from the random module so random.seed() makes the
                    # whole pipeline reproducible
                    rng = np.random.RandomState(random.getrandbits(32))
                    patch = rng.rand(
                        *(arr[..., i:i + eh, j:j + ew].shape if chw else
                          arr[i:i + eh, j:j + ew].shape)) * (
                        255 if arr.dtype == np.uint8 else 1)
                    patch = patch.astype(arr.dtype)
                else:
                    patch = np.asarray(self.value, dtype=arr.dtype)
                    if patch.ndim == 1:  # per-channel fill
                        patch = patch.reshape((-1, 1, 1) if chw else (1, 1, -1))
                if chw:
                    arr[..., i:i + eh, j:j + ew] = patch
                else:
                    arr[i:i + eh, j:j + ew] = patch
                return Tensor(arr) if was_tensor else arr
        return Tensor(arr) if was_tensor else arr


class RandomAffine(BaseTransform):
    """ref transforms.RandomAffine — rotation/translate/scale/shear sampled
    per call, nearest-neighbor inverse warp."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.translate = translate
        self.scale_rng = scale
        self.shear = shear
        self.fill = fill
        self.center = center

    def _apply_image(self, img):
        arr = _to_np(img)
        h, w = arr.shape[0], arr.shape[1]
        angle = np.deg2rad(random.uniform(*self.degrees))
        tx = ty = 0.0
        if self.translate:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale_rng) if self.scale_rng else 1.0
        if isinstance(self.shear, numbers.Number):
            sh = np.deg2rad(random.uniform(-self.shear, self.shear))
        elif isinstance(self.shear, (list, tuple)) and len(self.shear) >= 2:
            sh = np.deg2rad(random.uniform(self.shear[0], self.shear[1]))
        else:
            sh = 0.0
        if self.center is not None:
            cx, cy = self.center
        else:
            cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        ca, sa = np.cos(angle), np.sin(angle)
        # forward affine A = R·Shear·Scale; inverse-map each dst pixel
        a11, a12 = ca * sc, (-sa + ca * np.tan(sh)) * sc
        a21, a22 = sa * sc, (ca + sa * np.tan(sh)) * sc
        det = a11 * a22 - a12 * a21
        i11, i12, i21, i22 = a22 / det, -a12 / det, -a21 / det, a11 / det
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        dy, dx = yy - cy - ty, xx - cx - tx
        sy = i11 * dy + i12 * dx + cy
        sx = i21 * dy + i22 * dx + cx
        return _inverse_warp(arr, sy, sx, self.fill)


AffineTransform = RandomAffine  # legacy alias used by some reference code


class RandomPerspective(BaseTransform):
    """ref transforms.RandomPerspective — random corner displacement warp."""

    def __init__(self, prob=0.5, distortion_scale=0.5, interpolation="nearest",
                 fill=0, keys=None):
        super().__init__(keys)
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.fill = fill

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        arr = _to_np(img)
        h, w = arr.shape[0], arr.shape[1]
        d = self.distortion_scale
        dh, dw = int(h * d / 2), int(w * d / 2)

        def jit(y, x):
            return (y + random.randint(-dh, dh) if dh else y,
                    x + random.randint(-dw, dw) if dw else x)

        src = np.float64([[0, 0], [0, w - 1], [h - 1, 0], [h - 1, w - 1]])
        dst = np.float64([jit(0, 0), jit(0, w - 1), jit(h - 1, 0),
                          jit(h - 1, w - 1)])
        # solve the 8-dof homography dst->src (inverse map)
        A, b = [], []
        for (ys, xs), (yd, xd) in zip(src, dst):
            A.append([yd, xd, 1, 0, 0, 0, -ys * yd, -ys * xd])
            b.append(ys)
            A.append([0, 0, 0, yd, xd, 1, -xs * yd, -xs * xd])
            b.append(xs)
        try:
            hvec = np.linalg.solve(np.float64(A), np.float64(b))
        except np.linalg.LinAlgError:
            return arr
        m = np.append(hvec, 1.0).reshape(3, 3)
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        den = m[2, 0] * yy + m[2, 1] * xx + 1.0
        sy = (m[0, 0] * yy + m[0, 1] * xx + m[0, 2]) / den
        sx = (m[1, 0] * yy + m[1, 1] * xx + m[1, 2]) / den
        return _inverse_warp(arr, sy, sx, self.fill)


from . import functional  # noqa: E402,F401
from .functional import (adjust_brightness, adjust_contrast,  # noqa: E402,F401
                         adjust_hue, affine, center_crop, crop, erase, pad,
                         perspective, rotate, to_grayscale)
