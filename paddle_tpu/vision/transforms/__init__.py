"""Vision transforms (ref: python/paddle/vision/transforms/) — numpy-based
host-side preprocessing (HWC uint8 in, CHW float out by ToTensor)."""
from __future__ import annotations

import numbers
import random
from typing import List, Sequence

import numpy as np

from ...framework.core import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


def _to_np(img):
    if isinstance(img, Tensor):
        return np.asarray(img.value)
    return np.asarray(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if arr.dtype == np.uint8:
            arr = arr.astype(np.float32) / 255.0
        else:
            arr = arr.astype(np.float32)
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        super().__init__(keys)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def _apply_image(self, img):
        arr = _to_np(img).astype(np.float32)
        if self.data_format == "CHW":
            mean = self.mean.reshape(-1, 1, 1)
            std = self.std.reshape(-1, 1, 1)
        else:
            mean = self.mean
            std = self.std
        out = (arr - mean) / std
        return Tensor(out) if isinstance(img, Tensor) else out


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = _to_np(img)
        import jax
        import jax.numpy as jnp

        h, w = self.size
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3)
        if chw:
            out_shape = (arr.shape[0], h, w)
        elif arr.ndim == 3:
            out_shape = (h, w, arr.shape[2])
        else:
            out_shape = (h, w)
        out = jax.image.resize(jnp.asarray(arr, jnp.float32), out_shape, "linear")
        return np.asarray(out).astype(arr.dtype)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            arr = _to_np(img)
            return np.ascontiguousarray(arr[..., ::-1]) if arr.ndim == 3 and \
                arr.shape[0] in (1, 3) else np.ascontiguousarray(np.fliplr(arr))
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return np.ascontiguousarray(np.flipud(_to_np(img)))
        return img


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def _apply_image(self, img):
        arr = _to_np(img)
        if self.padding:
            p = self.padding if isinstance(self.padding, (list, tuple)) else \
                [self.padding] * 4
            arr = np.pad(arr, [(p[1], p[3]), (p[0], p[2])] + [(0, 0)] * (arr.ndim - 2))
        h, w = arr.shape[0], arr.shape[1]
        th, tw = self.size
        i = random.randint(0, max(h - th, 0))
        j = random.randint(0, max(w - tw, 0))
        return arr[i:i + th, j:j + tw]


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def _apply_image(self, img):
        arr = _to_np(img)
        h, w = arr.shape[0], arr.shape[1]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        arr = _to_np(img)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(self.order)


def to_tensor(pic, data_format="CHW"):
    return ToTensor(data_format)(pic)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


def hflip(img):
    return np.ascontiguousarray(np.fliplr(_to_np(img)))


def vflip(img):
    return np.ascontiguousarray(np.flipud(_to_np(img)))
