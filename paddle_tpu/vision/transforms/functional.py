"""Functional transform ops (ref: python/paddle/vision/transforms/
functional.py — adjust_brightness/contrast/hue, crop/center_crop, pad,
rotate, affine, perspective, erase, to_grayscale).

Deterministic single-image forms of the random transform classes in
``__init__`` — they share the same numpy warp/color machinery
(_inverse_warp, _rgb_to_gray, the HSV rotation), so class and functional
paths cannot drift."""
from __future__ import annotations

import numbers

import numpy as np

from ...framework.core import Tensor


def _np(img):
    from . import _to_np

    return _to_np(img)


def _wrap_like(out, img):
    return Tensor(np.ascontiguousarray(out)) if isinstance(img, Tensor) \
        else out


def adjust_brightness(img, brightness_factor):
    """pixel * factor, clipped (ref functional.adjust_brightness)."""
    from . import _clip_to_dtype

    arr = _np(img)
    out = _clip_to_dtype(arr.astype(np.float32) * float(brightness_factor),
                         arr.dtype)
    return _wrap_like(out, img)


def adjust_contrast(img, contrast_factor):
    """blend with the mean luma level (ref functional.adjust_contrast)."""
    from . import _clip_to_dtype, _rgb_to_gray

    raw = _np(img)
    arr = raw.astype(np.float32)
    pivot = (_rgb_to_gray(arr).mean()
             if arr.ndim == 3 and arr.shape[-1] == 3 else arr.mean())
    out = pivot + float(contrast_factor) * (arr - pivot)
    return _wrap_like(_clip_to_dtype(out, raw.dtype), img)


def adjust_hue(img, hue_factor):
    """rotate hue by ``hue_factor`` in [-0.5, 0.5] turns (ref
    functional.adjust_hue); shares HueTransform's vectorized HSV math."""
    from . import HueTransform

    assert -0.5 <= hue_factor <= 0.5, hue_factor
    t = HueTransform.__new__(HueTransform)
    t.range = (float(hue_factor), float(hue_factor))
    t.keys = None
    out = t._apply_image(_np(img))
    return _wrap_like(out, img)


def to_grayscale(img, num_output_channels=1):
    """ITU-R 601-2 luma (ref functional.to_grayscale)."""
    from . import Grayscale

    out = Grayscale(num_output_channels)._apply_image(_np(img))
    return _wrap_like(out, img)


def crop(img, top, left, height, width):
    """HWC crop (ref functional.crop)."""
    arr = _np(img)
    return _wrap_like(arr[top:top + height, left:left + width], img)


def center_crop(img, output_size):
    """ref functional.center_crop."""
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    arr = _np(img)
    h, w = arr.shape[0], arr.shape[1]
    th, tw = output_size
    top = max(0, (h - th) // 2)
    left = max(0, (w - tw) // 2)
    return crop(img, top, left, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    """ref functional.pad — delegates to the Pad transform."""
    from . import Pad

    out = Pad(padding, fill=fill,
              padding_mode=padding_mode)._apply_image(_np(img))
    return _wrap_like(out, img)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """ref functional.rotate — deterministic RandomRotation."""
    from . import RandomRotation

    t = RandomRotation.__new__(RandomRotation)
    t.degrees = (float(angle), float(angle))
    t.expand = expand
    t.center = center
    t.fill = fill
    t.keys = None
    return _wrap_like(t._apply_image(_np(img)), img)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    """ref functional.affine — rotation/translate/scale/shear composed into
    ONE inverse map (translation inside the matrix: out-of-range pixels get
    ``fill``, never wrap)."""
    from . import _inverse_warp

    arr = _np(img)
    h, w = arr.shape[0], arr.shape[1]
    tx, ty = (translate if translate else (0, 0))
    sc = float(scale) if scale else 1.0
    sh = np.deg2rad(float(shear)) if isinstance(shear, numbers.Number) \
        else (np.deg2rad(float(shear[0])) if shear else 0.0)
    ang = np.deg2rad(float(angle))
    if center is not None:
        cx, cy = center
    else:
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    ca, sa = np.cos(ang), np.sin(ang)
    a11, a12 = ca * sc, (-sa + ca * np.tan(sh)) * sc
    a21, a22 = sa * sc, (ca + sa * np.tan(sh)) * sc
    det = a11 * a22 - a12 * a21
    i11, i12, i21, i22 = a22 / det, -a12 / det, -a21 / det, a11 / det
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    dy, dx = yy - cy - float(ty), xx - cx - float(tx)
    sy = i11 * dy + i12 * dx + cy
    sx = i21 * dy + i22 * dx + cx
    return _wrap_like(_inverse_warp(arr, sy, sx, fill), img)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """ref functional.perspective — warp mapping ``startpoints`` (corners,
    (x, y)) to ``endpoints``; shares RandomPerspective's homography solve."""
    from . import _inverse_warp

    arr = _np(img)
    h, w = arr.shape[0], arr.shape[1]
    # reference gives (x, y); the solver below works in (y, x)
    src = np.float64([[p[1], p[0]] for p in startpoints])
    dst = np.float64([[p[1], p[0]] for p in endpoints])
    A, b = [], []
    for (ys, xs), (yd, xd) in zip(src, dst):
        A.append([yd, xd, 1, 0, 0, 0, -ys * yd, -ys * xd])
        b.append(ys)
        A.append([0, 0, 0, yd, xd, 1, -xs * yd, -xs * xd])
        b.append(xs)
    hvec = np.linalg.solve(np.float64(A), np.float64(b))
    m = np.append(hvec, 1.0).reshape(3, 3)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    den = m[2, 0] * yy + m[2, 1] * xx + 1.0
    sy = (m[0, 0] * yy + m[0, 1] * xx + m[0, 2]) / den
    sx = (m[1, 0] * yy + m[1, 1] * xx + m[1, 2]) / den
    return _wrap_like(_inverse_warp(arr, sy, sx, fill), img)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase the rectangle [i:i+h, j:j+w] with value ``v`` (ref
    functional.erase); CHW tensors and HWC arrays both supported."""
    was_tensor = isinstance(img, Tensor)
    arr = np.array(_np(img))
    # paddle contract: Tensor input is CHW, ndarray/PIL is HWC — branch on
    # the type, not on shape guesses (a (3, H, 3) strip would misclassify)
    chw = was_tensor and arr.ndim == 3
    val = np.asarray(v, dtype=arr.dtype)
    if chw:
        arr[..., i:i + h, j:j + w] = (
            val.reshape(-1, 1, 1) if val.ndim == 1 else val)
    else:
        arr[i:i + h, j:j + w] = (
            val.reshape(1, 1, -1) if val.ndim == 1 else val)
    return Tensor(arr) if was_tensor else arr
