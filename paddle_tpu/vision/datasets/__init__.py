"""Vision datasets (ref: python/paddle/vision/datasets/).

Zero-egress environment: datasets generate deterministic synthetic data with
the real formats/shapes when the on-disk files are absent (download=False
semantics), so training recipes run end-to-end.
"""
from __future__ import annotations

import os

import numpy as np

from ...io import Dataset


class _SyntheticImageDataset(Dataset):
    """Deterministic fake data with the correct schema."""

    def __init__(self, n, shape, num_classes, transform=None, seed=0):
        self._n = n
        self._shape = shape
        self._num_classes = num_classes
        self.transform = transform
        self._seed = seed

    def __len__(self):
        return self._n

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed + idx)
        img = rng.randint(0, 256, self._shape, np.uint8)
        label = np.asarray(rng.randint(0, self._num_classes), np.int64)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class MNIST(_SyntheticImageDataset):
    def __init__(self, image_path=None, label_path=None, mode="train", transform=None,
                 download=True, backend=None):
        n = 60000 if mode == "train" else 10000
        # keep tests fast: cap synthetic size
        super().__init__(min(n, 2048), (28, 28, 1), 10, transform)


class FashionMNIST(MNIST):
    pass


class Cifar10(_SyntheticImageDataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend=None):
        n = 50000 if mode == "train" else 10000
        super().__init__(min(n, 2048), (32, 32, 3), 10, transform)


class Cifar100(_SyntheticImageDataset):
    def __init__(self, data_file=None, mode="train", transform=None, download=True,
                 backend=None):
        n = 50000 if mode == "train" else 10000
        super().__init__(min(n, 2048), (32, 32, 3), 100, transform)


class Flowers(_SyntheticImageDataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None, mode="train",
                 transform=None, download=True, backend=None):
        super().__init__(1024, (224, 224, 3), 102, transform)


class VOC2012(Dataset):
    """Segmentation dataset (ref: python/paddle/vision/datasets/voc2012.py).

    Samples: (image HWC uint8, label map HW uint8 with class ids 0..20 and
    255 = ignore). Synthetic fallback when the tarball is absent.
    """

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self._n = 256
        self.transform = transform
        self._seed = {"train": 0, "test": 1, "valid": 2}.get(mode, 0)

    def __len__(self):
        return self._n

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed * 100003 + idx)
        img = rng.randint(0, 256, (224, 224, 3), np.uint8)
        label = rng.randint(0, 21, (224, 224), np.uint8)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.samples = []
        self.classes = []
        if os.path.isdir(root):
            self.classes = sorted(d for d in os.listdir(root)
                                  if os.path.isdir(os.path.join(root, d)))
            for ci, c in enumerate(self.classes):
                for f in sorted(os.listdir(os.path.join(root, c))):
                    self.samples.append((os.path.join(root, c, f), ci))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = np.load(path) if path.endswith(".npy") else self._load_image(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray(label, np.int64)

    @staticmethod
    def _load_image(path):
        try:
            from PIL import Image

            return np.asarray(Image.open(path).convert("RGB"))
        except ImportError:
            raise ImportError("PIL is required for image folders")


ImageFolder = DatasetFolder
