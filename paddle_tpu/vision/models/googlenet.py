"""GoogLeNet / InceptionV1 (ref: python/paddle/vision/models/googlenet.py)."""
from __future__ import annotations

from ...tensor.manipulation import concat
from ...nn import (AdaptiveAvgPool2D, AvgPool2D, Conv2D, Dropout, Flatten, Linear, MaxPool2D,
                   ReLU, Sequential)
from ...nn.layer_base import Layer


class ConvLayer(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1, groups=1):
        super().__init__()
        self._conv = Conv2D(num_channels, num_filters, filter_size, stride=stride,
                            padding=(filter_size - 1) // 2, groups=groups, bias_attr=False)
        self._relu = ReLU()

    def forward(self, x):
        return self._relu(self._conv(x))


class Inception(Layer):
    def __init__(self, input_channels, output_channels, filter1, filter3R, filter3,
                 filter5R, filter5, proj):
        super().__init__()
        self._conv1 = ConvLayer(input_channels, filter1, 1)
        self._conv3r = ConvLayer(input_channels, filter3R, 1)
        self._conv3 = ConvLayer(filter3R, filter3, 3)
        self._conv5r = ConvLayer(input_channels, filter5R, 1)
        self._conv5 = ConvLayer(filter5R, filter5, 5)
        self._pool = MaxPool2D(kernel_size=3, stride=1, padding=1)
        self._convprj = ConvLayer(input_channels, proj, 1)

    def forward(self, x):
        return concat([self._conv1(x), self._conv3(self._conv3r(x)),
                       self._conv5(self._conv5r(x)), self._convprj(self._pool(x))], axis=1)


class GoogLeNet(Layer):
    """Returns (out, out1, out2) — main logits + two aux heads, like the ref."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self._conv = ConvLayer(3, 64, 7, 2)
        self._pool = MaxPool2D(kernel_size=3, stride=2)
        self._conv_1 = ConvLayer(64, 64, 1)
        self._conv_2 = ConvLayer(64, 192, 3)
        self._ince3a = Inception(192, 192, 64, 96, 128, 16, 32, 32)
        self._ince3b = Inception(256, 256, 128, 128, 192, 32, 96, 64)
        self._ince4a = Inception(480, 480, 192, 96, 208, 16, 48, 64)
        self._ince4b = Inception(512, 512, 160, 112, 224, 24, 64, 64)
        self._ince4c = Inception(512, 512, 128, 128, 256, 24, 64, 64)
        self._ince4d = Inception(512, 512, 112, 144, 288, 32, 64, 64)
        self._ince4e = Inception(528, 528, 256, 160, 320, 32, 128, 128)
        self._ince5a = Inception(832, 832, 256, 160, 320, 32, 128, 128)
        self._ince5b = Inception(832, 832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self._pool_5 = AdaptiveAvgPool2D(1)
        self._drop = Dropout(p=0.4)
        if num_classes > 0:
            self._fc_out = Linear(1024, num_classes)
            self._flatten = Flatten()
        # aux classifiers
        self._pool_o1 = AvgPool2D(kernel_size=5, stride=3)
        self._conv_o1 = ConvLayer(512, 128, 1)
        self._fc_o1 = Linear(1152, 1024)
        self._drop_o1 = Dropout(p=0.7)
        self._out1 = Linear(1024, num_classes) if num_classes > 0 else None
        self._relu = ReLU()
        self._pool_o2 = AvgPool2D(kernel_size=5, stride=3)
        self._conv_o2 = ConvLayer(528, 128, 1)
        self._fc_o2 = Linear(1152, 1024)
        self._drop_o2 = Dropout(p=0.7)
        self._out2 = Linear(1024, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self._pool(self._conv(x))
        x = self._pool(self._conv_2(self._conv_1(x)))
        x = self._ince3b(self._ince3a(x))
        x = self._pool(x)
        ince4a = self._ince4a(x)
        ince4d = self._ince4d(self._ince4c(self._ince4b(ince4a)))
        x = self._pool(self._ince4e(ince4d))
        x = self._ince5b(self._ince5a(x))
        if self.with_pool:
            x = self._pool_5(x)
        x = self._drop(x)
        if self.num_classes <= 0:
            return x
        out = self._fc_out(self._flatten(x))

        o1 = self._conv_o1(self._pool_o1(ince4a))
        o1 = self._relu(self._fc_o1(self._flatten(o1)))
        out1 = self._out1(self._drop_o1(o1))

        o2 = self._conv_o2(self._pool_o2(ince4d))
        o2 = self._relu(self._fc_o2(self._flatten(o2)))
        out2 = self._out2(self._drop_o2(o2))
        return out, out1, out2


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled; load via state_dict")
    return GoogLeNet(**kwargs)
