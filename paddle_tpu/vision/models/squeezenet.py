"""SqueezeNet (ref: python/paddle/vision/models/squeezenet.py)."""
from __future__ import annotations

from ...tensor.manipulation import concat
from ...nn import AdaptiveAvgPool2D, Conv2D, Dropout, Flatten, MaxPool2D, ReLU, Sequential
from ...nn.layer_base import Layer


class MakeFireConv(Layer):
    def __init__(self, input_channels, output_channels, filter_size, padding=0):
        super().__init__()
        self._conv = Conv2D(input_channels, output_channels, filter_size, padding=padding)
        self._relu = ReLU()

    def forward(self, x):
        return self._relu(self._conv(x))


class MakeFire(Layer):
    def __init__(self, input_channels, squeeze_channels, expand1x1_channels,
                 expand3x3_channels):
        super().__init__()
        self._conv = MakeFireConv(input_channels, squeeze_channels, 1)
        self._conv_path1 = MakeFireConv(squeeze_channels, expand1x1_channels, 1)
        self._conv_path2 = MakeFireConv(squeeze_channels, expand3x3_channels, 3, padding=1)

    def forward(self, x):
        x = self._conv(x)
        return concat([self._conv_path1(x), self._conv_path2(x)], axis=1)


class SqueezeNet(Layer):
    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        assert version in ("1.0", "1.1"), "version must be '1.0' or '1.1'"
        self.version = version
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            self._conv = Conv2D(3, 96, 7, stride=2)
            self._pool = MaxPool2D(3, stride=2)
            fires = [MakeFire(96, 16, 64, 64), MakeFire(128, 16, 64, 64),
                     MakeFire(128, 32, 128, 128)]
            fires2 = [MakeFire(256, 32, 128, 128), MakeFire(256, 48, 192, 192),
                      MakeFire(384, 48, 192, 192), MakeFire(384, 64, 256, 256)]
            fires3 = [MakeFire(512, 64, 256, 256)]
        else:
            self._conv = Conv2D(3, 64, 3, stride=2, padding=1)
            self._pool = MaxPool2D(3, stride=2)
            fires = [MakeFire(64, 16, 64, 64), MakeFire(128, 16, 64, 64)]
            fires2 = [MakeFire(128, 32, 128, 128), MakeFire(256, 32, 128, 128)]
            fires3 = [MakeFire(256, 48, 192, 192), MakeFire(384, 48, 192, 192),
                      MakeFire(384, 64, 256, 256), MakeFire(512, 64, 256, 256)]
        self._relu = ReLU()
        self._stage1 = Sequential(*fires)
        self._stage2 = Sequential(*fires2)
        self._stage3 = Sequential(*fires3)
        if num_classes > 0:
            self._drop = Dropout(p=0.5)
            self._conv9 = Conv2D(512, num_classes, 1)
            self._flatten = Flatten()
        if with_pool:
            self._avg_pool = AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = self._relu(self._conv(x))
        x = self._pool(x)
        x = self._stage1(x)
        x = self._pool(x)
        x = self._stage2(x)
        if self.version == "1.1":
            x = self._pool(x)
        x = self._stage3(x)
        if self.num_classes > 0:
            x = self._relu(self._conv9(self._drop(x)))
        if self.with_pool:
            x = self._avg_pool(x)
        if self.num_classes > 0:
            x = self._flatten(x)
        return x


def _squeezenet(version, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled; load via state_dict")
    return SqueezeNet(version=version, **kwargs)


def squeezenet1_0(pretrained=False, **kwargs):
    return _squeezenet("1.0", pretrained, **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    return _squeezenet("1.1", pretrained, **kwargs)
