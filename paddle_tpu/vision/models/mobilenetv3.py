"""MobileNetV3 small/large (ref: python/paddle/vision/models/mobilenetv3.py)."""
from __future__ import annotations

from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, Conv2D, Dropout, Flatten, Hardsigmoid,
                   Hardswish, Linear, ReLU, Sequential)
from ...nn.layer_base import Layer


def _make_divisible(v, divisor=8, min_value=None):
    if min_value is None:
        min_value = divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvNormActivation(Sequential):
    def __init__(self, in_channels, out_channels, kernel_size=3, stride=1, padding=None,
                 groups=1, activation_layer=ReLU):
        if padding is None:
            padding = (kernel_size - 1) // 2
        layers = [Conv2D(in_channels, out_channels, kernel_size, stride=stride,
                         padding=padding, groups=groups, bias_attr=False),
                  BatchNorm2D(out_channels)]
        if activation_layer is not None:
            layers.append(activation_layer())
        super().__init__(*layers)


class SqueezeExcitation(Layer):
    def __init__(self, input_channels, squeeze_channels):
        super().__init__()
        self.avgpool = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(input_channels, squeeze_channels, 1)
        self.fc2 = Conv2D(squeeze_channels, input_channels, 1)
        self.relu = ReLU()
        self.hardsigmoid = Hardsigmoid()

    def forward(self, x):
        scale = self.relu(self.fc1(self.avgpool(x)))
        return x * self.hardsigmoid(self.fc2(scale))


class InvertedResidualConfig:
    def __init__(self, in_channels, kernel, expanded_channels, out_channels, use_se,
                 activation, stride, scale=1.0):
        self.in_channels = _make_divisible(in_channels * scale)
        self.kernel = kernel
        self.expanded_channels = _make_divisible(expanded_channels * scale)
        self.out_channels = _make_divisible(out_channels * scale)
        self.use_se = use_se
        self.use_hs = activation == "hardswish"
        self.stride = stride


class InvertedResidual(Layer):
    def __init__(self, cfg: InvertedResidualConfig):
        super().__init__()
        self.use_res_connect = cfg.stride == 1 and cfg.in_channels == cfg.out_channels
        act = Hardswish if cfg.use_hs else ReLU
        layers = []
        if cfg.expanded_channels != cfg.in_channels:
            layers.append(ConvNormActivation(cfg.in_channels, cfg.expanded_channels,
                                             kernel_size=1, activation_layer=act))
        layers.append(ConvNormActivation(cfg.expanded_channels, cfg.expanded_channels,
                                         kernel_size=cfg.kernel, stride=cfg.stride,
                                         groups=cfg.expanded_channels, activation_layer=act))
        if cfg.use_se:
            layers.append(SqueezeExcitation(cfg.expanded_channels,
                                            _make_divisible(cfg.expanded_channels // 4)))
        layers.append(ConvNormActivation(cfg.expanded_channels, cfg.out_channels,
                                         kernel_size=1, activation_layer=None))
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        if self.use_res_connect:
            out = out + x
        return out


class MobileNetV3(Layer):
    def __init__(self, config, last_channel, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.config = config
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        firstconv_out = config[0].in_channels
        lastconv_in = config[-1].out_channels
        lastconv_out = 6 * lastconv_in
        self.conv = ConvNormActivation(3, firstconv_out, kernel_size=3, stride=2,
                                       activation_layer=Hardswish)
        self.blocks = Sequential(*[InvertedResidual(cfg) for cfg in config])
        self.lastconv = ConvNormActivation(lastconv_in, lastconv_out, kernel_size=1,
                                           activation_layer=Hardswish)
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(lastconv_out, last_channel), Hardswish(), Dropout(0.2),
                Linear(last_channel, num_classes))
            self.flatten = Flatten()

    def forward(self, x):
        x = self.lastconv(self.blocks(self.conv(x)))
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = self.classifier(self.flatten(x))
        return x


def _small_cfg(scale):
    c = lambda *a: InvertedResidualConfig(*a, scale=scale)
    return [c(16, 3, 16, 16, True, "relu", 2), c(16, 3, 72, 24, False, "relu", 2),
            c(24, 3, 88, 24, False, "relu", 1), c(24, 5, 96, 40, True, "hardswish", 2),
            c(40, 5, 240, 40, True, "hardswish", 1), c(40, 5, 240, 40, True, "hardswish", 1),
            c(40, 5, 120, 48, True, "hardswish", 1), c(48, 5, 144, 48, True, "hardswish", 1),
            c(48, 5, 288, 96, True, "hardswish", 2), c(96, 5, 576, 96, True, "hardswish", 1),
            c(96, 5, 576, 96, True, "hardswish", 1)]


def _large_cfg(scale):
    c = lambda *a: InvertedResidualConfig(*a, scale=scale)
    return [c(16, 3, 16, 16, False, "relu", 1), c(16, 3, 64, 24, False, "relu", 2),
            c(24, 3, 72, 24, False, "relu", 1), c(24, 5, 72, 40, True, "relu", 2),
            c(40, 5, 120, 40, True, "relu", 1), c(40, 5, 120, 40, True, "relu", 1),
            c(40, 3, 240, 80, False, "hardswish", 2), c(80, 3, 200, 80, False, "hardswish", 1),
            c(80, 3, 184, 80, False, "hardswish", 1), c(80, 3, 184, 80, False, "hardswish", 1),
            c(80, 3, 480, 112, True, "hardswish", 1), c(112, 3, 672, 112, True, "hardswish", 1),
            c(112, 5, 672, 160, True, "hardswish", 2), c(160, 5, 960, 160, True, "hardswish", 1),
            c(160, 5, 960, 160, True, "hardswish", 1)]


class MobileNetV3Small(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_small_cfg(scale), _make_divisible(1024 * scale),
                         scale=scale, num_classes=num_classes, with_pool=with_pool)


class MobileNetV3Large(MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_large_cfg(scale), _make_divisible(1280 * scale),
                         scale=scale, num_classes=num_classes, with_pool=with_pool)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled; load via state_dict")
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled; load via state_dict")
    return MobileNetV3Large(scale=scale, **kwargs)
