"""OCR model family — the conv-heavy path of BASELINE.json config 5
(PP-OCRv4 det+rec).

The reference repo itself carries only the kernel substrate for OCR
(conv/pool/interpolate PHI kernels, warpctc op — ref
paddle/phi/kernels/gpu/conv_kernel.cu, paddle/fluid/operators/ctc_align_op*);
the models live in PaddleOCR on top of paddle.vision backbones. Here the
same pair is provided natively:

- ``DBNet``: Differentiable-Binarization text detector — light 4-stage conv
  backbone, FPN neck (top-down adds + upsampled concat), DB head emitting
  probability/threshold maps and the differentiable binarization
  ``1/(1+exp(-k(P-T)))``.
- ``CRNN``: text recognizer — VGG-style conv tower pooling height to 1,
  2-layer bidirectional LSTM encoder over width, CTC projection. Pairs with
  ``F.ctc_loss``.

Both are MXU-friendly: plain NCHW convs XLA lowers onto the MXU, no dynamic
shapes, upsampling via nearest interpolate.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.dispatch import apply_op
from ...nn import (BatchNorm2D, Conv2D, Layer, LayerList, Linear, MaxPool2D,
                   ReLU, Sequential)
from ...nn import functional as F
from ...nn.layer.rnn import LSTM

__all__ = ["DBNet", "CRNN", "db_loss", "crnn_ctc_loss", "dbnet", "crnn"]


def _conv_bn(cin, cout, k=3, stride=1, padding=1):
    return Sequential(
        Conv2D(cin, cout, k, stride=stride, padding=padding, bias_attr=False),
        BatchNorm2D(cout), ReLU())


class _Backbone(Layer):
    """4-stage strided conv backbone; returns features at 1/4..1/32."""

    def __init__(self, in_channels=3, base=16):
        super().__init__()
        c = base
        self.stem = Sequential(_conv_bn(in_channels, c, stride=2),
                               _conv_bn(c, c))
        self.stages = LayerList([
            Sequential(_conv_bn(c, 2 * c, stride=2), _conv_bn(2 * c, 2 * c)),
            Sequential(_conv_bn(2 * c, 4 * c, stride=2), _conv_bn(4 * c, 4 * c)),
            Sequential(_conv_bn(4 * c, 8 * c, stride=2), _conv_bn(8 * c, 8 * c)),
            Sequential(_conv_bn(8 * c, 16 * c, stride=2), _conv_bn(16 * c, 16 * c)),
        ])
        self.out_channels = [2 * c, 4 * c, 8 * c, 16 * c]

    def forward(self, x):
        x = self.stem(x)
        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        return feats  # strides 4, 8, 16, 32


class _FPN(Layer):
    """DB-style neck: lateral 1x1 + top-down nearest-upsample adds, then each
    level reduced and upsampled to 1/4 scale and concatenated."""

    def __init__(self, in_channels, out_channels=96):
        super().__init__()
        self.laterals = LayerList([
            Conv2D(c, out_channels, 1, bias_attr=False) for c in in_channels])
        quarter = out_channels // 4
        self.smooth = LayerList([
            Conv2D(out_channels, quarter, 3, padding=1, bias_attr=False)
            for _ in in_channels])
        self.out_channels = quarter * 4

    def forward(self, feats):
        lat = [l(f) for l, f in zip(self.laterals, feats)]
        for i in range(len(lat) - 2, -1, -1):
            up = F.interpolate(lat[i + 1], size=lat[i].shape[2:], mode="nearest")
            lat[i] = lat[i] + up
        outs = []
        tgt = lat[0].shape[2:]
        for s, f in zip(self.smooth, lat):
            f = s(f)
            if tuple(f.shape[2:]) != tuple(tgt):
                f = F.interpolate(f, size=tgt, mode="nearest")
            outs.append(f)
        from ...tensor.manipulation import concat

        return concat(outs, axis=1)


class _DBHead(Layer):
    """Conv → upsample ×4 → 1-channel sigmoid map."""

    def __init__(self, in_channels):
        super().__init__()
        mid = in_channels // 4
        self.conv1 = _conv_bn(in_channels, mid)
        self.conv2 = Conv2D(mid, 1, 1)

    def forward(self, x):
        x = self.conv1(x)
        x = F.interpolate(x, scale_factor=4, mode="nearest")
        return F.sigmoid(self.conv2(x))


class DBNet(Layer):
    """Differentiable Binarization detector (det side of config 5).

    forward → dict with 'maps': (B, 3, H, W) = prob, thresh, binary maps in
    train mode; (B, 1, H, W) prob map in eval.
    """

    def __init__(self, in_channels=3, base_channels=16, k=50.0):
        super().__init__()
        self.backbone = _Backbone(in_channels, base_channels)
        self.neck = _FPN(self.backbone.out_channels)
        self.prob_head = _DBHead(self.neck.out_channels)
        self.thresh_head = _DBHead(self.neck.out_channels)
        self.k = k

    def forward(self, x):
        feat = self.neck(self.backbone(x))
        prob = self.prob_head(feat)
        if not self.training:
            return {"maps": prob}
        thresh = self.thresh_head(feat)
        binary = apply_op(
            lambda p, t: 1.0 / (1.0 + jnp.exp(-self.k * (p - t))), prob, thresh,
            op_name="db_binarize")
        from ...tensor.manipulation import concat

        return {"maps": concat([prob, thresh, binary], axis=1)}


def db_loss(maps, shrink_map, shrink_mask, thresh_map=None, thresh_mask=None,
            alpha=5.0, beta=10.0, eps=1e-6):
    """DB loss: BCE on the probability map + dice on the binary map + L1 on
    the threshold map (when supervision is provided)."""
    from ...framework.core import Tensor

    def f(m, sm, smask, *tm):
        prob, thresh, binary = m[:, 0], m[:, 1], m[:, 2]
        smf = sm.astype(jnp.float32)
        w = smask.astype(jnp.float32)
        p = jnp.clip(prob, eps, 1 - eps)
        bce = -(smf * jnp.log(p) + (1 - smf) * jnp.log(1 - p))
        bce = (bce * w).sum() / jnp.maximum(w.sum(), 1.0)
        inter = (binary * smf * w).sum()
        union = (binary * w).sum() + (smf * w).sum() + eps
        dice = 1.0 - 2.0 * inter / union
        loss = alpha * bce + dice
        if tm:
            t, tmask = tm
            tw = tmask.astype(jnp.float32)
            l1 = (jnp.abs(thresh - t) * tw).sum() / jnp.maximum(tw.sum(), 1.0)
            loss = loss + beta * l1
        return loss

    args = [maps, shrink_map, shrink_mask]
    if thresh_map is not None:
        args += [thresh_map, thresh_mask]
    return apply_op(f, *args, op_name="db_loss")


class CRNN(Layer):
    """CRNN recognizer (rec side of config 5): conv tower → BiLSTM → CTC
    logits (B, T, num_classes+1); blank index 0."""

    def __init__(self, num_classes, in_channels=3, hidden_size=96):
        super().__init__()
        self.features = Sequential(
            _conv_bn(in_channels, 32), MaxPool2D(2, 2),          # H/2, W/2
            _conv_bn(32, 64), MaxPool2D(2, 2),                   # H/4, W/4
            _conv_bn(64, 128), _conv_bn(128, 128),
            MaxPool2D((2, 1), (2, 1)),                           # H/8, W/4
            _conv_bn(128, 256),
            MaxPool2D((2, 1), (2, 1)),                           # H/16, W/4
            _conv_bn(256, 256, k=2, padding=0),                  # H/16-1 → 1
        )
        self.encoder = LSTM(256, hidden_size, num_layers=2,
                            direction="bidirect")
        self.head = Linear(2 * hidden_size, num_classes + 1)
        self.num_classes = num_classes

    def forward(self, x):
        f = self.features(x)  # (B, C, 1, W')
        B, C = f.shape[0], f.shape[1]
        from ...tensor.manipulation import reshape, transpose

        seq = transpose(reshape(f, [B, C, -1]), [0, 2, 1])  # (B, W', C)
        enc, _ = self.encoder(seq)
        return self.head(enc)  # (B, T, num_classes+1)


def crnn_ctc_loss(logits, labels, label_lengths, blank=0):
    """CTC loss over CRNN logits: all timesteps are valid input frames."""
    from ...tensor.creation import full
    from ...tensor.manipulation import transpose

    t = logits.shape[1]
    tl = full([logits.shape[0]], t, dtype="int32")
    return F.ctc_loss(transpose(logits, [1, 0, 2]), labels, tl, label_lengths,
                      blank=blank)


def dbnet(**kwargs) -> DBNet:
    return DBNet(**kwargs)


def crnn(num_classes=36, **kwargs) -> CRNN:
    return CRNN(num_classes, **kwargs)
