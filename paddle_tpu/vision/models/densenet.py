"""DenseNet (ref: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ...tensor.manipulation import concat
from ...nn import (AdaptiveAvgPool2D, AvgPool2D, BatchNorm2D, Conv2D, Dropout, Flatten,
                   Linear, MaxPool2D, ReLU, Sequential)
from ...nn.layer_base import Layer


class BNACConvLayer(Layer):
    """BN → ReLU → Conv (pre-activation)."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1, pad=0, groups=1):
        super().__init__()
        self._batch_norm = BatchNorm2D(num_channels)
        self._act = ReLU()
        self._conv = Conv2D(num_channels, num_filters, filter_size, stride=stride,
                            padding=pad, groups=groups, bias_attr=False)

    def forward(self, x):
        return self._conv(self._act(self._batch_norm(x)))


class DenseLayer(Layer):
    def __init__(self, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        self.dropout = dropout
        self.bn_ac_func1 = BNACConvLayer(num_channels, bn_size * growth_rate, 1)
        self.bn_ac_func2 = BNACConvLayer(bn_size * growth_rate, growth_rate, 3, pad=1)
        if dropout:
            self.dropout_func = Dropout(p=dropout)

    def forward(self, x):
        conv = self.bn_ac_func2(self.bn_ac_func1(x))
        if self.dropout:
            conv = self.dropout_func(conv)
        return concat([x, conv], axis=1)


class DenseBlock(Layer):
    def __init__(self, num_channels, num_layers, bn_size, growth_rate, dropout):
        super().__init__()
        self.dense_layer_func = []
        ch = num_channels
        layers = []
        for _ in range(num_layers):
            layers.append(DenseLayer(ch, growth_rate, bn_size, dropout))
            ch += growth_rate
        self.layers = Sequential(*layers)
        self.out_channels = ch

    def forward(self, x):
        return self.layers(x)


class TransitionLayer(Layer):
    def __init__(self, num_channels, num_output_features):
        super().__init__()
        self.conv_ac_func = BNACConvLayer(num_channels, num_output_features, 1)
        self.pool2d_avg = AvgPool2D(kernel_size=2, stride=2)

    def forward(self, x):
        return self.pool2d_avg(self.conv_ac_func(x))


class ConvBNLayer(Layer):
    def __init__(self, num_channels, num_filters, filter_size, stride=1, pad=0):
        super().__init__()
        self._conv = Conv2D(num_channels, num_filters, filter_size, stride=stride,
                            padding=pad, bias_attr=False)
        self._batch_norm = BatchNorm2D(num_filters)
        self._act = ReLU()

    def forward(self, x):
        return self._act(self._batch_norm(self._conv(x)))


_CFG = {121: (64, 32, [6, 12, 24, 16]),
        161: (96, 48, [6, 12, 36, 24]),
        169: (64, 32, [6, 12, 32, 32]),
        201: (64, 32, [6, 12, 48, 32]),
        264: (64, 32, [6, 12, 64, 48])}


class DenseNet(Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000, with_pool=True):
        super().__init__()
        assert layers in _CFG, f"layers must be one of {list(_CFG)}"
        num_init_features, growth_rate, block_config = _CFG[layers]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1_func = ConvBNLayer(3, num_init_features, 7, stride=2, pad=3)
        self.pool2d_max = MaxPool2D(kernel_size=3, stride=2, padding=1)

        blocks = []
        ch = num_init_features
        for i, num_layers in enumerate(block_config):
            block = DenseBlock(ch, num_layers, bn_size, growth_rate, dropout)
            blocks.append(block)
            ch = block.out_channels
            if i != len(block_config) - 1:
                blocks.append(TransitionLayer(ch, ch // 2))
                ch = ch // 2
        self.blocks = Sequential(*blocks)
        self.batch_norm = BatchNorm2D(ch)
        self.relu = ReLU()
        if with_pool:
            self.pool2d_avg = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.flatten = Flatten()
            self.out = Linear(ch, num_classes)

    def forward(self, x):
        x = self.pool2d_max(self.conv1_func(x))
        x = self.relu(self.batch_norm(self.blocks(x)))
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = self.out(self.flatten(x))
        return x


def _densenet(layers, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled; load via state_dict")
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
