"""ShuffleNetV2 (ref: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ...tensor.manipulation import chunk, concat
from ...nn import (AdaptiveAvgPool2D, BatchNorm2D, ChannelShuffle, Conv2D, Flatten, Linear,
                   MaxPool2D, ReLU, Sequential, Swish)
from ...nn.layer_base import Layer


def _conv_bn_act(inp, oup, k, stride, padding, groups=1, act=ReLU):
    layers = [Conv2D(inp, oup, k, stride=stride, padding=padding, groups=groups,
                     bias_attr=False), BatchNorm2D(oup)]
    if act is not None:
        layers.append(act())
    return Sequential(*layers)


class InvertedResidual(Layer):
    def __init__(self, in_channels, out_channels, stride, act=ReLU):
        super().__init__()
        self._stride = stride
        branch_features = out_channels // 2
        self._conv_pw = _conv_bn_act(in_channels // 2, branch_features, 1, 1, 0, act=act)
        self._conv_dw = _conv_bn_act(branch_features, branch_features, 3, stride, 1,
                                     groups=branch_features, act=None)
        self._conv_linear = _conv_bn_act(branch_features, branch_features, 1, 1, 0, act=act)
        self._shuffle = ChannelShuffle(2)

    def forward(self, x):
        x1, x2 = chunk(x, 2, axis=1)
        out = concat([x1, self._conv_linear(self._conv_dw(self._conv_pw(x2)))], axis=1)
        return self._shuffle(out)


class InvertedResidualDS(Layer):
    """Downsampling variant: both branches convolve, stride 2."""

    def __init__(self, in_channels, out_channels, stride, act=ReLU):
        super().__init__()
        branch_features = out_channels // 2
        self._conv_dw_1 = _conv_bn_act(in_channels, in_channels, 3, stride, 1,
                                       groups=in_channels, act=None)
        self._conv_linear_1 = _conv_bn_act(in_channels, branch_features, 1, 1, 0, act=act)
        self._conv_pw_2 = _conv_bn_act(in_channels, branch_features, 1, 1, 0, act=act)
        self._conv_dw_2 = _conv_bn_act(branch_features, branch_features, 3, stride, 1,
                                       groups=branch_features, act=None)
        self._conv_linear_2 = _conv_bn_act(branch_features, branch_features, 1, 1, 0, act=act)
        self._shuffle = ChannelShuffle(2)

    def forward(self, x):
        x1 = self._conv_linear_1(self._conv_dw_1(x))
        x2 = self._conv_linear_2(self._conv_dw_2(self._conv_pw_2(x)))
        return self._shuffle(concat([x1, x2], axis=1))


class ShuffleNetV2(Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        stage_repeats = [4, 8, 4]
        act_layer = Swish if act == "swish" else ReLU
        if scale == 0.25:
            stage_out = [-1, 24, 24, 48, 96, 512]
        elif scale == 0.33:
            stage_out = [-1, 24, 32, 64, 128, 512]
        elif scale == 0.5:
            stage_out = [-1, 24, 48, 96, 192, 1024]
        elif scale == 1.0:
            stage_out = [-1, 24, 116, 232, 464, 1024]
        elif scale == 1.5:
            stage_out = [-1, 24, 176, 352, 704, 1024]
        elif scale == 2.0:
            stage_out = [-1, 24, 244, 488, 976, 2048]
        else:
            raise NotImplementedError(f"unsupported scale {scale}")

        self._conv1 = _conv_bn_act(3, stage_out[1], 3, 2, 1, act=act_layer)
        self._max_pool = MaxPool2D(kernel_size=3, stride=2, padding=1)
        blocks = []
        in_c = stage_out[1]
        for stage_id, num_repeat in enumerate(stage_repeats):
            out_c = stage_out[stage_id + 2]
            for i in range(num_repeat):
                if i == 0:
                    blocks.append(InvertedResidualDS(in_c, out_c, 2, act=act_layer))
                else:
                    blocks.append(InvertedResidual(out_c, out_c, 1, act=act_layer))
            in_c = out_c
        self._blocks = Sequential(*blocks)
        self._last_conv = _conv_bn_act(in_c, stage_out[-1], 1, 1, 0, act=act_layer)
        if with_pool:
            self._pool2d_avg = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self._flatten = Flatten()
            self._fc = Linear(stage_out[-1], num_classes)

    def forward(self, x):
        x = self._max_pool(self._conv1(x))
        x = self._last_conv(self._blocks(x))
        if self.with_pool:
            x = self._pool2d_avg(x)
        if self.num_classes > 0:
            x = self._fc(self._flatten(x))
        return x


def _shufflenet(scale, act, pretrained, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled; load via state_dict")
    return ShuffleNetV2(scale=scale, act=act, **kwargs)


def shufflenet_v2_x0_25(pretrained=False, **kwargs):
    return _shufflenet(0.25, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_33(pretrained=False, **kwargs):
    return _shufflenet(0.33, "relu", pretrained, **kwargs)


def shufflenet_v2_x0_5(pretrained=False, **kwargs):
    return _shufflenet(0.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    return _shufflenet(1.0, "relu", pretrained, **kwargs)


def shufflenet_v2_x1_5(pretrained=False, **kwargs):
    return _shufflenet(1.5, "relu", pretrained, **kwargs)


def shufflenet_v2_x2_0(pretrained=False, **kwargs):
    return _shufflenet(2.0, "relu", pretrained, **kwargs)


def shufflenet_v2_swish(pretrained=False, **kwargs):
    return _shufflenet(1.0, "swish", pretrained, **kwargs)
