"""Vision ops (ref: python/paddle/vision/ops.py — roi_align/roi_pool/psroi_pool,
nms/matrix_nms, deform_conv2d, box utilities).

TPU-native notes: RoI ops are dense bilinear gathers (vmap over RoIs);
deform_conv2d is bilinear sampling + one big einsum so the contraction lands
on the MXU (the reference's deformable_conv_op.cu im2col+gemm, re-expressed
for XLA). NMS variants are host-side (dynamic output shapes), matching the
reference's eager semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op
from ..nn.initializer import Uniform
from ..nn.layer_base import Layer


# ---------------------------------------------------------------- NMS family

def _np_iou_matrix(b):
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    xx1 = np.maximum(b[:, None, 0], b[None, :, 0])
    yy1 = np.maximum(b[:, None, 1], b[None, :, 1])
    xx2 = np.minimum(b[:, None, 2], b[None, :, 2])
    yy2 = np.minimum(b[:, None, 3], b[None, :, 3])
    inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
    return inter / (areas[:, None] + areas[None, :] - inter + 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Host-side NMS (dynamic output — eager only, like the reference op)."""
    b = np.asarray(to_array(boxes))
    s = np.asarray(to_array(scores)) if scores is not None else np.arange(
        len(b), 0, -1, dtype=np.float32)

    def _single(idxs):
        bb, ss = b[idxs], s[idxs]
        order = np.argsort(-ss)
        keep = []
        suppressed = np.zeros(len(bb), bool)
        iou = _np_iou_matrix(bb)
        for i_ in order:
            if suppressed[i_]:
                continue
            keep.append(idxs[i_])
            suppressed |= iou[i_] > iou_threshold
            suppressed[i_] = True
        return keep

    if category_idxs is None:
        keep = _single(np.arange(len(b)))
    else:
        cats = np.asarray(to_array(category_idxs))
        keep = []
        for c in (categories if categories is not None else np.unique(cats)):
            keep.extend(_single(np.nonzero(cats == int(c))[0]))
        keep.sort(key=lambda i_: -s[i_])
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0, nms_top_k=400,
               keep_top_k=200, use_gaussian=False, gaussian_sigma=2.0, background_label=0,
               normalized=True, return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (SOLOv2) — decayed scores instead of hard suppression.
    Ref: paddle/phi/kernels/cpu/matrix_nms_kernel.cc; host-side here."""
    bxs = np.asarray(to_array(bboxes))  # [N, M, 4]
    scs = np.asarray(to_array(scores))  # [N, C, M]
    out, out_idx, rois_num = [], [], []
    for n in range(bxs.shape[0]):
        dets = []
        for c in range(scs.shape[1]):
            if c == background_label:
                continue
            s = scs[n, c]
            sel = np.nonzero(s > score_threshold)[0]
            if sel.size == 0:
                continue
            sel = sel[np.argsort(-s[sel])][:nms_top_k]
            bb, ss = bxs[n, sel], s[sel]
            iou = _np_iou_matrix(bb)
            # iou_max[j] = max IoU of box j with any higher-scored box
            low = np.tril(iou, -1)
            iou_max = np.concatenate([[0.0], low[1:, :].max(axis=1) if len(bb) > 1
                                      else np.zeros(0)])
            if use_gaussian:
                decay_m = np.exp((iou_max[None, :] ** 2 - iou ** 2) * gaussian_sigma)
            else:
                decay_m = (1 - iou) / (1 - iou_max[None, :] + 1e-10)
            # decay for box i = min(1, min_{j<i} decay(iou_ij, iou_max_j))
            decay_m = np.where(np.tril(np.ones_like(iou), -1) > 0, decay_m, 1.0)
            decay = np.minimum(decay_m.min(axis=1), 1.0)
            ds = ss * decay
            for j in range(len(sel)):
                if ds[j] > post_threshold:
                    dets.append((c, ds[j], bb[j], n * scs.shape[2] + sel[j]))
        dets.sort(key=lambda d: -d[1])
        dets = dets[:keep_top_k]
        rois_num.append(len(dets))
        for c, sc, bb, gi in dets:
            out.append([c, sc, *bb])
            out_idx.append(gi)
    out_t = Tensor(jnp.asarray(np.asarray(out, np.float32).reshape(-1, 6)))
    res = [out_t]
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(out_idx, np.int64))))
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(rois_num, np.int32))))
    return tuple(res) if len(res) > 1 else out_t


# ------------------------------------------------------------- box utilities

def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes vs priors (ref phi box_coder kernel)."""
    def f(pb, tb, pbv=None):
        norm = 0.0 if box_normalized else 1.0
        pw = pb[..., 2] - pb[..., 0] + norm
        ph = pb[..., 3] - pb[..., 1] + norm
        pcx = pb[..., 0] + pw * 0.5
        pcy = pb[..., 1] + ph * 0.5
        if pbv is None:
            pbv = jnp.ones(4, pb.dtype)
        if code_type == "encode_center_size":
            tw = tb[..., 2] - tb[..., 0] + norm
            th = tb[..., 3] - tb[..., 1] + norm
            tcx = tb[..., 0] + tw * 0.5
            tcy = tb[..., 1] + th * 0.5
            out = jnp.stack([(tcx[:, None] - pcx[None, :]) / pw[None, :],
                             (tcy[:, None] - pcy[None, :]) / ph[None, :],
                             jnp.log(tw[:, None] / pw[None, :]),
                             jnp.log(th[:, None] / ph[None, :])], axis=-1)
            return out / jnp.broadcast_to(pbv, out.shape)
        # decode_center_size: target [N, M, 4] deltas vs priors broadcast on `axis`
        d = tb * jnp.broadcast_to(pbv, tb.shape)
        exp = (lambda v: jnp.expand_dims(v, axis=axis))
        dcx = d[..., 0] * exp(pw) + exp(pcx)
        dcy = d[..., 1] * exp(ph) + exp(pcy)
        dw = jnp.exp(d[..., 2]) * exp(pw)
        dh = jnp.exp(d[..., 3]) * exp(ph)
        return jnp.stack([dcx - dw * 0.5, dcy - dh * 0.5,
                          dcx + dw * 0.5 - norm, dcy + dh * 0.5 - norm], axis=-1)

    if prior_box_var is None:
        return apply_op(f, prior_box, target_box)
    return apply_op(f, prior_box, target_box, prior_box_var)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,), variance=(0.1,
              0.1, 0.2, 0.2), flip=False, clip=False, steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    """SSD prior boxes (ref phi prior_box kernel)."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    step_h = steps[1] or ih / fh
    step_w = steps[0] or iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    boxes = []
    for h in range(fh):
        for w in range(fw):
            cx = (w + offset) * step_w
            cy = (h + offset) * step_h
            cell = []
            for k, ms in enumerate(min_sizes):
                if min_max_aspect_ratios_order:
                    cell.append((cx, cy, ms, ms))
                    if max_sizes:
                        sz = float(np.sqrt(ms * max_sizes[k]))
                        cell.append((cx, cy, sz, sz))
                    for ar in ars:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        cell.append((cx, cy, ms * np.sqrt(ar), ms / np.sqrt(ar)))
                else:
                    for ar in ars:
                        cell.append((cx, cy, ms * np.sqrt(ar), ms / np.sqrt(ar)))
                    if max_sizes:
                        sz = float(np.sqrt(ms * max_sizes[k]))
                        cell.append((cx, cy, sz, sz))
            boxes.extend(cell)
    arr = np.asarray(boxes, np.float32).reshape(fh, fw, -1, 4)
    out = np.stack([(arr[..., 0] - arr[..., 2] / 2) / iw, (arr[..., 1] - arr[..., 3] / 2) / ih,
                    (arr[..., 0] + arr[..., 2] / 2) / iw, (arr[..., 1] + arr[..., 3] / 2) / ih],
                   axis=-1)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def yolo_box(x, img_size, anchors, class_num, conf_thresh, downsample_ratio, clip_bbox=True,
             name=None, scale_x_y=1.0, iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output to boxes/scores (ref phi yolo_box kernel)."""
    def f(xv, imgs):
        n, _, h, w = xv.shape
        na = len(anchors) // 2
        an = jnp.asarray(np.asarray(anchors, np.float32).reshape(na, 2))
        sig = jax.nn.sigmoid
        ioup = None
        if iou_aware:
            # layout per GetIoUIndex: first na channels are IoU maps, rest regular
            ioup, xv = xv[:, :na], xv[:, na:]
        xv = xv.reshape(n, na, 5 + class_num, h, w)
        gx = jnp.arange(w, dtype=xv.dtype)[None, :]
        gy = jnp.arange(h, dtype=xv.dtype)[:, None]
        bx = (sig(xv[:, :, 0]) * scale_x_y - 0.5 * (scale_x_y - 1.0) + gx) / w
        by = (sig(xv[:, :, 1]) * scale_x_y - 0.5 * (scale_x_y - 1.0) + gy) / h
        bw = jnp.exp(xv[:, :, 2]) * an[None, :, 0, None, None] / (w * downsample_ratio)
        bh = jnp.exp(xv[:, :, 3]) * an[None, :, 1, None, None] / (h * downsample_ratio)
        conf = sig(xv[:, :, 4])
        if iou_aware:
            conf = conf ** (1.0 - iou_aware_factor) * sig(ioup) ** iou_aware_factor
        probs = sig(xv[:, :, 5:]) * conf[:, :, None]
        conf_mask = (conf > conf_thresh).astype(xv.dtype)
        imgh = imgs[:, 0].astype(xv.dtype)[:, None, None, None]
        imgw = imgs[:, 1].astype(xv.dtype)[:, None, None, None]
        x1 = (bx - bw / 2) * imgw
        y1 = (by - bh / 2) * imgh
        x2 = (bx + bw / 2) * imgw
        y2 = (by + bh / 2) * imgh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imgw - 1)
            y1 = jnp.clip(y1, 0, imgh - 1)
            x2 = jnp.clip(x2, 0, imgw - 1)
            y2 = jnp.clip(y2, 0, imgh - 1)
        boxes = jnp.stack([x1, y1, x2, y2], -1) * conf_mask[..., None]
        boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(n, -1, 4)
        scores = (probs * conf_mask[:, :, None]).transpose(0, 1, 3, 4, 2)
        scores = scores.reshape(n, -1, class_num)
        return boxes, scores

    return apply_op(f, x, img_size, n_outputs=2)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level, refer_scale,
                             pixel_offset=False, rois_num=None, name=None):
    """Route RoIs to FPN levels by scale (host-side; ref phi
    distribute_fpn_proposals kernel)."""
    rois = np.asarray(to_array(fpn_rois))
    off = 1.0 if pixel_offset else 0.0
    scale = np.sqrt(np.maximum((rois[:, 2] - rois[:, 0] + off) *
                               (rois[:, 3] - rois[:, 1] + off), 0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    # per-RoI image id (for per-image counts per level, ref MultiLevelRoIsNum)
    if rois_num is not None:
        rn = np.asarray(to_array(rois_num)).astype(np.int64)
        img_ids = np.repeat(np.arange(len(rn)), rn)
    else:
        rn, img_ids = None, None
    outs, idxs, res_num = [], [], []
    for l in range(min_level, max_level + 1):
        sel = np.nonzero(lvl == l)[0]
        outs.append(Tensor(jnp.asarray(rois[sel])))
        idxs.append(sel)
        if rn is not None:
            per_img = np.bincount(img_ids[sel], minlength=len(rn)).astype(np.int32)
            res_num.append(Tensor(jnp.asarray(per_img)))
    order = np.concatenate(idxs) if idxs else np.zeros(0, np.int64)
    restore = Tensor(jnp.asarray(np.argsort(order).astype(np.int32)))
    return outs, restore, (res_num if rn is not None else None)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances, pre_nms_top_n=6000,
                       post_nms_top_n=1000, nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (host-side pipeline: decode→clip→filter→NMS).
    Ref phi generate_proposals_v2 kernel."""
    sc = np.asarray(to_array(scores))          # [N, A, H, W]
    bd = np.asarray(to_array(bbox_deltas))     # [N, 4A, H, W]
    im = np.asarray(to_array(img_size))        # [N, 2]
    an = np.asarray(to_array(anchors)).reshape(-1, 4)
    va = np.asarray(to_array(variances)).reshape(-1, 4)
    n, a, h, w = sc.shape
    off = 1.0 if pixel_offset else 0.0
    all_rois, all_num = [], []
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)
        d = bd[i].reshape(a, 4, h, w).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-s)[:pre_nms_top_n]
        s, d, anc, v = s[order], d[order], an[order], va[order]
        aw = anc[:, 2] - anc[:, 0] + off
        ah = anc[:, 3] - anc[:, 1] + off
        acx = anc[:, 0] + aw * 0.5
        acy = anc[:, 1] + ah * 0.5
        cx = v[:, 0] * d[:, 0] * aw + acx
        cy = v[:, 1] * d[:, 1] * ah + acy
        bw = np.exp(np.minimum(v[:, 2] * d[:, 2], np.log(1000. / 16))) * aw
        bh = np.exp(np.minimum(v[:, 3] * d[:, 3], np.log(1000. / 16))) * ah
        boxes = np.stack([cx - bw / 2, cy - bh / 2, cx + bw / 2 - off, cy + bh / 2 - off], 1)
        ih, iw = im[i, 0], im[i, 1]
        boxes[:, 0::2] = np.clip(boxes[:, 0::2], 0, iw - off)
        boxes[:, 1::2] = np.clip(boxes[:, 1::2], 0, ih - off)
        keep = np.nonzero((boxes[:, 2] - boxes[:, 0] + off >= min_size) &
                          (boxes[:, 3] - boxes[:, 1] + off >= min_size))[0]
        boxes, s = boxes[keep], s[keep]
        if len(boxes):
            iou = _np_iou_matrix(boxes)
            order2 = np.argsort(-s)
            sup = np.zeros(len(boxes), bool)
            kept = []
            for j in order2:
                if sup[j]:
                    continue
                kept.append(j)
                if len(kept) >= post_nms_top_n:
                    break
                sup |= iou[j] > nms_thresh
                sup[j] = True
            boxes, s = boxes[kept], s[kept]
        all_rois.append(boxes)
        all_num.append(len(boxes))
    rois = Tensor(jnp.asarray(np.concatenate(all_rois, 0) if all_rois else
                              np.zeros((0, 4), np.float32)))
    nums = Tensor(jnp.asarray(np.asarray(all_num, np.int32)))
    if return_rois_num:
        return rois, nums
    return rois


# ---------------------------------------------------------------- RoI family

def _roi_batch_ids(boxes_num, n_rois):
    if boxes_num is None:
        return jnp.zeros((n_rois,), jnp.int32)
    bn = np.asarray(to_array(boxes_num)).astype(np.int64)
    return jnp.asarray(np.repeat(np.arange(len(bn)), bn).astype(np.int32))


def roi_align(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign via bilinear gather (XLA-friendly dense gather)."""
    os_ = output_size if isinstance(output_size, (list, tuple)) else (output_size,
                                                                      output_size)
    batch_ids = _roi_batch_ids(boxes_num, int(boxes.shape[0]))

    def f(feat, rois):
        oh, ow = os_
        offset = 0.5 if aligned else 0.0

        def one_roi(roi, batch_idx):
            x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
            x1, y1 = x1 * spatial_scale - offset, y1 * spatial_scale - offset
            x2, y2 = x2 * spatial_scale - offset, y2 * spatial_scale - offset
            rh = jnp.maximum(y2 - y1, 1e-6) / oh
            rw = jnp.maximum(x2 - x1, 1e-6) / ow
            ys = y1 + (jnp.arange(oh) + 0.5) * rh
            xs = x1 + (jnp.arange(ow) + 0.5) * rw
            fm = feat[batch_idx]  # C,H,W
            C, H, W = fm.shape
            y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(ys - y0, 0, 1)
            wx = jnp.clip(xs - x0, 0, 1)
            v00 = fm[:, y0][:, :, x0]
            v01 = fm[:, y0][:, :, x1i]
            v10 = fm[:, y1i][:, :, x0]
            v11 = fm[:, y1i][:, :, x1i]
            top = v00 * (1 - wx)[None, None, :] + v01 * wx[None, None, :]
            bot = v10 * (1 - wx)[None, None, :] + v11 * wx[None, None, :]
            return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]

        return jax.vmap(one_roi)(rois, batch_ids)

    return apply_op(f, x, boxes)


def roi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0, name=None):
    """RoIPool: max over quantized bins (ref phi roi_pool kernel)."""
    os_ = output_size if isinstance(output_size, (list, tuple)) else (output_size,
                                                                      output_size)
    batch_ids = _roi_batch_ids(boxes_num, int(boxes.shape[0]))

    def f(feat, rois):
        oh, ow = os_
        _, _, H, W = feat.shape

        def one_roi(roi, batch_idx):
            x1 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1) / oh
            rw = jnp.maximum(x2 - x1 + 1, 1) / ow
            fm = feat[batch_idx]
            ys = jnp.arange(H)[None, :]
            xs = jnp.arange(W)[None, :]
            hstart = jnp.clip(y1 + jnp.floor(jnp.arange(oh) * rh).astype(jnp.int32), 0, H)
            hend = jnp.clip(y1 + jnp.ceil((jnp.arange(oh) + 1) * rh).astype(jnp.int32), 0, H)
            wstart = jnp.clip(x1 + jnp.floor(jnp.arange(ow) * rw).astype(jnp.int32), 0, W)
            wend = jnp.clip(x1 + jnp.ceil((jnp.arange(ow) + 1) * rw).astype(jnp.int32), 0, W)
            hm = (ys >= hstart[:, None]) & (ys < hend[:, None])        # [oh, H]
            wm = (xs >= wstart[:, None]) & (xs < wend[:, None])        # [ow, W]
            m = hm[:, None, :, None] & wm[None, :, None, :]            # [oh,ow,H,W]
            vals = jnp.where(m[None], fm[:, None, None, :, :],
                             jnp.asarray(-jnp.inf, feat.dtype))
            out = vals.max(axis=(-1, -2))
            empty = ~m.any(axis=(-1, -2))
            return jnp.where(empty[None], jnp.zeros((), feat.dtype), out)

        return jax.vmap(one_roi)(rois, batch_ids)

    return apply_op(f, x, boxes)


def psroi_pool(x, boxes, boxes_num=None, output_size=1, spatial_scale=1.0, name=None):
    """Position-sensitive RoI pooling (R-FCN; ref phi psroi_pool kernel).
    Input channels must equal C_out * oh * ow; bin (i,j) averages channel slice."""
    os_ = output_size if isinstance(output_size, (list, tuple)) else (output_size,
                                                                      output_size)
    batch_ids = _roi_batch_ids(boxes_num, int(boxes.shape[0]))

    def f(feat, rois):
        oh, ow = os_
        N, C, H, W = feat.shape
        c_out = C // (oh * ow)

        def one_roi(roi, batch_idx):
            x1 = roi[0] * spatial_scale
            y1 = roi[1] * spatial_scale
            x2 = roi[2] * spatial_scale
            y2 = roi[3] * spatial_scale
            rh = jnp.maximum(y2 - y1, 0.1) / oh
            rw = jnp.maximum(x2 - x1, 0.1) / ow
            fm = feat[batch_idx].reshape(c_out, oh, ow, H, W)
            ys = jnp.arange(H, dtype=feat.dtype)[None, :]
            xs = jnp.arange(W, dtype=feat.dtype)[None, :]
            hstart = jnp.floor(y1 + jnp.arange(oh) * rh)
            hend = jnp.ceil(y1 + (jnp.arange(oh) + 1) * rh)
            wstart = jnp.floor(x1 + jnp.arange(ow) * rw)
            wend = jnp.ceil(x1 + (jnp.arange(ow) + 1) * rw)
            hm = (ys >= hstart[:, None]) & (ys < hend[:, None])
            wm = (xs >= wstart[:, None]) & (xs < wend[:, None])
            m = (hm[:, None, :, None] & wm[None, :, None, :]).astype(feat.dtype)
            s = jnp.einsum("cijhw,ijhw->cij", fm, m)
            cnt = jnp.maximum(m.sum(axis=(-1, -2)), 1.0)
            return s / cnt

        return jax.vmap(one_roi)(rois, batch_ids)

    return apply_op(f, x, boxes)


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num, aligned=True):
        return roi_align(x, boxes, boxes_num, self._output_size, self._spatial_scale,
                         aligned=aligned)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size, self._spatial_scale)


class PSRoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self._output_size, self._spatial_scale)


# ----------------------------------------------------------- deformable conv

def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 (ref deformable_conv_op.cu im2col+gemm), expressed
    as bilinear gathers + einsum so the contraction runs on the MXU.

    x: [N, Cin, H, W]; offset: [N, 2*dg*kh*kw, Ho, Wo];
    mask (v2): [N, dg*kh*kw, Ho, Wo]; weight: [Cout, Cin/groups, kh, kw].
    """
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def f(xv, off, w, *rest):
        msk = rest[0] if mask is not None else None
        N, Cin, H, W = xv.shape
        Cout, _, kh, kw = w.shape
        Ho = (H + 2 * p[0] - (d[0] * (kh - 1) + 1)) // s[0] + 1
        Wo = (W + 2 * p[1] - (d[1] * (kw - 1) + 1)) // s[1] + 1
        K = kh * kw
        dg = deformable_groups
        # base sampling grid [K, Ho, Wo]
        base_y = (jnp.arange(Ho) * s[0] - p[0])[None, :, None] + \
            (jnp.arange(kh) * d[0]).repeat(kw)[:, None, None]
        base_x = (jnp.arange(Wo) * s[1] - p[1])[None, None, :] + \
            jnp.tile(jnp.arange(kw) * d[1], kh)[:, None, None]
        off = off.reshape(N, dg, K, 2, Ho, Wo)
        sy = base_y[None, None].astype(xv.dtype) + off[:, :, :, 0]
        sx = base_x[None, None].astype(xv.dtype) + off[:, :, :, 1]

        def sample(fm, yy, xx):
            # fm: [Cg, H, W]; yy/xx: [K, Ho, Wo] → [Cg, K, Ho, Wo]
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy = yy - y0
            wx = xx - x0
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)

            def tap(yi, xi):
                valid = (yi >= 0) & (yi < H) & (xi >= 0) & (xi < W)
                v = fm[:, jnp.clip(yi, 0, H - 1), jnp.clip(xi, 0, W - 1)]
                return v * valid[None].astype(fm.dtype)

            return (tap(y0i, x0i) * ((1 - wy) * (1 - wx))[None] +
                    tap(y0i, x0i + 1) * ((1 - wy) * wx)[None] +
                    tap(y0i + 1, x0i) * (wy * (1 - wx))[None] +
                    tap(y0i + 1, x0i + 1) * (wy * wx)[None])

        xg = xv.reshape(N, dg, Cin // dg, H, W)
        cols = jax.vmap(jax.vmap(sample))(xg, sy, sx)      # [N, dg, Cg, K, Ho, Wo]
        cols = cols.reshape(N, Cin, K, Ho, Wo)
        if msk is not None:
            m = msk.reshape(N, dg, K, Ho, Wo)
            m = jnp.repeat(m, Cin // dg, axis=1).reshape(N, Cin, K, Ho, Wo)
            cols = cols * m
        cols = cols.reshape(N, groups, Cin // groups, K, Ho, Wo)
        wg = w.reshape(groups, Cout // groups, Cin // groups, K)
        out = jnp.einsum("ngckhw,gock->ngohw", cols, wg,
                         preferred_element_type=jnp.float32).astype(xv.dtype)
        out = out.reshape(N, Cout, Ho, Wo)
        if bias is not None and mask is None and len(rest) == 1:
            out = out + rest[0][None, :, None, None]
        elif bias is not None and mask is not None and len(rest) == 2:
            out = out + rest[1][None, :, None, None]
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return apply_op(f, *args)


class DeformConv2D(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, deformable_groups=1, groups=1, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        ks = (kernel_size, kernel_size) if isinstance(kernel_size, int) else tuple(kernel_size)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._deformable_groups = deformable_groups
        self._groups = groups
        fan_in = in_channels * ks[0] * ks[1] // groups
        k = float(np.sqrt(1.0 / fan_in))
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *ks],
            default_initializer=Uniform(-k, k))
        self.bias = (None if bias_attr is False else
                     self.create_parameter([out_channels], is_bias=True,
                                           default_initializer=Uniform(-k, k)))

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._deformable_groups,
                             self._groups, mask)


def read_file(filename, name=None):
    """Raw file bytes as a uint8 1-D Tensor (ref vision/ops.py read_file)."""
    with open(filename, "rb") as f:
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return Tensor(jnp.asarray(data))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode JPEG bytes (uint8 1-D Tensor) to a CHW uint8 image Tensor
    (ref vision/ops.py decode_jpeg — nvjpeg there, PIL here: decode is
    host-side data loading either way)."""
    import io

    try:
        from PIL import Image
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("decode_jpeg needs Pillow") from e

    raw = bytes(np.asarray(to_array(x)).astype(np.uint8))
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]  # (1, H, W)
    else:
        arr = arr.transpose(2, 0, 1)  # (C, H, W)
    return Tensor(jnp.asarray(np.ascontiguousarray(arr)))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (ref vision/ops.py yolo_loss:50 / CUDA
    yolov3_loss op): per-sample sum of box (sigmoid-CE xy + L1 wh, scaled by
    2-w*h), objectness (sigmoid-CE with IoU>ignore_thresh negatives
    ignored), and class (sigmoid-CE, optional label smoothing) terms.

    x: [N, S*(5+C), H, W]; gt_box: [N, B, 4] (cx, cy, w, h, normalized);
    gt_label: [N, B] int; returns [N] loss."""
    xv = to_array(x)
    gb = to_array(gt_box).astype(jnp.float32)
    gl = to_array(gt_label).astype(jnp.int32)
    gs = (to_array(gt_score).astype(jnp.float32) if gt_score is not None
          else jnp.ones(gl.shape, jnp.float32))
    N, _, H, W = xv.shape
    S = len(anchor_mask)
    C = int(class_num)
    an = np.asarray(anchors, np.float32).reshape(-1, 2)  # all anchors (w,h)
    mask_an = an[np.asarray(anchor_mask)]
    in_h, in_w = H * downsample_ratio, W * downsample_ratio

    p = xv.reshape(N, S, 5 + C, H, W).astype(jnp.float32)
    tx, ty, tw, th = p[:, :, 0], p[:, :, 1], p[:, :, 2], p[:, :, 3]
    tobj, tcls = p[:, :, 4], p[:, :, 5:]

    # ---- build targets (host loop over the fixed B gt slots is traced
    # statically; B is small)
    B = gb.shape[1]
    gx = gb[..., 0] * W    # [N, B] in grid units
    gy = gb[..., 1] * H
    gw = gb[..., 2] * in_w  # pixels
    gh = gb[..., 3] * in_h
    valid = (gb[..., 2] > 0) & (gb[..., 3] > 0)
    # best anchor per gt over ALL anchors (shape-only IoU)
    inter = (jnp.minimum(gw[..., None], an[:, 0]) *
             jnp.minimum(gh[..., None], an[:, 1]))
    union = gw[..., None] * gh[..., None] + an[:, 0] * an[:, 1] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-9), -1)  # [N, B]

    obj_t = jnp.zeros((N, S, H, W))
    obj_w = jnp.zeros((N, S, H, W))  # per-cell gt_score weight
    xy_t = jnp.zeros((N, S, 2, H, W))
    wh_t = jnp.zeros((N, S, 2, H, W))
    box_w = jnp.zeros((N, S, H, W))
    cls_t = jnp.zeros((N, S, C, H, W))
    mask_list = list(np.asarray(anchor_mask))
    batch = jnp.arange(N)
    for b in range(B):
        gi = jnp.clip(gx[:, b].astype(jnp.int32), 0, W - 1)
        gj = jnp.clip(gy[:, b].astype(jnp.int32), 0, H - 1)
        for s, a_idx in enumerate(mask_list):
            sel = valid[:, b] & (best[:, b] == a_idx)
            w8 = jnp.where(sel, gs[:, b], 0.0)
            obj_t = obj_t.at[batch, s, gj, gi].max(jnp.where(sel, 1.0, 0.0))
            obj_w = obj_w.at[batch, s, gj, gi].max(w8)
            sxy = jnp.stack([gx[:, b] - gi, gy[:, b] - gj], -1)  # in (0,1)
            swh = jnp.stack(
                [jnp.log(jnp.maximum(gw[:, b] / an[a_idx, 0], 1e-9)),
                 jnp.log(jnp.maximum(gh[:, b] / an[a_idx, 1], 1e-9))], -1)
            for d in range(2):
                xy_t = xy_t.at[batch, s, d, gj, gi].set(
                    jnp.where(sel, sxy[:, d], xy_t[batch, s, d, gj, gi]))
                wh_t = wh_t.at[batch, s, d, gj, gi].set(
                    jnp.where(sel, swh[:, d], wh_t[batch, s, d, gj, gi]))
            scale = 2.0 - gb[:, b, 2] * gb[:, b, 3]
            box_w = box_w.at[batch, s, gj, gi].set(
                jnp.where(sel, scale * gs[:, b], box_w[batch, s, gj, gi]))
            lbl = jnp.clip(gl[:, b], 0, C - 1)
            cls_t = cls_t.at[batch, s, lbl, gj, gi].set(
                jnp.where(sel, 1.0, cls_t[batch, s, lbl, gj, gi]))

    # ---- ignore mask: predicted boxes overlapping any gt above thresh are
    # not penalized as background
    grid_x = jnp.arange(W, dtype=jnp.float32)
    grid_y = jnp.arange(H, dtype=jnp.float32)[:, None]
    px = (jax.nn.sigmoid(tx) * scale_x_y - (scale_x_y - 1) / 2 + grid_x) / W
    py = (jax.nn.sigmoid(ty) * scale_x_y - (scale_x_y - 1) / 2 + grid_y) / H
    pw = jnp.exp(jnp.clip(tw, -10, 10)) * mask_an[:, 0][:, None, None] / in_w
    ph = jnp.exp(jnp.clip(th, -10, 10)) * mask_an[:, 1][:, None, None] / in_h

    def iou_cell(px, py, pw, ph, qx, qy, qw, qh):
        x1 = jnp.maximum(px - pw / 2, qx - qw / 2)
        x2 = jnp.minimum(px + pw / 2, qx + qw / 2)
        y1 = jnp.maximum(py - ph / 2, qy - qh / 2)
        y2 = jnp.minimum(py + ph / 2, qy + qh / 2)
        inter = jnp.clip(x2 - x1, 0) * jnp.clip(y2 - y1, 0)
        return inter / jnp.maximum(pw * ph + qw * qh - inter, 1e-9)

    best_iou = jnp.zeros((N, S, H, W))
    for b in range(B):
        i = iou_cell(px, py, pw, ph,
                     gb[:, b, 0][:, None, None, None],
                     gb[:, b, 1][:, None, None, None],
                     gb[:, b, 2][:, None, None, None],
                     gb[:, b, 3][:, None, None, None])
        best_iou = jnp.maximum(best_iou,
                               jnp.where(valid[:, b][:, None, None, None],
                                         i, 0.0))
    noobj_mask = (best_iou < ignore_thresh).astype(jnp.float32)

    def bce(logit, target):
        return jnp.maximum(logit, 0) - logit * target + \
            jnp.log1p(jnp.exp(-jnp.abs(logit)))

    axes = (1, 2, 3)
    loss_xy = jnp.sum(box_w[:, :, None] * bce(
        jnp.stack([tx, ty], 2), xy_t), (1, 2, 3, 4))
    loss_wh = jnp.sum(box_w[:, :, None] * jnp.abs(
        jnp.stack([tw, th], 2) - wh_t) * obj_t[:, :, None], (1, 2, 3, 4))
    loss_obj = jnp.sum(obj_w * bce(tobj, obj_t) * obj_t, axes) + \
        jnp.sum(noobj_mask * bce(tobj, obj_t) * (1 - obj_t), axes)
    smooth = 1.0 / max(C, 1) if use_label_smooth else 0.0
    cls_target = cls_t * (1 - smooth) + smooth / max(C, 1)
    loss_cls = jnp.sum(obj_t[:, :, None] * bce(tcls, cls_target),
                       (1, 2, 3, 4))
    return Tensor(loss_xy + loss_wh + loss_obj + loss_cls)
