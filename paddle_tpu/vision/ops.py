"""Vision ops (ref: python/paddle/vision/ops.py — roi_align, nms,
deform_conv2d, box utilities)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None, categories=None,
        top_k=None):
    """Host-side NMS (dynamic output — eager only, like the reference op)."""
    b = np.asarray(to_array(boxes))
    s = np.asarray(to_array(scores)) if scores is not None else np.arange(
        len(b), 0, -1, dtype=np.float32)
    order = np.argsort(-s)
    keep = []
    suppressed = np.zeros(len(b), bool)
    areas = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    for i_ in order:
        if suppressed[i_]:
            continue
        keep.append(i_)
        xx1 = np.maximum(b[i_, 0], b[:, 0])
        yy1 = np.maximum(b[i_, 1], b[:, 1])
        xx2 = np.minimum(b[i_, 2], b[:, 2])
        yy2 = np.minimum(b[i_, 3], b[:, 3])
        inter = np.maximum(xx2 - xx1, 0) * np.maximum(yy2 - yy1, 0)
        iou = inter / (areas[i_] + areas - inter + 1e-10)
        suppressed |= iou > iou_threshold
        suppressed[i_] = True
    keep = np.asarray(keep, np.int64)
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(keep))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    raise NotImplementedError("box_coder: planned")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0, sampling_ratio=-1,
              aligned=True, name=None):
    """RoIAlign via bilinear gather (XLA-friendly dense gather)."""
    os_ = output_size if isinstance(output_size, (list, tuple)) else (output_size,
                                                                      output_size)

    def f(feat, rois):
        n_rois = rois.shape[0]
        oh, ow = os_
        offset = 0.5 if aligned else 0.0

        def one_roi(roi, batch_idx):
            x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
            x1, y1 = x1 * spatial_scale - offset, y1 * spatial_scale - offset
            x2, y2 = x2 * spatial_scale - offset, y2 * spatial_scale - offset
            rh = jnp.maximum(y2 - y1, 1e-6) / oh
            rw = jnp.maximum(x2 - x1, 1e-6) / ow
            ys = y1 + (jnp.arange(oh) + 0.5) * rh
            xs = x1 + (jnp.arange(ow) + 0.5) * rw
            fm = feat[batch_idx]  # C,H,W
            C, H, W = fm.shape
            y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1)
            x1i = jnp.clip(x0 + 1, 0, W - 1)
            wy = jnp.clip(ys - y0, 0, 1)
            wx = jnp.clip(xs - x0, 0, 1)
            v00 = fm[:, y0][:, :, x0]
            v01 = fm[:, y0][:, :, x1i]
            v10 = fm[:, y1i][:, :, x0]
            v11 = fm[:, y1i][:, :, x1i]
            top = v00 * (1 - wx)[None, None, :] + v01 * wx[None, None, :]
            bot = v10 * (1 - wx)[None, None, :] + v11 * wx[None, None, :]
            return top * (1 - wy)[None, :, None] + bot * wy[None, :, None]

        batch_ids = jnp.zeros((n_rois,), jnp.int32)
        return jax.vmap(one_roi)(rois, batch_ids)

    return apply_op(f, x, boxes)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    raise NotImplementedError(
        "deform_conv2d: planned as a Pallas gather kernel (ref deformable_conv_op.cu)")


def generate_proposals(*args, **kwargs):
    raise NotImplementedError("generate_proposals: detection pipeline op, planned")
