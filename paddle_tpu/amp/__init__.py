"""AMP (ref: python/paddle/amp/auto_cast.py:664 auto_cast, :726 decorate;
grad_scaler.py:581 GradScaler, AmpScaler:38).

TPU-native policy: bf16-first. O1 = op-list-based autocast at dispatch time
(mirrors the reference's white/black lists from
python/paddle/fluid/dygraph/amp/auto_cast.py); O2 = cast the model to bf16
with fp32 master weights in the optimizer (multi_precision). Loss scaling is
a no-op for bf16 (same dynamic range as fp32) but fully implemented for fp16
parity — found_inf short-circuits the step exactly like AmpScaler.
"""
from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework.dtype import convert_dtype

# Ref: fluid/dygraph/amp/auto_cast.py WHITE_LIST/BLACK_LIST
WHITE_LIST = {"matmul", "conv2d", "conv1d", "conv3d", "einsum", "linear", "bmm", "mm",
              "flash_attention", "sdpa"}
BLACK_LIST = {"exp", "square", "log", "mean", "sum", "cos_sim", "softmax",
              "softmax_with_cross_entropy", "sigmoid_cross_entropy_with_logits",
              "cross_entropy", "c_softmax_with_cross_entropy", "layer_norm", "group_norm",
              "rms_norm", "reduce_sum", "log_softmax"}


class _AmpState(threading.local):
    def __init__(self):
        self.level = "O0"
        self.dtype = jnp.bfloat16
        self.custom_white = set()
        self.custom_black = set()


_amp_state = _AmpState()


def amp_state():
    return _amp_state


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None, level="O1",
              dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast parity."""
    prev = (_amp_state.level, _amp_state.dtype, _amp_state.custom_white,
            _amp_state.custom_black)
    _amp_state.level = level if enable else "O0"
    _amp_state.dtype = convert_dtype(dtype)
    _amp_state.custom_white = set(custom_white_list or [])
    _amp_state.custom_black = set(custom_black_list or [])
    try:
        yield
    finally:
        (_amp_state.level, _amp_state.dtype, _amp_state.custom_white,
         _amp_state.custom_black) = prev


amp_guard = auto_cast


def should_cast_to_low_precision(op_name: str) -> bool:
    if _amp_state.level == "O0":
        return False
    if op_name in _amp_state.custom_black or op_name in BLACK_LIST:
        return False
    if _amp_state.level == "O2":
        return True
    return op_name in WHITE_LIST or op_name in _amp_state.custom_white


def amp_dtype():
    return _amp_state.dtype


def decorate(models, optimizers=None, level="O1", dtype="bfloat16", master_weight=None,
             save_dtype=None):
    """paddle.amp.decorate parity (ref auto_cast.py:726): O2 casts model params
    to the low-precision dtype; optimizer keeps fp32 master weights."""
    d = convert_dtype(dtype)
    models_list = models if isinstance(models, (list, tuple)) else [models]
    if level == "O2":
        for m in models_list:
            m._convert_dtype(d)
            m._casted_by_pure_fp16 = True
        if optimizers is not None:
            opts = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
            for o in opts:
                if hasattr(o, "_multi_precision"):
                    o._multi_precision = True
    if optimizers is None:
        return models
    return models, optimizers


class GradScaler:
    """Ref grad_scaler.py:581 / AmpScaler:38 — dynamic loss scaling."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._get_params():
            if p.grad is not None:
                g = p.grad.value.astype(jnp.float32) * inv
                if not bool(jnp.isfinite(g).all()):
                    found = True
                p.grad = Tensor(g)
        self._found_inf = found

    def minimize(self, optimizer, loss):
        loss.backward()
        self.step(optimizer)
        self.update()

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def update(self):
        if not self._enable or not self._dynamic:
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_count": self._good_steps,
                "decr_count": self._bad_steps,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, state_dict):
        self._scale = state_dict.get("scale", self._scale)
        self._good_steps = state_dict.get("incr_count", 0)
        self._bad_steps = state_dict.get("decr_count", 0)

    set_state_dict = load_state_dict


AmpScaler = GradScaler
