"""Weight-decay regularizers (ref: python/paddle/regularizer.py).

Paddle semantics: a regularizer set on an optimizer's ``weight_decay`` (or on a
parameter's ``ParamAttr.regularizer``, which takes precedence) is folded into
the gradient before the update rule runs: ``grad += coeff * d penalty / d w``.
For L2 that is ``coeff * w``; for L1, ``coeff * sign(w)``.

TPU note: the fold happens inside the jitted update step, so XLA fuses it into
the optimizer elementwise kernel — no extra HBM pass.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    """Base class (ref: python/paddle/fluid/regularizer.py)."""

    _mode = "l2"

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        # legacy alias used by fluid-era code paths
        self._regularization_coeff = self._coeff

    @property
    def coeff(self):
        return self._coeff

    def __call__(self, param):
        """Return d(penalty)/d(param) to be added to the gradient."""
        if self._mode == "l1":
            return self._coeff * jnp.sign(param)
        return self._coeff * param

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """L1 weight decay: loss += coeff * sum(|w|) (ref regularizer.py L1Decay)."""

    _mode = "l1"


class L2Decay(WeightDecayRegularizer):
    """L2 weight decay: loss += 0.5 * coeff * sum(w^2) (ref regularizer.py L2Decay)."""

    _mode = "l2"
