"""paddle.fft parity (ref: python/paddle/fft.py) over jnp.fft."""
from __future__ import annotations

import jax.numpy as jnp

from .framework.dispatch import apply_op


def _mk1(fn_name):
    fn = getattr(jnp.fft, fn_name)

    def op(x, n=None, axis=-1, norm="backward", name=None):
        return apply_op(lambda v: fn(v, n=n, axis=axis, norm=norm), x)

    op.__name__ = fn_name
    return op


def _mkn(fn_name):
    fn = getattr(jnp.fft, fn_name)

    def op(x, s=None, axes=None, norm="backward", name=None):
        ax = tuple(axes) if axes is not None else None
        return apply_op(lambda v: fn(v, s=s, axes=ax, norm=norm), x)

    op.__name__ = fn_name
    return op


fft = _mk1("fft")
ifft = _mk1("ifft")
rfft = _mk1("rfft")
irfft = _mk1("irfft")
hfft = _mk1("hfft")
ihfft = _mk1("ihfft")
fft2 = _mkn("fft2")
ifft2 = _mkn("ifft2")
rfft2 = _mkn("rfft2")
irfft2 = _mkn("irfft2")
fftn = _mkn("fftn")
ifftn = _mkn("ifftn")
rfftn = _mkn("rfftn")
irfftn = _mkn("irfftn")


def _hfftn_impl(v, s, axes, norm):
    """hfftn == irfftn(conj(x)) with the norm swapped backward<->forward and
    (for backward) a prod(out_sizes) scale — verified against scipy.fft
    (ihfftn is the inverse composition)."""
    ax = tuple(axes) if axes is not None else tuple(range(v.ndim))
    inner = {"backward": "backward", "forward": "backward",
             "ortho": "ortho"}[norm]
    r = jnp.fft.irfftn(jnp.conj(v), s=s, axes=ax, norm=inner)
    if norm == "backward":
        n = 1
        for a in ax:
            n *= r.shape[a]
        r = r * n
    return r


def _ihfftn_impl(v, s, axes, norm):
    ax = tuple(axes) if axes is not None else tuple(range(v.ndim))
    inner = {"backward": "forward", "forward": "backward",
             "ortho": "ortho"}[norm]
    return jnp.conj(jnp.fft.rfftn(v, s=s, axes=ax, norm=inner))


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op(lambda v: _hfftn_impl(v, s, axes, norm), x)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return apply_op(lambda v: _ihfftn_impl(v, s, axes, norm), x)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    """N-d FFT of a Hermitian-symmetric signal (real output; ref fft.py
    hfftn) — scipy-verified composition, see _hfftn_impl."""
    return apply_op(lambda v: _hfftn_impl(v, s, axes, norm), x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    """Inverse of :func:`hfftn` (Hermitian-symmetric spectrum of a real
    signal; ref fft.py ihfftn)."""
    return apply_op(lambda v: _ihfftn_impl(v, s, axes, norm), x)


def fftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor

    return Tensor(jnp.fft.fftfreq(int(n), d=float(d)))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    from .framework.core import Tensor

    return Tensor(jnp.fft.rfftfreq(int(n), d=float(d)))


def fftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.fftshift(v, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    return apply_op(lambda v: jnp.fft.ifftshift(v, axes=axes), x)
