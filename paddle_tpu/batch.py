"""paddle.batch — batched reader decorator (ref: python/paddle/batch.py)."""
from __future__ import annotations

__all__ = []


def batch(reader, batch_size, drop_last=False):
    """Create a batched reader combining items from ``reader`` into lists.

    Args:
        reader: a no-arg callable returning a generator of samples.
        batch_size (int): number of samples per emitted batch.
        drop_last (bool): drop the trailing partial batch when True.
    """
    if batch_size <= 0 or int(batch_size) != batch_size:
        raise ValueError(
            f"batch_size should be a positive integer, but got {batch_size}")
    batch_size = int(batch_size)

    def batch_reader():
        buf = []
        for instance in reader():
            buf.append(instance)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batch_reader
