"""paddle.geometric parity (ref: python/paddle/geometric/ — message passing
send_u_recv/send_ue_recv, segment ops, sample_neighbors)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op


def _seg(op):
    return {"sum": jax.ops.segment_sum, "mean": None, "max": jax.ops.segment_max,
            "min": jax.ops.segment_min}[op]


def segment_sum(data, segment_ids, name=None):
    def f(d, s):
        n = int(np.asarray(to_array(segment_ids)).max()) + 1 if True else None
        return jax.ops.segment_sum(d, s.astype(jnp.int32), num_segments=n)

    return apply_op(f, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    def f(d, s):
        s = s.astype(jnp.int32)
        n = int(np.asarray(to_array(segment_ids)).max()) + 1
        tot = jax.ops.segment_sum(d, s, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones_like(s, d.dtype), s, num_segments=n)
        return tot / jnp.maximum(cnt, 1.0)[..., None] if d.ndim > 1 else \
            tot / jnp.maximum(cnt, 1.0)

    return apply_op(f, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    def f(d, s):
        n = int(np.asarray(to_array(segment_ids)).max()) + 1
        return jax.ops.segment_max(d, s.astype(jnp.int32), num_segments=n)

    return apply_op(f, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    def f(d, s):
        n = int(np.asarray(to_array(segment_ids)).max()) + 1
        return jax.ops.segment_min(d, s.astype(jnp.int32), num_segments=n)

    return apply_op(f, data, segment_ids)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None, name=None):
    """Gather features at src, scatter-reduce at dst (ref geometric/message_passing)."""

    def f(xv, src, dst):
        n = out_size or xv.shape[0]
        msgs = jnp.take(xv, src.astype(jnp.int32), axis=0)
        seg = dst.astype(jnp.int32)
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, seg, num_segments=n)
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(msgs, seg, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(seg, xv.dtype), seg,
                                      num_segments=n)
            return tot / jnp.maximum(cnt, 1.0)[:, None]
        if reduce_op == "max":
            return jax.ops.segment_max(msgs, seg, num_segments=n)
        if reduce_op == "min":
            return jax.ops.segment_min(msgs, seg, num_segments=n)
        raise ValueError(reduce_op)

    return apply_op(f, x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op="add", reduce_op="sum",
                 out_size=None, name=None):
    def f(xv, yv, src, dst):
        n = out_size or xv.shape[0]
        msgs = jnp.take(xv, src.astype(jnp.int32), axis=0)
        if message_op == "add":
            msgs = msgs + yv
        elif message_op == "mul":
            msgs = msgs * yv
        seg = dst.astype(jnp.int32)
        if reduce_op == "sum":
            return jax.ops.segment_sum(msgs, seg, num_segments=n)
        if reduce_op == "mean":
            tot = jax.ops.segment_sum(msgs, seg, num_segments=n)
            cnt = jax.ops.segment_sum(jnp.ones_like(seg, xv.dtype), seg,
                                      num_segments=n)
            return tot / jnp.maximum(cnt, 1.0)[:, None]
        raise ValueError(reduce_op)

    return apply_op(f, x, y, src_index, dst_index)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """CSC neighbor sampling (host-side, dynamic shapes — eager only)."""
    from ..framework.random import derived_rng

    rng = derived_rng("geometric.sample_neighbors")
    rows = np.asarray(to_array(row))
    cptr = np.asarray(to_array(colptr))
    nodes = np.asarray(to_array(input_nodes))
    out_n, out_count = [], []
    for v in nodes:
        lo, hi = cptr[v], cptr[v + 1]
        neigh = rows[lo:hi]
        if sample_size > 0 and len(neigh) > sample_size:
            neigh = rng.choice(neigh, sample_size, replace=False)
        out_n.append(neigh)
        out_count.append(len(neigh))
    return (Tensor(jnp.asarray(np.concatenate(out_n) if out_n else np.zeros(0))),
            Tensor(jnp.asarray(np.asarray(out_count, np.int64))))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from x[src] ⊕ y[dst] (ref geometric/message_passing/send_recv.py
    send_uv)."""

    def f(xv, yv, src, dst):
        xs = jnp.take(xv, src.astype(jnp.int32), axis=0)
        yd = jnp.take(yv, dst.astype(jnp.int32), axis=0)
        if message_op == "add":
            return xs + yd
        if message_op == "sub":
            return xs - yd
        if message_op == "mul":
            return xs * yd
        if message_op == "div":
            return xs / yd
        raise ValueError(message_op)

    return apply_op(f, x, y, src_index, dst_index)


def _reindex(x_np, neighbor_list, count_list):
    """Shared reindex core: nodes = unique(x ++ neighbors), x first by first
    occurrence; edges (neighbor → repeated center) relabeled."""
    all_ids = np.concatenate([x_np] + neighbor_list)
    uniq, first_pos = np.unique(all_ids, return_index=True)
    out_nodes = all_ids[np.sort(first_pos)]
    lut = {int(v): i for i, v in enumerate(out_nodes)}
    reindex_src = np.asarray([lut[int(v)] for v in np.concatenate(neighbor_list)],
                             np.int64) if neighbor_list else np.zeros(0, np.int64)
    dst = np.concatenate([np.repeat(x_np, c) for c in count_list]) \
        if count_list else np.zeros(0, np.int64)
    reindex_dst = np.asarray([lut[int(v)] for v in dst], np.int64)
    return reindex_src, reindex_dst, out_nodes


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None, name=None):
    """Relabel sampled subgraph node ids to 0..n-1 (ref geometric/reindex.py:24).
    Host-side (dynamic output shapes — eager only)."""
    x_np = np.asarray(to_array(x)).astype(np.int64)
    nb = np.asarray(to_array(neighbors)).astype(np.int64)
    cnt = np.asarray(to_array(count)).astype(np.int64)
    src, dst, out = _reindex(x_np, [nb], [cnt])
    return Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)), Tensor(jnp.asarray(out))


def reindex_heter_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                        name=None):
    """Heterogeneous variant: lists of neighbors/count per edge type
    (ref geometric/reindex.py reindex_heter_graph)."""
    x_np = np.asarray(to_array(x)).astype(np.int64)
    nbs = [np.asarray(to_array(n)).astype(np.int64) for n in neighbors]
    cnts = [np.asarray(to_array(c)).astype(np.int64) for c in count]
    src, dst, out = _reindex(x_np, nbs, cnts)
    return Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)), Tensor(jnp.asarray(out))


__all__ = ['send_u_recv', 'send_ue_recv', 'send_uv', 'segment_sum', 'segment_mean',
           'segment_min', 'segment_max', 'reindex_graph', 'reindex_heter_graph',
           'sample_neighbors']
