"""Compat shim mirroring the reference's generated-op namespace.

Ref: python/paddle/_C_ops.py:19 re-exports `core.eager.ops.*` C functions.
Here there is no generated C layer — every op is a Python function over
jax — so this module resolves op names against the public functional
namespaces (tensor ops first, then nn.functional), letting code written
against `paddle._C_ops.<op>` run unchanged.
"""
from __future__ import annotations

import importlib

_NAMESPACES = ("paddle_tpu.tensor", "paddle_tpu.nn.functional", "paddle_tpu")

# reference op name -> (module, attr) overrides where names diverge
_ALIASES = {
    "elementwise_add": ("paddle_tpu.tensor", "add"),
    "elementwise_sub": ("paddle_tpu.tensor", "subtract"),
    "elementwise_mul": ("paddle_tpu.tensor", "multiply"),
    "elementwise_div": ("paddle_tpu.tensor", "divide"),
    "reduce_sum": ("paddle_tpu.tensor", "sum"),
    "reduce_mean": ("paddle_tpu.tensor", "mean"),
    "softmax_with_cross_entropy": ("paddle_tpu.nn.functional", "cross_entropy"),
    "fill_constant": ("paddle_tpu.tensor", "full"),
}


def _inplace(fn):
    """Reference `op_` mutates its first arg; emulate by writing the result
    back into the input Tensor so callers that drop the return value still
    see the update."""
    import functools

    @functools.wraps(fn)
    def wrapped(x, *args, **kwargs):
        from .framework.core import Tensor

        out = fn(x, *args, **kwargs)
        if isinstance(x, Tensor) and isinstance(out, Tensor):
            # shape-changing inplace ops (reshape_, squeeze_, ...) mutate the
            # same tensor in the reference, so write back unconditionally
            x._value = out.value
            return x
        return out

    return wrapped


def __getattr__(name):
    if name.startswith("__"):
        raise AttributeError(name)
    if name in _ALIASES:
        mod, attr = _ALIASES[name]
        return getattr(importlib.import_module(mod), attr)
    base = name[:-1] if name.endswith("_") else name  # inplace variants
    for ns in _NAMESPACES:
        mod = importlib.import_module(ns)
        if hasattr(mod, name):
            fn = getattr(mod, name)
            # a same-named attr ending in _ may itself be the true inplace op
            return fn
        if hasattr(mod, base):
            return _inplace(getattr(mod, base))
    raise AttributeError(f"_C_ops has no op {name!r}")
