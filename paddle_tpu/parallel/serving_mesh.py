"""TP placement for the paged serving executor.

The serving tick goes multi-chip the GSPMD way (PAPERS.md): the paged
programs — chunked prefill, decode window, both speculative verify paths —
are NOT rewritten per shard. Instead this module places the executor's
device state onto a 1-D ``tp`` mesh and lets the partitioner slice the
compiled programs and insert the collectives:

- model params reuse the layer-declared training ``pspec`` annotations
  (``models/llama.py`` marks q/k/v/up/gate column-parallel and o/down
  row-parallel over the ``"tensor"`` axis); serving renames that axis to
  ``tp`` so a serving mesh never collides with a training mesh living in
  the same process;
- KV block pools ``(num_blocks, block_size, kv_heads, head_dim)`` shard
  the kv-head axis, so every shard holds its head-slice of EVERY block
  and the single host-side block table indexes all shards at once;
- per-(block, kv-head) int8 scales ``(num_blocks, kv_heads)`` shard with
  their heads;
- LoRA pool pages shard on the same axis as the base weight they touch:
  column-parallel targets (q/k/v/gate/up) shard the B-factor output dim,
  row-parallel targets (o/down) shard the A-factor input dim, so the
  batched BGMV delta stays inside the partitioned program with no
  per-adapter gather.

Why placement-only works bit-for-bit at the token level: the paged
programs index pools by block id and head — both sharding-invariant — and
the only cross-shard reductions GSPMD introduces (o_proj/down_proj psum)
reorder float accumulation without changing the greedy argmax on any
tested shape. Logits may differ in ulps from the single-chip program;
emitted tokens must not (tests/test_tp_serving.py gates this).

``jax.device_put`` lives HERE and not in ``paddle_tpu/inference/`` on
purpose: graftlint GL014 bans bare transfers inside the serving engine so
every cross-mesh byte moves through either these construction-time
placements or the offload/migration paths (kv_offload.py / fleet.py).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

__all__ = [
    "SERVING_TP_AXIS", "build_serving_mesh", "validate_tp",
    "mesh_fingerprint", "serving_param_specs", "place_params",
    "pool_spec", "place_pools", "lora_pool_specs", "place_lora_flat",
    "place_replicated", "audit_pool_shardings",
]

SERVING_TP_AXIS = "tp"

# training axis name whose layer pspecs carry the column/row-parallel
# layout serving reuses (see parallel/engine.py param_specs)
_TRAIN_TENSOR_AXIS = "tensor"


def build_serving_mesh(tp: int, devices: Optional[Sequence] = None) -> Mesh:
    """1-D ``tp`` mesh over the first ``tp`` addressable devices."""
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < tp:
        raise ValueError(
            f"mesh tp={tp} needs {tp} devices but only {len(devs)} are "
            f"addressable — on CPU dryruns set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N")
    return Mesh(np.array(devs[:tp]), (SERVING_TP_AXIS,))


def validate_tp(cfg, tp: int) -> None:
    """Every dimension the serving layout shards must split evenly —
    uneven splits would silently pad pool blocks and break the
    block-table addressing, so refuse at construction."""
    bad = []
    for dim, n in (("num_key_value_heads", cfg.num_key_value_heads),
                   ("num_attention_heads", cfg.num_attention_heads),
                   ("intermediate_size", cfg.intermediate_size),
                   ("vocab_size", cfg.vocab_size)):
        if n % tp:
            bad.append(f"{dim}={n}")
    if bad:
        raise ValueError(
            f"mesh tp={tp} does not divide {', '.join(bad)} — every "
            f"sharded dimension must split evenly across the tp axis")


def mesh_fingerprint(mesh: Optional[Mesh]) -> str:
    """Snapshot-stamp for the serving layout: ``tp1`` is the single-chip
    engine, ``tpN`` an N-way sharded one. Snapshot payloads are
    full-width host gathers, so any tp restores into any tp — the stamp
    records provenance, it is not a compatibility gate."""
    if mesh is None:
        return "tp1"
    return f"tp{mesh.shape[SERVING_TP_AXIS]}"


def serving_param_specs(model, mesh: Mesh) -> Dict[str, P]:
    """Layer pspecs with the training ``tensor`` axis renamed to ``tp``;
    params without a pspec (norms, rope tables) replicate."""
    specs: Dict[str, P] = {}
    for name, p in model.named_parameters():
        spec = getattr(p, "pspec", None)
        if spec is None:
            specs[name] = P()
        else:
            specs[name] = P(*[
                SERVING_TP_AXIS if a == _TRAIN_TENSOR_AXIS else None
                for a in spec])
    for name, b in model.named_buffers():
        specs.setdefault(name, P())
    return specs


def place_params(model, params: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    specs = serving_param_specs(model, mesh)
    return {name: jax.device_put(v, NamedSharding(mesh, specs.get(name, P())))
            for name, v in params.items()}


def pool_spec(ndim: int) -> P:
    """KV pool tensors shard the kv-head axis: codes/fp rows are
    (num_blocks, block_size, kv_heads, head_dim), int8 scales are
    (num_blocks, kv_heads)."""
    if ndim == 4:
        return P(None, None, SERVING_TP_AXIS, None)
    if ndim == 2:
        return P(None, SERVING_TP_AXIS)
    raise ValueError(f"unexpected pool tensor rank {ndim}")


def place_pools(pools: Sequence[Any], mesh: Mesh) -> List[Any]:
    return [jax.device_put(p, NamedSharding(mesh, pool_spec(p.ndim)))
            for p in pools]


# LoRA targets whose base weight is row-parallel (input dim sharded):
# their A factor shards its input dim; everything else is column-parallel
# and shards the B factor's output dim.
_ROW_PARALLEL_TARGETS = ("o", "down")


def lora_pool_specs(targets: Sequence[str]) -> List[P]:
    """Specs for the AdapterPool flat list [A_t0, B_t0, ..., scale]:
    A is (pages, layers, in, rank), B is (pages, layers, rank, out)."""
    specs: List[P] = []
    for t in targets:
        if t in _ROW_PARALLEL_TARGETS:
            specs.append(P(None, None, SERVING_TP_AXIS, None))   # A: in dim
            specs.append(P())                                    # B replicated
        else:
            specs.append(P())                                    # A replicated
            specs.append(P(None, None, None, SERVING_TP_AXIS))   # B: out dim
    specs.append(P())                                            # scale vector
    return specs


def place_lora_flat(targets: Sequence[str], flat: Sequence[Any],
                    mesh: Mesh) -> List[Any]:
    specs = lora_pool_specs(targets)
    if len(specs) != len(flat):
        raise ValueError(
            f"LoRA flat list has {len(flat)} tensors, expected "
            f"{len(specs)} for targets {tuple(targets)}")
    return [jax.device_put(a, NamedSharding(mesh, s))
            for a, s in zip(flat, specs)]


def place_replicated(x: Any, mesh: Mesh) -> Any:
    return jax.device_put(x, NamedSharding(mesh, P()))


def audit_pool_shardings(pools: Sequence[Any], mesh: Mesh) -> Dict[str, int]:
    """Conservation audit for the sharded pools: donation rotates pool
    buffers every trip, so verify each tensor still carries the layout it
    was placed with (a silent reshard to replicated would triple HBM and
    break the per-shard capacity math). Returns per-shard accounting for
    ``GenerationServer.assert_conserved()``."""
    tp = mesh.shape[SERVING_TP_AXIS]
    shard_bytes = 0
    for p in pools:
        want = NamedSharding(mesh, pool_spec(p.ndim))
        got = getattr(p, "sharding", None)
        if got is None or not got.is_equivalent_to(want, p.ndim):
            raise AssertionError(
                f"pool tensor {p.shape} lost its tp sharding: have {got}, "
                f"expected {want}")
        shard_bytes += p.nbytes // tp
    return {"tp": tp, "pool_tensors": len(pools),
            "pool_bytes_per_shard": shard_bytes}
