"""TP placement for the paged serving executor.

The serving tick goes multi-chip the GSPMD way (PAPERS.md): the paged
programs — chunked prefill, decode window, both speculative verify paths —
are NOT rewritten per shard. Instead this module places the executor's
device state onto a 1-D ``tp`` mesh and lets the partitioner slice the
compiled programs and insert the collectives:

- model params reuse the layer-declared training ``pspec`` annotations
  (``models/llama.py`` marks q/k/v/up/gate column-parallel and o/down
  row-parallel over the ``"tensor"`` axis); serving renames that axis to
  ``tp`` so a serving mesh never collides with a training mesh living in
  the same process;
- KV block pools ``(num_blocks, block_size, kv_heads, head_dim)`` shard
  the kv-head axis, so every shard holds its head-slice of EVERY block
  and the single host-side block table indexes all shards at once;
- per-(block, kv-head) int8 scales ``(num_blocks, kv_heads)`` shard with
  their heads;
- LoRA pool pages shard on the same axis as the base weight they touch:
  column-parallel targets (q/k/v/gate/up) shard the B-factor output dim,
  row-parallel targets (o/down) shard the A-factor input dim, so the
  batched BGMV delta stays inside the partitioned program with no
  per-adapter gather.

Why placement-only works bit-for-bit at the token level: the paged
programs index pools by block id and head — both sharding-invariant — and
the only cross-shard reductions GSPMD introduces (o_proj/down_proj psum)
reorder float accumulation without changing the greedy argmax on any
tested shape. Logits may differ in ulps from the single-chip program;
emitted tokens must not (tests/test_tp_serving.py gates this).

``jax.device_put`` lives HERE and not in ``paddle_tpu/inference/`` on
purpose: graftlint GL014 bans bare transfers inside the serving engine so
every cross-mesh byte moves through either these construction-time
placements or the offload/migration paths (kv_offload.py / fleet.py).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

__all__ = [
    "SERVING_TP_AXIS", "SERVING_CP_AXIS", "parse_mesh",
    "build_serving_mesh", "validate_tp", "validate_cp",
    "mesh_fingerprint", "serving_param_specs", "place_params",
    "pool_spec", "place_pools", "lora_pool_specs", "place_lora_flat",
    "place_replicated", "audit_pool_shardings",
]

SERVING_TP_AXIS = "tp"

# context-parallel axis for chunked prefill: the (1, C) prefill chunk is
# constrained to shard its sequence dim over ``cp`` while params and KV
# pools name only ``tp`` (replicated across cp), so GSPMD partitions the
# per-token work — embedding, q/k/v projections, rope — across the cp
# group and all-gathers the chunk's K/V before the pool scatter. Each
# shard then attends over the FULL written prefix, which is why cp>1 is
# bit-identical to cp=1: no reduction changes order, only batch-of-token
# work moves.
SERVING_CP_AXIS = "cp"

# training axis name whose layer pspecs carry the column/row-parallel
# layout serving reuses (see parallel/engine.py param_specs)
_TRAIN_TENSOR_AXIS = "tensor"


def parse_mesh(spec) -> Tuple[int, int]:
    """Normalize a ``GenerationServer(mesh=...)`` value to ``(tp, cp)``.

    Accepts None (single chip), a bare int (tp for backward compat),
    ``"tp=N"``, ``"cp=M"``, or the combined ``"tp=NxCp=M"`` (the ``x``
    separator is case-insensitive, as is each axis name)."""
    if spec is None:
        return 1, 1
    if isinstance(spec, int):
        if spec < 1:
            raise ValueError(f"mesh tp must be >= 1, got {spec}")
        return spec, 1
    if not isinstance(spec, str):
        raise ValueError(
            f"mesh must be None, an int, or 'tp=N'/'cp=M'/'tp=NxCp=M', "
            f"got {spec!r}")
    tp, cp = 1, 1
    seen = set()
    for part in spec.lower().split("x"):
        part = part.strip()
        if "=" not in part:
            raise ValueError(
                f"unrecognized mesh spec {spec!r} — expected "
                f"'tp=N', 'cp=M', or 'tp=NxCp=M'")
        axis, _, val = part.partition("=")
        axis = axis.strip()
        if axis not in ("tp", "cp") or axis in seen:
            raise ValueError(
                f"unrecognized mesh spec {spec!r} — expected "
                f"'tp=N', 'cp=M', or 'tp=NxCp=M'")
        seen.add(axis)
        try:
            n = int(val)
        except ValueError:
            raise ValueError(
                f"mesh axis {axis!r} needs an integer size, got {val!r}")
        if n < 1:
            raise ValueError(f"mesh {axis} must be >= 1, got {n}")
        if axis == "tp":
            tp = n
        else:
            cp = n
    return tp, cp


def build_serving_mesh(tp: int, cp: int = 1,
                       devices: Optional[Sequence] = None) -> Mesh:
    """``tp`` mesh over the first ``tp*cp`` addressable devices; stays
    1-D at ``cp=1`` (byte-identical to the pre-cp layout) and becomes a
    2-D ``(tp, cp)`` mesh otherwise — every existing spec that names
    only ``tp`` keeps its meaning (replicated over cp)."""
    if tp < 1:
        raise ValueError(f"tp must be >= 1, got {tp}")
    if cp < 1:
        raise ValueError(f"cp must be >= 1, got {cp}")
    devs = list(devices) if devices is not None else jax.devices()
    if len(devs) < tp * cp:
        raise ValueError(
            f"mesh tp={tp} cp={cp} needs {tp * cp} devices but only "
            f"{len(devs)} are addressable — on CPU dryruns set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=N")
    if cp == 1:
        return Mesh(np.array(devs[:tp]), (SERVING_TP_AXIS,))
    return Mesh(np.array(devs[:tp * cp]).reshape(tp, cp),
                (SERVING_TP_AXIS, SERVING_CP_AXIS))


def validate_tp(cfg, tp: int) -> None:
    """Every dimension the serving layout shards must split evenly —
    uneven splits would silently pad pool blocks and break the
    block-table addressing, so refuse at construction."""
    bad = []
    for dim, n in (("num_key_value_heads", cfg.num_key_value_heads),
                   ("num_attention_heads", cfg.num_attention_heads),
                   ("intermediate_size", cfg.intermediate_size),
                   ("vocab_size", cfg.vocab_size)):
        if n % tp:
            bad.append(f"{dim}={n}")
    if bad:
        raise ValueError(
            f"mesh tp={tp} does not divide {', '.join(bad)} — every "
            f"sharded dimension must split evenly across the tp axis")


def validate_cp(cp: int, prefill_chunk: int) -> None:
    """The prefill chunk's sequence dim is the ONLY thing cp shards, so
    the (block-rounded) chunk length must split evenly — an uneven split
    would make GSPMD pad the chunk and the scatter's padded rows would
    land outside the scratch-masked region."""
    if cp > 1 and prefill_chunk % cp:
        raise ValueError(
            f"mesh cp={cp} does not divide prefill_chunk="
            f"{prefill_chunk} — the chunked-prefill sequence dim must "
            f"split evenly across the cp axis")


def mesh_fingerprint(mesh: Optional[Mesh]) -> str:
    """Snapshot-stamp for the serving layout: ``tp1`` is the single-chip
    engine, ``tpN`` an N-way sharded one, ``tpNcpM`` a context-parallel
    one. Snapshot payloads are full-width host gathers, so any layout
    restores into any other — the stamp records provenance, it is not a
    compatibility gate."""
    if mesh is None:
        return "tp1"
    cp = mesh.shape.get(SERVING_CP_AXIS, 1)
    tp = mesh.shape[SERVING_TP_AXIS]
    return f"tp{tp}" if cp == 1 else f"tp{tp}cp{cp}"


def serving_param_specs(model, mesh: Mesh) -> Dict[str, P]:
    """Layer pspecs with the training ``tensor`` axis renamed to ``tp``;
    params without a pspec (norms, rope tables) replicate."""
    specs: Dict[str, P] = {}
    for name, p in model.named_parameters():
        spec = getattr(p, "pspec", None)
        if spec is None:
            specs[name] = P()
        else:
            specs[name] = P(*[
                SERVING_TP_AXIS if a == _TRAIN_TENSOR_AXIS else None
                for a in spec])
    for name, b in model.named_buffers():
        specs.setdefault(name, P())
    return specs


def place_params(model, params: Dict[str, Any], mesh: Mesh) -> Dict[str, Any]:
    specs = serving_param_specs(model, mesh)
    return {name: jax.device_put(v, NamedSharding(mesh, specs.get(name, P())))
            for name, v in params.items()}


def pool_spec(ndim: int) -> P:
    """KV pool tensors shard the kv-head axis: codes/fp rows are
    (num_blocks, block_size, kv_heads, head_dim), int8 scales are
    (num_blocks, kv_heads)."""
    if ndim == 4:
        return P(None, None, SERVING_TP_AXIS, None)
    if ndim == 2:
        return P(None, SERVING_TP_AXIS)
    raise ValueError(f"unexpected pool tensor rank {ndim}")


def place_pools(pools: Sequence[Any], mesh: Mesh) -> List[Any]:
    return [jax.device_put(p, NamedSharding(mesh, pool_spec(p.ndim)))
            for p in pools]


# LoRA targets whose base weight is row-parallel (input dim sharded):
# their A factor shards its input dim; everything else is column-parallel
# and shards the B factor's output dim.
_ROW_PARALLEL_TARGETS = ("o", "down")


def lora_pool_specs(targets: Sequence[str]) -> List[P]:
    """Specs for the AdapterPool flat list [A_t0, B_t0, ..., scale]:
    A is (pages, layers, in, rank), B is (pages, layers, rank, out)."""
    specs: List[P] = []
    for t in targets:
        if t in _ROW_PARALLEL_TARGETS:
            specs.append(P(None, None, SERVING_TP_AXIS, None))   # A: in dim
            specs.append(P())                                    # B replicated
        else:
            specs.append(P())                                    # A replicated
            specs.append(P(None, None, None, SERVING_TP_AXIS))   # B: out dim
    specs.append(P())                                            # scale vector
    return specs


def place_lora_flat(targets: Sequence[str], flat: Sequence[Any],
                    mesh: Mesh) -> List[Any]:
    specs = lora_pool_specs(targets)
    if len(specs) != len(flat):
        raise ValueError(
            f"LoRA flat list has {len(flat)} tensors, expected "
            f"{len(specs)} for targets {tuple(targets)}")
    return [jax.device_put(a, NamedSharding(mesh, s))
            for a, s in zip(flat, specs)]


def place_replicated(x: Any, mesh: Mesh) -> Any:
    return jax.device_put(x, NamedSharding(mesh, P()))


def audit_pool_shardings(pools: Sequence[Any], mesh: Mesh) -> Dict[str, int]:
    """Conservation audit for the sharded pools: donation rotates pool
    buffers every trip, so verify each tensor still carries the layout it
    was placed with (a silent reshard to replicated would triple HBM and
    break the per-shard capacity math). Returns per-shard accounting for
    ``GenerationServer.assert_conserved()``."""
    tp = mesh.shape[SERVING_TP_AXIS]
    shard_bytes = 0
    for p in pools:
        want = NamedSharding(mesh, pool_spec(p.ndim))
        got = getattr(p, "sharding", None)
        if got is None or not got.is_equivalent_to(want, p.ndim):
            raise AssertionError(
                f"pool tensor {p.shape} lost its tp sharding: have {got}, "
                f"expected {want}")
        shard_bytes += p.nbytes // tp
    return {"tp": tp, "pool_tensors": len(pools),
            "pool_bytes_per_shard": shard_bytes}
