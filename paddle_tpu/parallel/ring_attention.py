"""Ring attention — context parallelism for long sequences.

NEW DESIGN: the reference has no sequence/context parallelism at all
(SURVEY §5.7 — grep-verified absent); its TP all-gathers full activations so
sequence length is bounded by one chip's HBM. Here the sequence axis is
sharded over the mesh's 'context' axis and K/V blocks rotate around the ring
via lax.ppermute, overlapping each hop with the blockwise-softmax compute of
the resident block (the standard ring-attention recipe on ICI).

Used inside shard_map (the explicit-collectives region); composes with the
Pallas flash kernel for the per-block compute.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@functools.partial(jax.checkpoint, static_argnums=())
def _block_attn(q, k, v, scale, mask):
    """One KV block's contribution: returns (m, l, acc) pieces.

    q: (B,H,Sq,D) k/v: (B,H,Sk,D) mask: (Sq,Sk) bool or None.
    Remat-wrapped: the (Sq, Sk) score block is recomputed in backward instead
    of being saved per ring hop, keeping residuals O(S·D) like the flash
    kernel (n hops would otherwise stash n score blocks each).
    """
    s = jnp.einsum("bhsd,bhtd->bhst", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)  # (B,H,Sq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhst,bhtd->bhsd", p, v.astype(jnp.float32))
    return m, l, acc


def ring_attention(q, k, v, axis_name: str = "context", causal: bool = True,
                   scale: Optional[float] = None):
    """Per-shard ring attention body (call inside shard_map).

    q, k, v: local shards (B, H, S_local, D); sequence dim sharded over
    `axis_name`. Returns local output shard (B, H, S_local, D).
    """
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape
    sc = scale if scale is not None else 1.0 / math.sqrt(D)

    q_pos = my_idx * S + jnp.arange(S)  # global positions of local queries

    # derive the carries from q so they inherit the 'varying over axis_name'
    # type shard_map's scan check requires
    zero = (q[..., 0] * 0.0).astype(jnp.float32)  # (B,H,S)
    m0 = zero + NEG_INF
    l0 = zero
    acc0 = (q * 0.0).astype(jnp.float32)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        m, l, acc, kb, vb = carry
        src = (my_idx - t) % n  # which shard's block we currently hold
        k_pos = src * S + jnp.arange(S)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = None
        bm, bl, bacc = _block_attn(q, kb, vb, sc, mask)
        m_new = jnp.maximum(m, bm)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(bm - m_new)
        l_new = l * alpha + bl * beta
        acc_new = acc * alpha[..., None] + bacc * beta[..., None]
        # rotate K/V to the next shard (overlapped with compute by XLA since
        # the ppermute has no data dependence on this step's attention)
        kb_next = jax.lax.ppermute(kb, axis_name, perm)
        vb_next = jax.lax.ppermute(vb, axis_name, perm)
        return (m_new, l_new, acc_new, kb_next, vb_next), None

    (m, l, acc, _, _), _ = jax.lax.scan(step, (m0, l0, acc0, k, v), jnp.arange(n))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention_bshd(q, k, v, axis_name: str = "context", causal: bool = True,
                        scale: Optional[float] = None):
    """(B, S, H, D) layout wrapper."""
    out = ring_attention(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                         jnp.swapaxes(v, 1, 2), axis_name, causal, scale)
    return jnp.swapaxes(out, 1, 2)


def ulysses_attention_bshd(q, k, v, axis_name: str = "sep", causal: bool = True,
                           scale: Optional[float] = None, attn_fn=None):
    """Ulysses/DeepSpeed-style sequence parallelism: all_to_all swaps the
    sharded dim from sequence→heads, runs full-sequence attention locally on
    H/n heads, then swaps back (NEW design; absent in reference, SURVEY §2.3).

    q,k,v local: (B, S/n, H, D) → output (B, S/n, H, D).
    """
    def a2a_seq_to_heads(x):
        # (B, S/n, H, D) -> (B, S, H/n, D)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    def a2a_heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2, tiled=True)

    qg = a2a_seq_to_heads(q)
    kg = a2a_seq_to_heads(k)
    vg = a2a_seq_to_heads(v)
    if attn_fn is None:
        from ..ops.flash_attention import flash_attention_bshd

        out = flash_attention_bshd(qg, kg, vg, causal=causal, scale=scale)
    else:
        out = attn_fn(qg, kg, vg)
    return a2a_heads_to_seq(out)
