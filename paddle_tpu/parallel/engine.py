"""Sharded train-step builder — the GSPMD-native auto-parallel Engine.

Ref: python/paddle/distributed/auto_parallel/engine.py:58 (Engine.fit :811,
_build :515 → _parallel :700) + parallelizer_v2.py: the reference completes
dist attrs, slices per-rank programs (Partitioner) and inserts reshard comm.
Here all three steps are XLA's job: we (1) collect per-parameter
PartitionSpecs (layer-provided, e.g. ColumnParallelLinear, or FSDP-style
auto-sharding), (2) jit the (loss, grads, opt-update) step with those
shardings as in/out shardings over the mesh, (3) let GSPMD propagate and
insert collectives. ZeRO == param/opt-state sharding over the "sharding"
axis (ref dygraph_sharding_optimizer.py:29, group_sharded_stage{2,3}.py).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..framework.core import Parameter, Tensor, no_grad_ctx
from ..jit import functional_call, state_values
from .api import _filter_spec, auto_shard_spec, mesh_context


def param_specs(model, mesh: Mesh, fsdp: bool = False, fsdp_axis: str = "sharding"
                ) -> Dict[str, P]:
    """fsdp=True applies the canonical ZeRO-3 layout policy, shared with
    distributed.sharding (ref group_sharded_stage3.py:60 — param sharding
    with fwd allgather, which GSPMD emits automatically). Even splits only:
    these specs are applied eagerly via device_put in _build_state."""
    axis_size = mesh.shape[fsdp_axis] if fsdp_axis in mesh.axis_names else 1
    specs: Dict[str, P] = {}
    for name, p in model.named_parameters():
        spec = getattr(p, "pspec", None)
        if spec is None:
            spec = (auto_shard_spec(p.value.shape, axis_size, axis=fsdp_axis)
                    if fsdp else P())
        specs[name] = _filter_spec(spec, mesh)
    for name, b in model.named_buffers():
        specs[name] = P()
    return specs


def _sharding_of(mesh, spec):
    return NamedSharding(mesh, spec)


class ParallelEngine:
    """Owns sharded params + optimizer state and a compiled train step.

    Stateful on purpose (donated buffers): eager model params are copied in
    once, updated on-device every step, and synced back on demand
    (`sync_to_model`) for checkpointing through the normal state_dict path.
    """

    def __init__(self, model, optimizer=None, loss_fn: Optional[Callable] = None,
                 mesh: Optional[Mesh] = None, fsdp: bool = False, remat: bool = False,
                 remat_policy: Optional[str] = "dots", batch_spec: Any = P("data"),
                 donate: bool = True, abstract: bool = False):
        """abstract=True keeps params/opt-state as ShapeDtypeStructs — the
        step can be .lower()ed (AOT partitioning validation at any scale)
        but not executed."""
        from ..distributed.collective import get_global_mesh

        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or get_global_mesh()
        if self.mesh is None:
            devs = np.array(jax.devices()[:1])
            self.mesh = Mesh(devs.reshape(1), ("data",))
        self.fsdp = fsdp
        self.remat = remat
        self.remat_policy = remat_policy
        self.batch_spec = batch_spec
        self._donate = donate
        self._abstract = abstract
        self._build_state()
        self._train_step = None
        self._eval_step = None

    # ------------------------------------------------------------------ state
    def _build_state(self):
        mesh = self.mesh
        # single-device mesh: keep plain (unsharded) arrays — NamedSharding
        # inputs route jit through the SPMD partitioner, which compiles a
        # measurably worse program around Pallas custom calls (6x step time
        # at S=16k on one v5e); GSPMD buys nothing with one device anyway
        self._spmd = mesh.size > 1
        self.specs = param_specs(self.model, mesh, fsdp=self.fsdp)
        vals = state_values(self.model)
        self._trainable = {name for name, p in self.model.named_parameters()
                           if p.trainable}
        if self._abstract:
            self.params = {
                name: jax.ShapeDtypeStruct(
                    v.shape, v.dtype,
                    sharding=_sharding_of(mesh, self.specs.get(name, P())))
                for name, v in vals.items()
            }
            if self.optimizer is not None:
                train = {n: v for n, v in self.params.items()
                         if n in self._trainable}
                st = jax.eval_shape(self.optimizer.init_state, train)
                # re-attach shardings per owning param
                self.opt_state = {
                    n: {k: jax.ShapeDtypeStruct(
                        s.shape, s.dtype,
                        sharding=_sharding_of(mesh, self.specs.get(n, P())))
                        for k, s in slots.items()}
                    for n, slots in st.items()
                }
            else:
                self.opt_state = {}
            return
        if not self._spmd:
            # copy: self.params gets donated every step; aliasing the model's
            # live Parameter buffers would invalidate eager use of the model
            # (model(x), p.value) until sync_to_model
            self.params = {name: jnp.copy(v) for name, v in vals.items()}
            self.opt_state = (self.optimizer.init_state(
                {n: v for n, v in self.params.items() if n in self._trainable})
                if self.optimizer is not None else {})
            return
        self.params = {
            name: jax.device_put(v, _sharding_of(mesh, self.specs.get(name, P())))
            for name, v in vals.items()
        }
        if self.optimizer is not None:
            train_params = {n: v for n, v in self.params.items() if n in self._trainable}
            state = self.optimizer.init_state(train_params)
            # opt state shards like its param (ZeRO-1/2: ref
            # dygraph_sharding_optimizer.py — state lives sharded)
            self.opt_state = {
                n: {k: jax.device_put(v, _sharding_of(mesh, self.specs.get(n, P())))
                    for k, v in slots.items()}
                for n, slots in state.items()
            }
        else:
            self.opt_state = {}

    # ------------------------------------------------------------- train step
    def _loss_from_batch(self, params, batch, state_out=None):
        """state_out: dict capturing buffer values the forward reassigned
        (BN running stats etc.) so the jitted step can carry them."""
        model, loss_fn = self.model, self.loss_fn

        def call(p, *args):
            with mesh_context(self.mesh):
                out = functional_call(model, p, *[Tensor(a) for a in args],
                                      mutated_state=state_out)
            return out

        if isinstance(batch, dict):
            inputs = batch.get("inputs", ())
            labels = batch.get("labels", ())
            inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
            labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        else:
            *inputs, label = batch
            labels = (label,)
        if loss_fn is None:
            # model computes its own loss (e.g. fused lm-head+CE path where
            # logits must never materialize): forward(*inputs, *labels) -> loss
            out = call(params, *inputs, *labels)
            out = out[0] if isinstance(out, (list, tuple)) else out
            return out.value if isinstance(out, Tensor) else out
        out = call(params, *inputs)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        with mesh_context(self.mesh):
            loss = loss_fn(*outs, *[Tensor(l) for l in labels])
        return loss.value if isinstance(loss, Tensor) else loss

    @staticmethod
    def _raw(v):
        return v.value if isinstance(v, Tensor) else v

    def _batch_sharding(self, arr, spec):
        """NamedSharding for one batch array: drop mesh axes the array's dims
        can't be evenly split over (tiny eval batches on a big global mesh)."""
        spec = _filter_spec(spec, self.mesh)
        dims = []
        for i, ax in enumerate(spec):
            if ax is None:
                dims.append(None)
                continue
            axes = (ax,) if isinstance(ax, str) else tuple(ax)
            size = int(np.prod([self.mesh.shape[a] for a in axes]))
            dims.append(ax if i < arr.ndim and arr.shape[i] % size == 0 else None)
        return _sharding_of(self.mesh, P(*dims))

    def build_train_step(self):
        mesh = self.mesh
        opt = self.optimizer

        def step_fn(params, opt_state, step_count, lr, batch):
            train = {n: v for n, v in params.items() if n in self._trainable}
            frozen = {n: v for n, v in params.items() if n not in self._trainable}

            def loss_of(tr):
                # aux = buffers the forward reassigned (BN running stats):
                # captured from the eager side effect and carried as a jit
                # output so the compiled path matches eager BN semantics
                mutated = {}
                loss = self._loss_from_batch({**tr, **frozen}, batch,
                                             state_out=mutated)
                new_bufs = {n: self._raw(v) for n, v in mutated.items()
                            if n not in self._trainable}
                return loss, new_bufs

            if self.remat:
                # keep MXU outputs, recompute elementwise (the reference's
                # recompute granularity is whole-layer; saving dot outputs is
                # the better HBM/FLOP tradeoff on TPU). Named policies rely
                # on the checkpoint_name annotations in models/llama.py
                # ("attn_out", "qkv", "mlp_out").
                cp = jax.checkpoint_policies
                policy = None
                if self.remat_policy == "dots":
                    policy = cp.dots_with_no_batch_dims_saveable
                elif self.remat_policy == "nothing":
                    policy = cp.nothing_saveable
                elif self.remat_policy == "save_attn":
                    policy = cp.save_only_these_names("attn_out")
                elif self.remat_policy == "save_attn_mlp":
                    policy = cp.save_only_these_names("attn_out", "mlp_out")
                elif self.remat_policy == "save_qkv_attn":
                    policy = cp.save_only_these_names("attn_out", "qkv")
                elif self.remat_policy == "offload_attn":
                    # activations ride host RAM instead of being recomputed
                    policy = cp.save_and_offload_only_these_names(
                        names_which_can_be_saved=[],
                        names_which_can_be_offloaded=["attn_out", "mlp_out"],
                        offload_src="device", offload_dst="pinned_host")
                elif self.remat_policy is not None and \
                        self.remat_policy != "none":
                    raise ValueError(
                        f"unknown remat_policy {self.remat_policy!r}")
                loss_of_ = jax.checkpoint(loss_of, policy=policy)
            else:
                loss_of_ = loss_of
            (loss, new_bufs), grads = jax.value_and_grad(
                loss_of_, has_aux=True)(train)
            new_train, new_state = opt.pure_update(train, grads, opt_state, lr,
                                                   step_count + 1)
            if self._spmd:
                # keep shardings stable across steps
                new_train = {
                    n: jax.lax.with_sharding_constraint(
                        v, _sharding_of(mesh, self.specs.get(n, P())))
                    for n, v in new_train.items()
                }
            frozen = {**frozen, **new_bufs}
            return {**new_train, **frozen}, new_state, step_count + 1, loss

        self._step_count = jnp.zeros((), jnp.int32)
        donate = (0, 1, 2) if self._donate else ()
        self._train_step = jax.jit(step_fn, donate_argnums=donate)
        return self._train_step

    def train_batch(self, *batch):
        """Run one compiled, sharded train step; returns host loss."""
        if self._train_step is None:
            self.build_train_step()
        lr = self.optimizer.get_lr()
        batch_vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                           for b in batch)
        if self._spmd:
            batch_vals = tuple(
                jax.device_put(b, self._batch_sharding(
                    b, self.batch_spec if not isinstance(self.batch_spec, (list, tuple))
                    else self.batch_spec[i]))
                for i, b in enumerate(batch_vals))
        self.params, self.opt_state, self._step_count, loss = self._train_step(
            self.params, self.opt_state, self._step_count, lr, batch_vals)
        from ..framework.monitor import monitor_add

        monitor_add("engine_train_steps")
        from ..distributed.fleet.elastic import pulse_heartbeat

        pulse_heartbeat()  # progress-based hang detection (--elastic_timeout)
        if isinstance(self.optimizer._learning_rate, object) and hasattr(
                self.optimizer._learning_rate, "step"):
            try:
                self.optimizer._learning_rate.step()
            except TypeError:
                pass
        return Tensor(loss)

    def eval_batch(self, *batch):
        if self._eval_step is None:
            def ev(params, batch):
                return self._loss_from_batch(params, batch)

            self._eval_step = jax.jit(ev)
        batch_vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                           for b in batch)
        return Tensor(self._eval_step(self.params, batch_vals))

    # ------------------------------------------------------------------- sync
    def sync_to_model(self):
        store = {**dict(self.model.named_parameters()),
                 **dict(self.model.named_buffers())}
        for name, v in self.params.items():
            if name in store:
                store[name]._value = v

    def state_dict(self):
        self.sync_to_model()
        return self.model.state_dict()


def parallelize(model, optimizer=None, loss_fn=None, mesh=None, **kwargs) -> ParallelEngine:
    return ParallelEngine(model, optimizer=optimizer, loss_fn=loss_fn, mesh=mesh, **kwargs)


def make_train_step(model, loss_fn, optimizer, mesh=None, **kwargs):
    eng = ParallelEngine(model, optimizer=optimizer, loss_fn=loss_fn, mesh=mesh, **kwargs)
    eng.build_train_step()
    return eng
