"""Sharded train-step builder — the GSPMD-native auto-parallel Engine.

Ref: python/paddle/distributed/auto_parallel/engine.py:58 (Engine.fit :811,
_build :515 → _parallel :700) + parallelizer_v2.py: the reference completes
dist attrs, slices per-rank programs (Partitioner) and inserts reshard comm.
Here all three steps are XLA's job: we (1) collect per-parameter
PartitionSpecs (layer-provided, e.g. ColumnParallelLinear, or FSDP-style
auto-sharding), (2) jit the (loss, grads, opt-update) step with those
shardings as in/out shardings over the mesh, (3) let GSPMD propagate and
insert collectives. ZeRO == param/opt-state sharding over the "sharding"
axis (ref dygraph_sharding_optimizer.py:29, group_sharded_stage{2,3}.py).
"""
from __future__ import annotations

import functools
import os
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..faults import NULL_INJECTOR, StepFault
from ..framework.core import Parameter, Tensor, no_grad_ctx
from ..jit import functional_call, state_values
from .api import _filter_spec, auto_shard_spec, mesh_context


def param_specs(model, mesh: Mesh, fsdp: bool = False, fsdp_axis: str = "sharding"
                ) -> Dict[str, P]:
    """fsdp=True applies the canonical ZeRO-3 layout policy, shared with
    distributed.sharding (ref group_sharded_stage3.py:60 — param sharding
    with fwd allgather, which GSPMD emits automatically). Even splits only:
    these specs are applied eagerly via device_put in _build_state."""
    axis_size = mesh.shape[fsdp_axis] if fsdp_axis in mesh.axis_names else 1
    specs: Dict[str, P] = {}
    for name, p in model.named_parameters():
        spec = getattr(p, "pspec", None)
        if spec is None:
            spec = (auto_shard_spec(p.value.shape, axis_size, axis=fsdp_axis)
                    if fsdp else P())
        specs[name] = _filter_spec(spec, mesh)
    for name, b in model.named_buffers():
        specs[name] = P()
    return specs


def _sharding_of(mesh, spec):
    return NamedSharding(mesh, spec)


class ParallelEngine:
    """Owns sharded params + optimizer state and a compiled train step.

    Stateful on purpose (donated buffers): eager model params are copied in
    once, updated on-device every step, and synced back on demand
    (`sync_to_model`) for checkpointing through the normal state_dict path.
    """

    def __init__(self, model, optimizer=None, loss_fn: Optional[Callable] = None,
                 mesh: Optional[Mesh] = None, fsdp: bool = False, remat: bool = False,
                 remat_policy: Optional[str] = "dots", batch_spec: Any = P("data"),
                 donate: bool = True, abstract: bool = False,
                 offload_opt_state: bool = False,
                 alias_model_params: bool = False,
                 grad_accum: int = 1,
                 injector=NULL_INJECTOR,
                 telemetry=None):
        """abstract=True keeps params/opt-state as ShapeDtypeStructs — the
        step can be .lower()ed (AOT partitioning validation at any scale)
        but not executed.

        grad_accum=k splits each train_batch into k microbatches scanned
        inside the compiled step (leading batch dim must divide by k), with
        ONE optimizer update on the mean gradient — amortizes the
        optimizer/PCIe cost on the offload path (ref
        gradient_merge_optimizer.py; PT_ACCUM_DTYPE sets the accumulator
        dtype, default float32).

        offload_opt_state=True parks the optimizer moments in host RAM
        (pinned_host memory) between steps — the compiled step streams them
        d2h/h2d through PCIe, freeing ~8 bytes/param of HBM so a ~2-3B
        AdamW config fits one 16 GB chip (ref group_sharded_stage3.py:60
        cpu_offload semantics, done as XLA memory kinds instead of tensor
        .cpu() hooks). Single-device path only.
        """
        from ..distributed.collective import get_global_mesh

        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.mesh = mesh or get_global_mesh()
        if self.mesh is None:
            devs = np.array(jax.devices()[:1])
            self.mesh = Mesh(devs.reshape(1), ("data",))
        self.fsdp = fsdp
        self.remat = remat
        self.remat_policy = remat_policy
        self.batch_spec = batch_spec
        self._donate = donate
        self._abstract = abstract
        self._offload_opt = offload_opt_state
        self.grad_accum = int(grad_accum)
        if self.grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        # alias_model_params=True skips the defensive params copy (single-
        # device path): saves a full param-size HBM allocation on big
        # models, at the cost that the eager model is INVALID until
        # sync_to_model (donation consumes the shared buffers)
        self._alias_params = alias_model_params
        self.injector = injector or NULL_INJECTOR
        # optional TrainTelemetry (paddle_tpu/telemetry.py). None (the
        # default) keeps train_batch free of timestamp reads and of the
        # per-step block_until_ready the device_wait span needs.
        self.telemetry = telemetry
        if offload_opt_state and self.mesh.size > 1:
            raise NotImplementedError(
                "offload_opt_state is single-device; multi-chip runs shard "
                "the state over the mesh instead (ZeRO)")
        self._build_state()
        self._train_step = None
        self._eval_step = None

    @staticmethod
    def _place(v, sharding):
        """Materialize a host value under `sharding`. Multi-process: the
        mesh spans non-addressable devices, so assemble the global array
        from the (identical-per-process) host value — each process
        materializes only its addressable shards (ref parallel.py:108
        sync_params broadcast; identical host values replace the
        broadcast)."""
        if jax.process_count() <= 1:
            # copy first: device_put may alias the source buffer (zero-copy
            # same-device path), and the engine donates its state every
            # step — an aliased source (the model's live eager param)
            # would be deleted by the first step, breaking the "params are
            # copied in once" contract
            if isinstance(v, jax.Array):
                v = jnp.copy(v)
            return jax.device_put(v, sharding)
        arr = np.asarray(v)
        return jax.make_array_from_callback(
            arr.shape, sharding, lambda idx: arr[idx])

    @staticmethod
    def _host_sharding():
        from jax.sharding import SingleDeviceSharding

        dev = jax.devices()[0]
        kinds = {m.kind for m in dev.addressable_memories()}
        if "pinned_host" not in kinds:
            # the CPU backend has no device-placement custom call at all
            # (annotate_device_placement unregistered) — offload is a
            # TPU-backend feature, verified on chip (BASELINE.md round 4)
            raise NotImplementedError(
                f"offload_opt_state needs a backend with pinned_host "
                f"memory; this backend has {sorted(kinds)}")
        return SingleDeviceSharding(dev, memory_kind="pinned_host")

    # ------------------------------------------------------------------ state
    def _build_state(self):
        mesh = self.mesh
        # multi-process: a committed single-device jnp scalar can't enter a
        # jit spanning the global mesh; a host value is treated as
        # replicated (identical across processes by construction). Lives
        # here (not build_train_step) so engine_state_dict works on a
        # freshly built engine; set_engine_state may overwrite it.
        self._step_count = (np.zeros((), np.int32)
                            if jax.process_count() > 1
                            else jnp.zeros((), jnp.int32))
        # single-device mesh: keep plain (unsharded) arrays — NamedSharding
        # inputs route jit through the SPMD partitioner, which compiles a
        # measurably worse program around Pallas custom calls (6x step time
        # at S=16k on one v5e); GSPMD buys nothing with one device anyway
        self._spmd = mesh.size > 1
        self.specs = param_specs(self.model, mesh, fsdp=self.fsdp)
        vals = state_values(self.model)
        self._trainable = {name for name, p in self.model.named_parameters()
                           if p.trainable}
        if self._abstract:
            self.params = {
                name: jax.ShapeDtypeStruct(
                    v.shape, v.dtype,
                    sharding=_sharding_of(mesh, self.specs.get(name, P())))
                for name, v in vals.items()
            }
            if self.optimizer is not None:
                train = {n: v for n, v in self.params.items()
                         if n in self._trainable}
                st = jax.eval_shape(self.optimizer.init_state, train)
                # re-attach shardings per owning param
                self.opt_state = {
                    n: {k: jax.ShapeDtypeStruct(
                        s.shape, s.dtype,
                        sharding=_sharding_of(mesh, self.specs.get(n, P())))
                        for k, s in slots.items()}
                    for n, slots in st.items()
                }
            else:
                self.opt_state = {}
            return
        if not self._spmd:
            # copy: self.params gets donated every step; aliasing the model's
            # live Parameter buffers would invalidate eager use of the model
            # (model(x), p.value) until sync_to_model
            self.params = (dict(vals) if self._alias_params else
                           {name: jnp.copy(v) for name, v in vals.items()})
            train = {n: v for n, v in self.params.items()
                     if n in self._trainable}
            if self.optimizer is None:
                self.opt_state = {}
            elif self._offload_opt:
                if getattr(self.optimizer, "_mt_active", lambda: False)():
                    raise ValueError(
                        "offload_opt_state and PT_MT_ADAMW are mutually "
                        "exclusive (the flat state has no per-param layout "
                        "to stream); unset one")
                # init the slots INSIDE a jit whose out_shardings are host
                # memory: materializing the full f32 state on device first
                # (19 GB at 2.4B) is exactly what offload must avoid
                host = self._host_sharding()
                sds = jax.eval_shape(self.optimizer.init_state, train)
                self.opt_state = jax.jit(
                    self.optimizer.init_state,
                    out_shardings=jax.tree.map(lambda _: host, sds))(train)
            else:
                self.opt_state = self.optimizer.init_state(train)
            return
        multiproc = jax.process_count() > 1
        self.params = {
            name: self._place(v, _sharding_of(mesh, self.specs.get(name, P())))
            for name, v in vals.items()
        }
        if self.optimizer is not None:
            train_params = {n: v for n, v in self.params.items() if n in self._trainable}
            # opt state shards like its param (ZeRO-1/2: ref
            # dygraph_sharding_optimizer.py — state lives sharded)
            state_sh = {
                n: _sharding_of(mesh, self.specs.get(n, P()))
                for n in train_params}
            if multiproc:
                # eager ops on global arrays with non-addressable shards are
                # rejected; init through jit so XLA produces sharded state
                sds = jax.eval_shape(self.optimizer.init_state, train_params)
                out_sh = {n: {k: state_sh[n] for k in slots}
                          for n, slots in sds.items()}
                self.opt_state = jax.jit(
                    self.optimizer.init_state,
                    out_shardings=out_sh)(train_params)
            else:
                state = self.optimizer.init_state(train_params)
                self.opt_state = {
                    n: {k: jax.device_put(v, state_sh[n])
                        for k, v in slots.items()}
                    for n, slots in state.items()
                }
        else:
            self.opt_state = {}

    # ------------------------------------------------------------- train step
    def _loss_from_batch(self, params, batch, state_out=None):
        """state_out: dict capturing buffer values the forward reassigned
        (BN running stats etc.) so the jitted step can carry them."""
        model, loss_fn = self.model, self.loss_fn

        def call(p, *args):
            with mesh_context(self.mesh):
                out = functional_call(model, p, *[Tensor(a) for a in args],
                                      mutated_state=state_out)
            return out

        if isinstance(batch, dict):
            inputs = batch.get("inputs", ())
            labels = batch.get("labels", ())
            inputs = inputs if isinstance(inputs, (list, tuple)) else (inputs,)
            labels = labels if isinstance(labels, (list, tuple)) else (labels,)
        else:
            *inputs, label = batch
            labels = (label,)
        if loss_fn is None:
            # model computes its own loss (e.g. fused lm-head+CE path where
            # logits must never materialize): forward(*inputs, *labels) -> loss
            out = call(params, *inputs, *labels)
            out = out[0] if isinstance(out, (list, tuple)) else out
            return out.value if isinstance(out, Tensor) else out
        out = call(params, *inputs)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        with mesh_context(self.mesh):
            loss = loss_fn(*outs, *[Tensor(l) for l in labels])
        return loss.value if isinstance(loss, Tensor) else loss

    @staticmethod
    def _raw(v):
        return v.value if isinstance(v, Tensor) else v

    def _assemble_batch(self, batch):
        """Device-ready batch tuple, shared by train_batch/eval_batch.

        Multi-process (ref test_dist_base.py:899 per-rank readers): each
        process passes its LOCAL shard of the batch; the global array is
        assembled against the batch sharding without any cross-host gather
        of example data. Unlike the single-process path (which silently
        replicates a ragged batch), an unevenly-divisible local shard is an
        error here — the data never exists in one place to replicate —
        so pad to the bucket (io.LengthBucketBatchSampler) instead."""
        def spec_of(i):
            # PartitionSpec subclasses tuple: a bare P("data") must apply
            # whole to every batch element, not be indexed into per-element
            # axis names (which _filter_spec would then iterate char-wise)
            if isinstance(self.batch_spec, P):
                return self.batch_spec
            return (self.batch_spec[i]
                    if isinstance(self.batch_spec, (list, tuple))
                    else self.batch_spec)

        if self._spmd and jax.process_count() > 1:
            out = []
            for i, b in enumerate(batch):
                arr = np.asarray(b.value if isinstance(b, Tensor) else b)
                spec = _filter_spec(spec_of(i), self.mesh)
                try:
                    out.append(jax.make_array_from_process_local_data(
                        _sharding_of(self.mesh, spec), arr))
                except ValueError as e:
                    raise ValueError(
                        f"per-process batch shard of shape {arr.shape} "
                        f"does not assemble evenly under spec {spec} on "
                        f"mesh {dict(self.mesh.shape)}; pad the local "
                        f"shard to an even split (see io bucketing "
                        f"helpers)") from e
            return tuple(out)
        batch_vals = tuple(
            b.value if isinstance(b, Tensor) else jnp.asarray(b)
            for b in batch)
        if self._spmd:
            batch_vals = tuple(
                jax.device_put(b, self._batch_sharding(b, spec_of(i)))
                for i, b in enumerate(batch_vals))
        return batch_vals

    def _batch_sharding(self, arr, spec):
        """NamedSharding for one batch array: drop mesh axes the array's dims
        can't be evenly split over (tiny eval batches on a big global mesh)."""
        spec = _filter_spec(spec, self.mesh)
        dims = []
        for i, ax in enumerate(spec):
            if ax is None:
                dims.append(None)
                continue
            axes = (ax,) if isinstance(ax, str) else tuple(ax)
            size = int(np.prod([self.mesh.shape[a] for a in axes]))
            dims.append(ax if i < arr.ndim and arr.shape[i] % size == 0 else None)
        return _sharding_of(self.mesh, P(*dims))

    def build_train_step(self):
        mesh = self.mesh
        opt = self.optimizer

        def step_fn(params, opt_state, step_count, lr, batch):
            train = {n: v for n, v in params.items() if n in self._trainable}
            frozen = {n: v for n, v in params.items() if n not in self._trainable}

            def loss_of(tr, mb, frozen_vals):
                # aux = buffers the forward reassigned (BN running stats):
                # captured from the eager side effect and carried as a jit
                # output so the compiled path matches eager BN semantics
                mutated = {}
                loss = self._loss_from_batch({**tr, **frozen_vals}, mb,
                                             state_out=mutated)
                new_bufs = {n: self._raw(v) for n, v in mutated.items()
                            if n not in self._trainable}
                return loss, new_bufs

            if self.remat:
                # keep MXU outputs, recompute elementwise (the reference's
                # recompute granularity is whole-layer; saving dot outputs is
                # the better HBM/FLOP tradeoff on TPU). Named policies rely
                # on the checkpoint_name annotations in models/llama.py
                # ("attn_out", "qkv", "mlp_out").
                cp = jax.checkpoint_policies
                policy = None
                if self.remat_policy == "dots":
                    policy = cp.dots_with_no_batch_dims_saveable
                elif self.remat_policy == "nothing":
                    policy = cp.nothing_saveable
                elif self.remat_policy == "save_attn":
                    policy = cp.save_only_these_names("attn_out")
                elif self.remat_policy == "save_attn_mlp":
                    policy = cp.save_only_these_names("attn_out", "mlp_out")
                elif self.remat_policy == "save_qkv_attn":
                    policy = cp.save_only_these_names("attn_out", "qkv")
                elif self.remat_policy == "offload_attn":
                    # activations ride host RAM instead of being recomputed
                    policy = cp.save_and_offload_only_these_names(
                        names_which_can_be_saved=[],
                        names_which_can_be_offloaded=["attn_out", "mlp_out"],
                        offload_src="device", offload_dst="pinned_host")
                elif self.remat_policy is not None and \
                        self.remat_policy != "none":
                    raise ValueError(
                        f"unknown remat_policy {self.remat_policy!r}")
                loss_of_ = jax.checkpoint(loss_of, policy=policy)
            else:
                loss_of_ = loss_of
            accum = self.grad_accum
            if accum > 1:
                # gradient accumulation (ref gradient_merge_optimizer.py /
                # group_sharded k-microbatch amortization): scan over k
                # microbatches, sum grads, one optimizer update — divides
                # the per-step optimizer/PCIe cost by k on the offload path
                mbs = jax.tree.map(
                    lambda b: b.reshape((accum, b.shape[0] // accum)
                                        + b.shape[1:]), batch)
                acc_dtype = jnp.dtype(
                    os.environ.get("PT_ACCUM_DTYPE", "float32"))

                def body(carry, mb_i):
                    acc_l, acc_g, frozen_cur = carry
                    # buffers (BN running stats) thread microbatch →
                    # microbatch, matching eager sequential semantics (ref
                    # gradient_merge: each micro-step runs a full forward)
                    (l, bufs), g = jax.value_and_grad(
                        loss_of_, has_aux=True)(train, mb_i, frozen_cur)
                    acc_g = jax.tree.map(
                        lambda a, gi: a + gi.astype(a.dtype), acc_g, g)
                    return ((acc_l + l.astype(jnp.float32), acc_g,
                             {**frozen_cur, **bufs}), None)

                zero_g = {n: jnp.zeros(v.shape, acc_dtype)
                          for n, v in train.items()}
                (loss_sum, grads, frozen_out), _ = jax.lax.scan(
                    body, (jnp.zeros((), jnp.float32), zero_g, frozen),
                    mbs)
                loss = loss_sum / accum
                grads = jax.tree.map(lambda g: g / accum, grads)
                # every frozen entry rides the carry (mutated-or-not is
                # only known under the trace); unchanged ones are
                # pass-through values XLA elides
                new_bufs = frozen_out
            else:
                (loss, new_bufs), grads = jax.value_and_grad(
                    loss_of_, has_aux=True)(train, batch, frozen)
            if self._offload_opt and opt_state:
                new_train, new_state = self._offloaded_update(
                    opt, train, grads, opt_state, lr, step_count + 1, loss)
            else:
                new_train, new_state = opt.pure_update(train, grads,
                                                       opt_state, lr,
                                                       step_count + 1)
            if self._spmd:
                # keep shardings stable across steps
                new_train = {
                    n: jax.lax.with_sharding_constraint(
                        v, _sharding_of(mesh, self.specs.get(n, P())))
                    for n, v in new_train.items()
                }
            frozen = {**frozen, **new_bufs}
            return {**new_train, **frozen}, new_state, step_count + 1, loss

        donate = (0, 1, 2) if self._donate else ()
        jit_kw = {}
        if self._offload_opt and self.opt_state and not hasattr(
                self.optimizer, "_apply_one") and not hasattr(
                self.optimizer, "_apply_adamw"):
            raise NotImplementedError(
                "offload_opt_state needs a per-param update rule "
                "(_apply_one/_apply_adamw)")
        if self._offload_opt and self.opt_state:
            # pin the NEW opt state back to host memory; everything else
            # (None = unspecified) stays wherever XLA puts it
            host = self._host_sharding()
            jit_kw["out_shardings"] = (
                None, jax.tree.map(lambda _: host, self.opt_state), None,
                None)
        self._train_step = jax.jit(step_fn, donate_argnums=donate, **jit_kw)
        return self._train_step

    def _offloaded_update(self, opt, train, grads, opt_state, lr, step,
                          loss):
        """Per-param optimizer update with host-resident moments, streamed
        through a WINDOWED transfer chain.

        A naive whole-tree h2d materializes every moment tensor in HBM at
        once (measured RESOURCE_EXHAUSTED at 2.4B on v5e — XLA hoists the
        transfers), defeating the offload. Here each param's moments are
        transferred, updated and sent back inside a data-dependency chain
        built from optimization_barriers:

        - h2d_i is gated on h2d_{i-1} (PCIe h2d traffic serializes) AND on
          update_{i-W} (at most W ≈ PT_OFFLOAD_WINDOW moment sets live in
          HBM). W=1 is the round-4 strict chain; W>=2 double-buffers:
          param i+1's moments stream in while param i updates and its new
          state streams OUT (h2d/d2h ride opposite PCIe directions).
        - params walk in REVERSE name order (PT_OFFLOAD_ORDER=backward,
          default): backward produces grads for the LAST layers first, so
          updates and transfers start while earlier layers' backward still
          computes instead of stalling on the first param's grad.

        Host offload still trades step time for fit (ref
        group_sharded_stage3.py:60 cpu-offload, whose point is that the
        tradeoff is tunable) — the window + order make the PCIe pipe the
        only cost, not the scheduling.
        """
        from jax.sharding import SingleDeviceSharding

        from ..optimizer.optimizer import _pure_grad_clip

        dev_s = SingleDeviceSharding(jax.devices()[0], memory_kind="device")
        host = self._host_sharding()
        apply_adamw = getattr(opt, "_apply_adamw", None)
        # same pre-update semantics as pure_update: clip, decay masking,
        # L2-as-grad for non-decoupled optimizers
        if opt._grad_clip is not None:
            grads = _pure_grad_clip(opt._grad_clip, grads)
        window = max(1, int(os.environ.get("PT_OFFLOAD_WINDOW", "2")))
        order = os.environ.get("PT_OFFLOAD_ORDER", "backward")
        if order not in ("backward", "forward"):
            raise ValueError(
                f"PT_OFFLOAD_ORDER must be 'backward' or 'forward', got "
                f"{order!r}")
        names = sorted(train)
        if order == "backward":
            names = list(reversed(names))

        def scalar_token(v):
            return jax.lax.convert_element_type(
                v.ravel()[0], jnp.float32) * 0.0

        new_train, new_state = {}, {}
        h2d_token = loss * 0.0
        update_tokens = []
        i = -1  # running index into the live (grad-bearing) params
        for n in names:
            g = grads.get(n)
            if g is None:
                new_train[n] = train[n]
                new_state[n] = opt_state.get(n, {})
                continue
            g = g.astype(jnp.float32)
            i += 1
            gate = h2d_token
            if i >= window:
                gate = gate + update_tokens[i - window]
            slots = {
                k: jax.device_put(
                    jax.lax.optimization_barrier((v, gate))[0], dev_s)
                for k, v in opt_state[n].items()}
            h2d_token = scalar_token(next(iter(slots.values())))
            if apply_adamw is not None:
                decay = opt._wd_coeff
                if opt._apply_decay_param_fun is not None and \
                        not opt._apply_decay_param_fun(n):
                    decay = 0.0
                np_, ns = apply_adamw(train[n], g, lr, step, decay, slots)
            else:
                if opt._use_l2_decay() and opt._l2_coeff:
                    g = g + opt._reg_grad(train[n].astype(jnp.float32))
                np_, ns = opt._apply_one(train[n], g, lr, step, slots)
            update_tokens.append(scalar_token(next(iter(ns.values()))))
            new_train[n] = np_
            new_state[n] = {k: jax.device_put(v, host)
                            for k, v in ns.items()}
        return new_train, new_state

    def train_batch(self, *batch):
        """Run one compiled, sharded train step; returns host loss.

        With ``telemetry`` attached, phase timestamps (host→device
        assemble, compiled dispatch, device wait) are recorded AROUND the
        compiled call — never inside it (graftlint GL010) — and the step
        blocks on the loss so ``device_wait`` measures real device time.
        """
        tel = self.telemetry
        if self._train_step is None:
            self.build_train_step()
        lr = self.optimizer.get_lr()
        t0 = tel.clock() if tel is not None else 0.0
        batch_vals = self._assemble_batch(batch)
        t_h2d = tel.clock() if tel is not None else 0.0
        if self.grad_accum > 1:
            for b in batch_vals:
                if b.shape[0] % self.grad_accum:
                    raise ValueError(
                        f"grad_accum={self.grad_accum} needs the leading "
                        f"batch dim to divide evenly, got {b.shape}")
        spec = self.injector.fire("train_step")
        if spec is not None:
            # fire BEFORE the compiled dispatch: params/opt_state have not
            # been donated yet, so the caller may retry this step verbatim
            if spec.kind == "fatal":
                raise RuntimeError("injected fatal train-step fault")
            raise StepFault(
                f"injected train-step fault at step "
                f"{int(np.asarray(self._step_count))}")
        if tel is not None:
            from ..analysis.recompile_guard import compile_count

            c0 = compile_count()
        self.params, self.opt_state, self._step_count, loss = self._train_step(
            self.params, self.opt_state, self._step_count, lr, batch_vals)
        if tel is not None:
            t_dispatch = tel.clock()
            jax.block_until_ready(loss)
            t_wait = tel.clock()
            if not tel.model_params:
                tel.model_params = sum(
                    int(np.prod(v.shape)) for n, v in self.params.items()
                    if n in self._trainable)
            first = batch_vals[0] if batch_vals else None
            tokens = 0 if first is None else \
                int(np.prod(first.shape[:2])) if first.ndim >= 2 \
                else int(first.shape[0])
            prog = "train:" + ";".join(
                "x".join(map(str, b.shape)) for b in batch_vals)
            tel.record_step(
                step=int(np.asarray(self._step_count)) - 1, prog=prog,
                tokens=tokens, t0=t0, t_h2d=t_h2d, t_dispatch=t_dispatch,
                t_wait=t_wait, compiles=compile_count() - c0)
        from ..framework.monitor import monitor_add

        monitor_add("engine_train_steps")
        from ..distributed.fleet.elastic import pulse_heartbeat

        pulse_heartbeat()  # progress-based hang detection (--elastic_timeout)
        if isinstance(self.optimizer._learning_rate, object) and hasattr(
                self.optimizer._learning_rate, "step"):
            try:
                self.optimizer._learning_rate.step()
            except TypeError:
                pass
        return Tensor(loss)

    def eval_batch(self, *batch):
        if self._eval_step is None:
            def ev(params, batch):
                return self._loss_from_batch(params, batch)

            self._eval_step = jax.jit(ev)
        return Tensor(self._eval_step(self.params,
                                      self._assemble_batch(batch)))

    # ------------------------------------------------------------------- sync
    def engine_state_dict(self):
        """Host snapshot of the FULL engine training state (params +
        optimizer moments + step counter) for checkpoint/resume across
        elastic restarts (ref auto_checkpoint.py exactly-once resume; the
        reference snapshots executor scope vars, here the donated jit
        state). Values come back as numpy; sharded arrays are gathered —
        multi-process callers need replicated or addressable state (DP/
        ZeRO-replicated layouts qualify; every rank then writes an
        identical snapshot, so rank-local files are interchangeable)."""
        return {
            "params": jax.tree.map(np.asarray, dict(self.params)),
            "opt_state": jax.tree.map(np.asarray, self.opt_state),
            "step": int(np.asarray(self._step_count)),
        }

    def set_engine_state(self, state):
        """Inverse of engine_state_dict: re-place host values against this
        engine's shardings (works across process/mesh layouts as long as
        shapes match — the reshard is the placement)."""
        mesh = self.mesh
        if self._spmd:
            self.params = {
                n: self._place(v, _sharding_of(mesh, self.specs.get(n, P())))
                for n, v in state["params"].items()}
            self.opt_state = {
                n: {k: self._place(v, _sharding_of(mesh, self.specs.get(n, P())))
                    for k, v in slots.items()}
                for n, slots in state["opt_state"].items()}
        else:
            self.params = {n: jnp.asarray(v)
                           for n, v in state["params"].items()}
            if self._offload_opt and self.opt_state:
                host = self._host_sharding()
                self.opt_state = jax.tree.map(
                    lambda v: jax.device_put(v, host), state["opt_state"])
            else:
                self.opt_state = jax.tree.map(jnp.asarray,
                                              state["opt_state"])
        step = state.get("step", 0)
        self._step_count = (np.asarray(step, np.int32)
                            if jax.process_count() > 1
                            else jnp.asarray(step, jnp.int32))

    def sync_to_model(self):
        store = {**dict(self.model.named_parameters()),
                 **dict(self.model.named_buffers())}
        for name, v in self.params.items():
            if name in store:
                store[name]._value = v

    def state_dict(self):
        self.sync_to_model()
        return self.model.state_dict()


def parallelize(model, optimizer=None, loss_fn=None, mesh=None, **kwargs) -> ParallelEngine:
    return ParallelEngine(model, optimizer=optimizer, loss_fn=loss_fn, mesh=mesh, **kwargs)


def make_train_step(model, loss_fn, optimizer, mesh=None, **kwargs):
    eng = ParallelEngine(model, optimizer=optimizer, loss_fn=loss_fn, mesh=mesh, **kwargs)
    eng.build_train_step()
    return eng
