"""Sharding annotation primitives.

``shard_constraint`` is the workhorse: inside a pjit-traced program with an
active mesh it applies jax.lax.with_sharding_constraint (the analogue of the
reference's reshard-op insertion, reshard.py); outside it is the identity, so
the same layer code runs eagerly on one chip and partitioned on a pod.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op


class _MeshState(threading.local):
    def __init__(self):
        self.mesh: Optional[Mesh] = None
        self.in_spmd = False


_state = _MeshState()


def current_mesh() -> Optional[Mesh]:
    if _state.mesh is not None:
        return _state.mesh
    from ..distributed.collective import get_global_mesh

    return get_global_mesh()


@contextlib.contextmanager
def mesh_context(mesh: Mesh, in_spmd: bool = True):
    prev, prev_flag = _state.mesh, _state.in_spmd
    _state.mesh = mesh
    _state.in_spmd = in_spmd
    try:
        yield mesh
    finally:
        _state.mesh, _state.in_spmd = prev, prev_flag


def in_spmd_region() -> bool:
    return _state.in_spmd


def _filter_spec(spec: P, mesh: Mesh) -> P:
    """Drop axis names the mesh doesn't have / size-1 axes."""
    parts = []
    for p in spec:
        if p is None:
            parts.append(None)
        elif isinstance(p, (list, tuple)):
            kept = [a for a in p if a in mesh.shape and mesh.shape[a] > 1]
            parts.append(tuple(kept) if kept else None)
        else:
            parts.append(p if (p in mesh.shape and mesh.shape[p] > 1) else None)
    return P(*parts)


def auto_shard_spec(shape, axis_size: int, axis: str = "sharding",
                    min_size: int = 1024, allow_uneven: bool = False) -> P:
    """Canonical ZeRO layout policy (ref group_sharded_stage3.py:60 even
    param split): lay the largest axis-size-divisible dim over ``axis``;
    tiny arrays stay replicated. Shared by ParallelEngine (fsdp) and
    distributed.sharding so eager and compiled ZeRO agree.

    ``allow_uneven``: jit in/out shardings tolerate ragged splits (XLA pads),
    so callers that only feed specs to jit may pass True; eager
    ``jax.device_put`` rejects them, hence the safe default False."""
    shape = tuple(shape)
    size = 1
    for s in shape:
        size *= s
    if axis_size <= 1 or not shape or size < min_size:
        return P()
    for i in sorted(range(len(shape)), key=lambda i: -shape[i]):
        if shape[i] % axis_size == 0 and shape[i] >= axis_size:
            parts = [None] * len(shape)
            parts[i] = axis
            return P(*parts)
    # no evenly-divisible dim: still shard the largest (GSPMD pads the ragged
    # tail) — replicating e.g. a [50257] vocab row would be a memory regression
    if allow_uneven:
        best = max(range(len(shape)), key=lambda i: shape[i])
        if shape[best] >= axis_size:
            parts = [None] * len(shape)
            parts[best] = axis
            return P(*parts)
    return P()


def shard_constraint(x, spec: P):
    """Annotate intermediate sharding; identity outside SPMD tracing."""
    mesh = current_mesh()
    if mesh is None or not _state.in_spmd:
        return x
    spec = _filter_spec(spec, mesh)
    # trim spec to rank
    nd = x.ndim if hasattr(x, "ndim") else len(x.shape)
    parts = list(spec)[:nd]
    spec = P(*parts)
    sharding = NamedSharding(mesh, spec)
    if isinstance(x, Tensor):
        return apply_op(lambda v: jax.lax.with_sharding_constraint(v, sharding), x)
    return jax.lax.with_sharding_constraint(x, sharding)


def shard_tensor(x, mesh: Optional[Mesh] = None, spec: P = P(), process_mesh=None,
                 shard_spec=None):
    """paddle.distributed.shard_tensor parity (ref auto_parallel/interface.py:28):
    eagerly places the array with a NamedSharding."""
    mesh = mesh or current_mesh()
    if shard_spec is not None:
        spec = P(*[s if s else None for s in shard_spec])
    if mesh is None:
        return x if isinstance(x, Tensor) else Tensor(x)
    val = to_array(x)
    spec = _filter_spec(spec, mesh)
    out = jax.device_put(val, NamedSharding(mesh, spec))
    if isinstance(x, Tensor):
        x._value = out
        return x
    return Tensor(out)


def axis_size(name: str) -> int:
    mesh = current_mesh()
    if mesh is None or name not in mesh.shape:
        return 1
    return int(mesh.shape[name])


def axis_index(name: str):
    """Inside shard_map: this shard's index on the axis; 0 otherwise."""
    try:
        return jax.lax.axis_index(name)
    except NameError:
        return jnp.zeros((), jnp.int32)


def psum(x, axis_name: str):
    """psum that is identity when the axis isn't bound (eager path)."""
    try:
        return jax.lax.psum(x, axis_name)
    except NameError:
        return x


def all_gather_axis(x, axis_name: str, tiled=True):
    try:
        return jax.lax.all_gather(x, axis_name, tiled=tiled)
    except NameError:
        return x
