"""TPU parallel engine: mesh context, sharding annotations, and the sharded
train-step builder. This is the GSPMD-native replacement for the reference's
auto_parallel Engine/Partitioner/Resharder (ref
python/paddle/distributed/auto_parallel/engine.py:58, partitioner.py,
reshard.py) — propagation/partition/reshard all happen inside XLA.
"""
from .api import (current_mesh, mesh_context, shard_constraint, shard_tensor, psum,
                  all_gather_axis, axis_index, axis_size)
from .engine import ParallelEngine, parallelize, make_train_step
from .pipeline_engine import (PipelineEngine, gpt_pipeline_engine,
                              llama_pipeline_engine)
from .serving_mesh import build_serving_mesh, mesh_fingerprint

__all__ = ["current_mesh", "mesh_context", "shard_constraint", "shard_tensor", "psum",
           "all_gather_axis", "axis_index", "axis_size", "ParallelEngine", "parallelize",
           "make_train_step", "PipelineEngine", "llama_pipeline_engine", "gpt_pipeline_engine",
           "build_serving_mesh", "mesh_fingerprint"]
