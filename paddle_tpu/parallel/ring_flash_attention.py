"""Ring flash attention — Pallas blockwise kernels around the context ring.

NEW DESIGN (reference has no context parallelism, SURVEY §5.7). The plain
`ring_attention` (ring_attention.py) materializes an (S/n)² score block per
hop in jnp; this variant runs the Pallas flash kernels per resident block, so
per-hop memory is O(S·D) and a single chip's shard can itself be long.

Forward: per hop, run the flash forward on (q_local, k_block, v_block) to get
(out_b, lse_b); combine blocks with the logsumexp merge
    m' = max(m, lse_b);  l' = l·e^{m-m'} + e^{lse_b-m'};
    acc' = acc·e^{m-m'} + out_b·e^{lse_b-m'}
and rotate K/V with lax.ppermute. Block causality classes (full / diagonal /
masked-out) are picked by lax.switch; the masked class contributes
lse_b = -inf, i.e. zero weight, so the merge is uniform.

Backward is a second ring pass (custom_vjp — no scan transposition): with the
global LSE and delta = rowsum(dO·O), each hop calls the flash backward
kernels per block; dQ accumulates locally while the (dK, dV) partials rotate
WITH their K/V block, so after n hops every block's gradient arrives back at
its home rank. This is the standard ring-attention gradient schedule on ICI.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..ops.flash_attention import (NEG_INF, _flash_bwd_bhsd, _flash_fwd_bhsd,
                                   _ref_bhsd)

__all__ = ["ring_flash_attention", "ring_flash_attention_bshd"]


def _block_fwd(q, kb, vb, scale, block_kind):
    """(out_b, lse_b) for one resident block. block_kind: 0 full, 1 diagonal
    (causal), 2 masked-out."""

    def full(_):
        return _flash_fwd_bhsd(q, kb, vb, False, scale)

    def diag(_):
        return _flash_fwd_bhsd(q, kb, vb, True, scale)

    def skip(_):
        # derive from q so outputs carry the same varying-mesh-axes type
        return (q * 0, (q[..., 0] * 0).astype(jnp.float32) + NEG_INF)

    return jax.lax.switch(block_kind, (full, diag, skip), None)


def _block_bwd(q, kb, vb, do, lse, delta, scale, block_kind):
    """(dq_b, dk_b, dv_b) for one resident block given the GLOBAL lse/delta.
    The flash backward formulas hold per block when lse is global: p_ij =
    exp(s_ij - LSE_i) is each key's true softmax weight."""

    def full(_):
        return _flash_bwd_bhsd(q, kb, vb, do, lse, delta, False, scale)

    def diag(_):
        return _flash_bwd_bhsd(q, kb, vb, do, lse, delta, True, scale)

    def skip(_):
        return (q * 0, kb * 0, vb * 0)  # keeps the inputs' vma type

    return jax.lax.switch(block_kind, (full, diag, skip), None)


def _block_kind(src, my_idx, causal):
    if not causal:
        return jnp.zeros((), jnp.int32)
    return jnp.where(src < my_idx, 0, jnp.where(src == my_idx, 1, 2)
                     ).astype(jnp.int32)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention(q, k, v, axis_name: str = "context",
                         causal: bool = True,
                         scale: Optional[float] = None):
    """Per-shard ring attention body (call inside shard_map); Pallas flash
    per block. q,k,v local shards (B, H, S_local, D) with the sequence dim
    sharded over `axis_name`."""
    out, _ = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out


def _ring_fwd_impl(q, k, v, axis_name, causal, scale):
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, H, S, D = q.shape
    sc = scale if scale is not None else 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]

    m0 = jnp.full((B, H, S), NEG_INF, jnp.float32) + (q[..., 0] * 0.0)
    l0 = jnp.zeros((B, H, S), jnp.float32) + (q[..., 0] * 0.0)
    acc0 = jnp.zeros((B, H, S, D), jnp.float32) + (q * 0.0)

    def step(carry, t):
        m, l, acc, kb, vb = carry
        src = (my_idx - t) % n
        out_b, lse_b = _block_fwd(q, kb, vb, sc, _block_kind(src, my_idx, causal))
        m_new = jnp.maximum(m, lse_b)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(lse_b - m_new)
        l_new = l * alpha + beta
        acc_new = acc * alpha[..., None] + out_b.astype(jnp.float32) * beta[..., None]
        kb_next = jax.lax.ppermute(kb, axis_name, perm)
        vb_next = jax.lax.ppermute(vb, axis_name, perm)
        return (m_new, l_new, acc_new, kb_next, vb_next), None

    (m, l, acc, _, _), _ = jax.lax.scan(step, (m0, l0, acc0, k, v),
                                        jnp.arange(n))
    l_safe = jnp.maximum(l, 1e-30)
    out = (acc / l_safe[..., None]).astype(q.dtype)
    lse_global = m + jnp.log(l_safe)
    return out, lse_global


def _ring_fa_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_fa_bwd(axis_name, causal, scale, res, g):
    q, k, v, out, lse = res
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    D = q.shape[-1]
    sc = scale if scale is not None else 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]

    do = g
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1)

    def step(carry, t):
        dq_acc, kb, vb, dkb, dvb = carry
        src = (my_idx - t) % n
        dq_b, dk_b, dv_b = _block_bwd(
            q, kb, vb, do, lse, delta, sc, _block_kind(src, my_idx, causal))
        dq_acc = dq_acc + dq_b.astype(jnp.float32)
        dkb = dkb + dk_b.astype(jnp.float32)
        dvb = dvb + dv_b.astype(jnp.float32)
        # the (k, v, dk, dv) bundle travels the ring together; after the last
        # hop's rotation every block is home with its full gradient
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        dkb = jax.lax.ppermute(dkb, axis_name, perm)
        dvb = jax.lax.ppermute(dvb, axis_name, perm)
        return (dq_acc, kb, vb, dkb, dvb), None

    dq0 = jnp.zeros_like(q, dtype=jnp.float32)
    dk0 = jnp.zeros_like(k, dtype=jnp.float32)
    dv0 = jnp.zeros_like(v, dtype=jnp.float32)
    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


ring_flash_attention.defvjp(_ring_fa_fwd, _ring_fa_bwd)


def ring_flash_attention_bshd(q, k, v, axis_name: str = "context",
                              causal: bool = True,
                              scale: Optional[float] = None):
    """(B, S, H, D) layout wrapper."""
    out = ring_flash_attention(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                               jnp.swapaxes(v, 1, 2), axis_name, causal, scale)
    return jnp.swapaxes(out, 1, 2)
