"""Compiled pipeline-parallel TRAINING over the ``pipe`` mesh axis.

Ref: python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:117
(``forward_backward_pipeline`` — the 1F1B fwd+bwd schedule) and
pp_utils/p2p_communication.py:298 (stage-to-stage p2p).  The reference drives
the schedule from the host with NCCL send/recv per microbatch; stage-sharded
parameters live in separate processes.

TPU-native design — one compiled SPMD program:

- The homogeneous decoder-block stack is stacked into leaves of shape
  ``[num_stages, layers_per_stage, ...]`` sharded ``P("pipe")``: each device
  along the pipe axis holds exactly its stages' weights (stage-sharded
  params, the PP memory model).
- The microbatch schedule is the GPipe fill/drain loop ``spmd_pipeline_fn``
  (lax.scan over ticks, lax.ppermute rotating activations stage→stage+1)
  run under a *partial-manual* ``jax.shard_map``: only ``pipe`` is manual,
  so data/tensor/sharding axes keep their GSPMD shardings inside the loop
  (TP matmuls, DP batch splits compose transparently).
- The backward pipeline is ``jax.grad`` through that scan: scan's VJP
  replays ticks in reverse with the transposed ppermute — activation grads
  ppermute **backward** stage→stage-1, exactly the reference's
  ``send_backward_recv_forward`` dataflow — and per-stage grad accumulation
  falls out as the scan-carry accumulation of each stage's param grads.
  ``jax.checkpoint`` on the stage body gives the 1F1B-like memory bound
  (store only per-tick boundary activations, recompute block internals).
- Embedding / final-norm / lm-head live OUTSIDE the manual region,
  replicated over ``pipe`` and sharded over ``tensor`` by GSPMD.  Tied
  embeddings therefore need no special grad allreduce: the tied weight is a
  single array used at both ends, so its grad is the sum of both uses —
  the semantics of ref ``allreduce_shared_weight_gradients``
  (pipeline_parallel.py:117 steady-state) by construction.

- ``schedule="1f1b"`` replaces the grad-through-scan backward with the true
  1F1B tick order (``spmd_1f1b_train_fn``): head+loss move INSIDE the pipe
  region (run at the last stage), the backward is hand-driven with
  per-stage ``jax.vjp`` in the same scan, and live residuals are bounded by
  a ring of ``min(2S-1, num_micro)`` boundary activations — the reference
  1F1B memory property (pipeline_parallel.py:117), which the GPipe order
  cannot provide (its autodiff stores one residual per tick, O(num_micro)).

The optimizer update runs on the stage-local shards (opt state is sharded
``P("pipe")`` like its param), i.e. ZeRO-over-pipe for the block stack.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from ..framework.core import Tensor
from ..jit import functional_call, state_values
from .api import _filter_spec, mesh_context
from .engine import param_specs, _sharding_of


class PipelineEngine:
    """Train step = embed → pipelined block stack (pipe-manual shard_map) →
    head+loss, differentiated end-to-end, AdamW on stage-local shards.

    Generic over the model via three pure functions:
      pre_fn(params, *inputs)        -> activations  [B, ...]
      block_fn(block_params, acts)   -> acts          (ONE decoder block)
      post_fn(params, acts, *labels) -> scalar loss
    where ``params`` is the flat name→array dict of all NON-stacked params
    and ``block_params`` the name→array dict of one block (template-relative
    names).  Use :func:`llama_pipeline_engine` for the stock Llama wiring.
    """

    def __init__(self, model, layers, layers_prefix: str,
                 pre_fn: Callable, block_fn: Callable, post_fn: Callable,
                 optimizer=None, mesh: Optional[Mesh] = None,
                 num_micro: int = 2, remat: bool = True,
                 abstract: bool = False, fsdp: bool = False,
                 fsdp_axis: str = "sharding", num_chunks: int = 1,
                 schedule: str = "gpipe"):
        from ..distributed.collective import get_global_mesh

        assert optimizer is not None, \
            "PipelineEngine is a training engine: pass an optimizer " \
            "(for inference use the plain model / ParallelEngine.eval_batch)"
        self.model = model
        self.optimizer = optimizer
        self.mesh = mesh or get_global_mesh()
        assert self.mesh is not None and "pipe" in self.mesh.axis_names, \
            "PipelineEngine needs a mesh with a 'pipe' axis"
        self.num_stages = int(self.mesh.shape["pipe"])
        self.num_micro = num_micro
        self.num_chunks = num_chunks  # >1: interleaved virtual stages
        assert schedule in ("gpipe", "1f1b"), schedule
        self.schedule = schedule
        self.remat = remat
        self._abstract = abstract
        self._layers_prefix = layers_prefix
        self._pre_fn, self._block_fn, self._post_fn = pre_fn, block_fn, post_fn

        L = len(layers)
        S, C = self.num_stages, self.num_chunks
        assert L % (S * C) == 0, \
            f"{L} layers not divisible by {S} stages x {C} chunks"
        self.layers_per_stage = L // (S * C)  # per logical stage

        self.fsdp, self.fsdp_axis = fsdp, fsdp_axis

        # ---- split params: stacked block stack vs everything else
        all_vals = state_values(model)
        base_specs = param_specs(model, self.mesh, fsdp=fsdp,
                                 fsdp_axis=fsdp_axis)
        sub_names = [n for n, _ in layers[0].named_parameters()]
        trainable = {n for n, p in model.named_parameters() if p.trainable}

        self.stacked_specs: Dict[str, P] = {}
        stacked = {}
        lps = self.layers_per_stage
        for sub in sub_names:
            arrs = [all_vals[f"{layers_prefix}.{i}.{sub}"] for i in range(L)]
            w = tuple(arrs[0].shape)
            if C > 1:
                # interleaved: logical stage s = chunk*S + device owns layers
                # [s*lps, (s+1)*lps) -> element [dev, chunk, j] = layer
                # (chunk*S + dev)*lps + j
                shape = (S, C, lps) + w
                lead = P("pipe", None, None)
            else:
                shape = (S, lps) + w
                lead = P("pipe", None)
            base = tuple(base_specs.get(f"{layers_prefix}.0.{sub}", P()))
            self.stacked_specs[sub] = self._with_fsdp(
                _filter_spec(P(*lead, *base), self.mesh), shape)
            if abstract:
                stacked[sub] = (shape, arrs[0].dtype)  # no materialization
            else:
                # stack on HOST, then device_put with the final sharding —
                # never materializes an unsharded device copy of the stack
                st = np.stack([np.asarray(a) for a in arrs])
                if C > 1:
                    st = np.swapaxes(st.reshape((C, S, lps) + w), 0, 1)
                else:
                    st = st.reshape(shape)
                stacked[sub] = np.ascontiguousarray(st)
        self.rest_specs = {
            n: base_specs.get(n, P()) for n in all_vals
            if not n.startswith(layers_prefix + ".")
        }
        rest = {n: all_vals[n] for n in self.rest_specs}
        self._rest_trainable = {n for n in rest if n in trainable}
        # every block param of the (uniform) stack is trainable iff layer-0's is
        self._stacked_trainable = {
            sub for sub in sub_names
            if f"{layers_prefix}.0.{sub}" in trainable}

        if abstract:
            self.stacked = {
                k: jax.ShapeDtypeStruct(shape, dtype,
                                        sharding=_sharding_of(self.mesh,
                                                              self.stacked_specs[k]))
                for k, (shape, dtype) in stacked.items()}
            self.rest = {
                n: jax.ShapeDtypeStruct(v.shape, v.dtype,
                                        sharding=_sharding_of(self.mesh,
                                                              self.rest_specs[n]))
                for n, v in rest.items()}
        else:
            self.stacked = {k: jax.device_put(v, _sharding_of(self.mesh,
                                                              self.stacked_specs[k]))
                            for k, v in stacked.items()}
            self.rest = {n: jax.device_put(v, _sharding_of(self.mesh,
                                                           self.rest_specs[n]))
                        for n, v in rest.items()}

        self._init_opt_state()
        self._train_step = None
        self._step_count = jnp.zeros((), jnp.int32)

    # ------------------------------------------------------------------ state
    def _with_fsdp(self, spec, shape) -> P:
        """ZeRO over ``fsdp_axis`` for the stacked block params: shard the
        first still-unsharded, evenly-divisible weight dim (params AND opt
        state share the spec — ref group_sharded_stage3.py:60 semantics,
        expressed as a GSPMD layout)."""
        if not self.fsdp or self.fsdp_axis not in self.mesh.axis_names:
            return spec
        size = int(self.mesh.shape[self.fsdp_axis])
        if size <= 1:
            return spec
        entries = list(tuple(spec))
        entries += [None] * (len(shape) - len(entries))
        if self.fsdp_axis in entries:  # base spec already consumed the axis
            return P(*entries)
        lead = 3 if self.num_chunks > 1 else 2  # (pipe[, chunk], layer) dims
        for i in range(lead, len(shape)):
            if entries[i] is None and shape[i] % size == 0:
                entries[i] = self.fsdp_axis
                break
        return P(*entries)

    def _merged_trainable(self, rest, stacked):
        m = {f"rest.{n}": rest[n] for n in self._rest_trainable}
        m.update({f"stacked.{k}": stacked[k] for k in self._stacked_trainable})
        return m

    def _spec_of(self, merged_name: str) -> P:
        kind, _, name = merged_name.partition(".")
        return (self.rest_specs if kind == "rest" else self.stacked_specs)[name]

    def _init_opt_state(self):
        if self.optimizer is None:
            self.opt_state = {}
            return
        train = self._merged_trainable(self.rest, self.stacked)
        if self._abstract:
            st = jax.eval_shape(self.optimizer.init_state, train)
            self.opt_state = {
                n: {k: jax.ShapeDtypeStruct(
                    s.shape, s.dtype,
                    sharding=_sharding_of(self.mesh, self._spec_of(n)))
                    for k, s in slots.items()}
                for n, slots in st.items()}
            return
        st = self.optimizer.init_state(train)
        # opt state shards like its param: stage-local along pipe
        self.opt_state = {
            n: {k: jax.device_put(v, _sharding_of(self.mesh, self._spec_of(n)))
                for k, v in slots.items()}
            for n, slots in st.items()}

    # ------------------------------------------------------------- train step
    def _run_blocks(self, blocks, x):
        """One logical stage: apply ``layers_per_stage`` blocks (pytree with
        leading [lps] dim), rematerializing internals when remat is on."""
        lps, block_fn = self.layers_per_stage, self._block_fn

        def body(bs, x):
            for j in range(lps):
                x = block_fn({k: v[j] for k, v in bs.items()}, x)
            return x

        if self.remat:
            return jax.checkpoint(body)(blocks, x)
        return body(blocks, x)

    def _stage_fn(self, stage_id, params_shard, x):
        """shard_map per-shard stage: strip the size-1 pipe-shard dim and run
        this device's blocks (shared by the GPipe and 1F1B schedules)."""
        return self._run_blocks({k: v[0] for k, v in params_shard.items()}, x)

    def _apply_update(self, rest, stacked, train, grads, opt_state, lr,
                      step_count):
        """Optimizer step + sharding-constraint + reassembly tail, shared by
        every schedule's step_fn."""
        new_train, new_state = self.optimizer.pure_update(
            train, grads, opt_state, lr, step_count + 1)
        new_train = {
            n: jax.lax.with_sharding_constraint(
                v, _sharding_of(self.mesh, self._spec_of(n)))
            for n, v in new_train.items()}
        new_rest = {**rest,
                    **{n: new_train[f"rest.{n}"]
                       for n in self._rest_trainable}}
        new_stacked = {**stacked,
                       **{k: new_train[f"stacked.{k}"]
                          for k in self._stacked_trainable}}
        return new_rest, new_stacked, new_state

    def _pipeline_apply(self, stacked, acts):
        """acts [B, ...] -> [B, ...] through the pipelined stack."""
        from ..distributed.fleet.meta_parallel.pipeline_parallel import (
            spmd_interleaved_pipeline_fn, spmd_pipeline_fn)

        run_blocks = self._run_blocks
        B = acts.shape[0]
        assert B % self.num_micro == 0, (B, self.num_micro)
        micro = acts.reshape((self.num_micro, B // self.num_micro) +
                             acts.shape[1:])
        if self.num_chunks > 1:
            # interleaved virtual stages (ref PipelineParallelWithInterleave
            # pipeline_parallel.py:461), differentiated end-to-end like the
            # plain schedule (lockstep bubble caveat: see
            # spmd_interleaved_pipeline_fn docstring)
            def chunk_fn(chunk_id, params_chunk, x):
                return run_blocks(params_chunk, x)

            fn = spmd_interleaved_pipeline_fn(chunk_fn, self.num_stages,
                                              self.num_micro, self.num_chunks)
        else:
            fn = spmd_pipeline_fn(self._stage_fn, self.num_stages,
                                  self.num_micro)
        out = jax.shard_map(
            fn, mesh=self.mesh, in_specs=(P("pipe"), P()), out_specs=P(),
            axis_names=frozenset({"pipe"}))(stacked, micro)
        return out.reshape(acts.shape[:1] + out.shape[2:])

    def _ensure_post_names(self, input_vals, label_vals):
        """Which rest params does post_fn actually read?  Traced once with
        abstract values; the resulting name list bounds the per-tick grad
        accumulators the 1F1B schedule carries for the head/norm params."""
        if getattr(self, "_post_names", None) is not None:
            return
        label_protos = tuple(jax.ShapeDtypeStruct(l.shape, l.dtype)
                             for l in label_vals)
        rest_proto = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                      for n, v in self.rest.items()}
        acts = jax.eval_shape(
            lambda rf, *i: self._pre_fn(rf, *i), rest_proto,
            *(jax.ShapeDtypeStruct(x.shape, x.dtype) for x in input_vals))
        M = self.num_micro
        mb_acts = jax.ShapeDtypeStruct(
            (acts.shape[0] // M,) + acts.shape[1:], acts.dtype)
        mb_labels = tuple(
            jax.ShapeDtypeStruct((l.shape[0] // M,) + l.shape[1:], l.dtype)
            for l in label_protos)

        def f(rf, y, lb):
            loss = self._post_fn(rf, y, *lb)
            return loss.value if isinstance(loss, Tensor) else loss

        jaxpr = jax.make_jaxpr(f)(rest_proto, mb_acts, mb_labels).jaxpr
        used = set()
        for eqn in jaxpr.eqns:
            for v in eqn.invars:
                try:
                    used.add(v)
                except TypeError:  # unhashable Literal
                    pass
        for v in jaxpr.outvars:
            try:
                used.add(v)
            except TypeError:
                pass
        # dict flatten order == sorted keys == jaxpr invars prefix order
        names = sorted(rest_proto)
        self._post_names = [n for n, var in zip(names, jaxpr.invars)
                            if var in used]

    def _build_train_step_1f1b(self):
        """1F1B schedule: loss at the last stage inside the pipe region,
        hand-driven backward (per-stage vjp in the same scan), O(num_stages)
        live activations — see ``spmd_1f1b_train_fn``.  Ref:
        python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py:117."""
        from ..distributed.fleet.meta_parallel.pipeline_parallel import (
            spmd_1f1b_train_fn, spmd_staggered_interleaved_1f1b)

        mesh = self.mesh
        rest_frozen_names = [n for n in self.rest
                             if n not in self._rest_trainable]
        S, M, C = self.num_stages, self.num_micro, self.num_chunks

        def post_loss(pp, y, lb):
            loss = self._post_fn(pp, y, *lb)
            v = loss.value if isinstance(loss, Tensor) else loss
            return v.astype(jnp.float32)

        if C > 1:
            def chunk_fn(chunk_id, params_chunk, x):
                return self._run_blocks(params_chunk, x)

            fn = spmd_staggered_interleaved_1f1b(chunk_fn, post_loss, S, M, C)
        else:
            fn = spmd_1f1b_train_fn(self._stage_fn, post_loss, S, M)
        post_names = self._post_names

        def step_fn(rest, stacked, opt_state, step_count, lr, inputs, labels):
            frozen = {n: rest[n] for n in rest_frozen_names}
            train = self._merged_trainable(rest, stacked)
            rest_full = {**frozen,
                         **{n: train[f"rest.{n}"] for n in self._rest_trainable}}
            stk = {k: train[f"stacked.{k}"] for k in self._stacked_trainable}
            with mesh_context(mesh):
                acts, pre_vjp = jax.vjp(
                    lambda rf: self._pre_fn(rf, *inputs), rest_full)
                B = acts.shape[0]
                assert B % M == 0, (B, M)
                micro = acts.reshape((M, B // M) + acts.shape[1:])
                micro_labels = jax.tree_util.tree_map(
                    lambda l: l.reshape((M, B // M) + l.shape[1:]), labels)
                post_params = {n: rest_full[n] for n in post_names}
                loss, g_stk, g_post, d_micro = jax.shard_map(
                    fn, mesh=mesh,
                    in_specs=(P("pipe"), P(), P(), P()),
                    out_specs=(P(), P("pipe"), P(), P()),
                    axis_names=frozenset({"pipe"}))(
                        stk, post_params, micro, micro_labels)
                (d_rest_pre,) = pre_vjp(d_micro.reshape(acts.shape))
            grads = {}
            for n in self._rest_trainable:
                g = d_rest_pre[n]
                if n in g_post:
                    g = g + g_post[n]
                grads[f"rest.{n}"] = g
            for k in self._stacked_trainable:
                grads[f"stacked.{k}"] = g_stk[k]
            new_rest, new_stacked, new_state = self._apply_update(
                rest, stacked, train, grads, opt_state, lr, step_count)
            return new_rest, new_stacked, new_state, step_count + 1, loss

        self._train_step = jax.jit(step_fn)
        return self._train_step

    def build_train_step(self):
        if self.schedule == "1f1b":
            assert getattr(self, "_post_names", None) is not None, \
                "1f1b build needs input shapes: call train_batch/" \
                "lower_train_step (they trace post_fn's param usage first)"
            return self._build_train_step_1f1b()
        mesh = self.mesh
        rest_frozen_names = [n for n in self.rest
                             if n not in self._rest_trainable]

        def step_fn(rest, stacked, opt_state, step_count, lr, inputs, labels):
            frozen = {n: rest[n] for n in rest_frozen_names}

            def loss_of(tr):
                rest_full = {**frozen,
                             **{n: tr[f"rest.{n}"] for n in self._rest_trainable}}
                stk = {k: tr[f"stacked.{k}"] for k in self._stacked_trainable}
                with mesh_context(mesh):
                    acts = self._pre_fn(rest_full, *inputs)
                    out = self._pipeline_apply(stk, acts)
                    loss = self._post_fn(rest_full, out, *labels)
                return loss.value if isinstance(loss, Tensor) else loss

            train = self._merged_trainable(rest, stacked)
            loss, grads = jax.value_and_grad(loss_of)(train)
            new_rest, new_stacked, new_state = self._apply_update(
                rest, stacked, train, grads, opt_state, lr, step_count)
            return new_rest, new_stacked, new_state, step_count + 1, loss

        self._train_step = jax.jit(step_fn, static_argnums=())
        return self._train_step

    def lower_train_step(self, inputs, labels):
        """AOT-lower (abstract mode) for partitioning validation at scale."""
        if self._train_step is None:
            if self.schedule == "1f1b":
                self._ensure_post_names(inputs, labels)
            self.build_train_step()
        return self._train_step.lower(self.rest, self.stacked, self.opt_state,
                                      self._step_count, jnp.float32(0.0),
                                      inputs, labels)

    def train_batch(self, *batch):
        """batch = (*inputs, labels); returns host loss Tensor."""
        vals = tuple(b.value if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in batch)
        inputs, labels = vals[:-1], vals[-1:]
        if self._train_step is None:
            if self.schedule == "1f1b":
                self._ensure_post_names(inputs, labels)
            self.build_train_step()
        lr = self.optimizer.get_lr()
        (self.rest, self.stacked, self.opt_state, self._step_count,
         loss) = self._train_step(self.rest, self.stacked, self.opt_state,
                                  self._step_count, lr, inputs, labels)
        from ..distributed.fleet.elastic import pulse_heartbeat

        pulse_heartbeat()
        return Tensor(loss)

    # ------------------------------------------------------------------- sync
    def unstacked_params(self) -> Dict[str, Any]:
        """Flat name→array dict in the model's original layout (for
        checkpointing / parity checks)."""
        out = dict(self.rest)
        for sub, v in self.stacked.items():
            a = np.asarray(v)
            if self.num_chunks > 1:
                a = np.swapaxes(a, 0, 1)  # [S,C,lps,...] -> [C,S,lps,...]
                flat = a.reshape((-1,) + a.shape[3:])
            else:
                flat = a.reshape((-1,) + a.shape[2:])
            for i in range(flat.shape[0]):
                out[f"{self._layers_prefix}.{i}.{sub}"] = jnp.asarray(flat[i])
        return out

    def sync_to_model(self):
        store = {**dict(self.model.named_parameters()),
                 **dict(self.model.named_buffers())}
        for name, v in self.unstacked_params().items():
            if name in store:
                store[name]._value = v


def llama_pipeline_engine(model, optimizer=None, mesh=None, num_micro: int = 2,
                          remat: bool = True, abstract: bool = False,
                          fsdp: bool = False, num_chunks: int = 1,
                          schedule: str = "gpipe") -> PipelineEngine:
    """Wire a ``LlamaForCausalLM`` into the pipeline engine: embedding before
    the pipe region, decoder blocks inside, final-norm + lm-head + CE after.
    Tied embeddings (cfg.tie_word_embeddings) share one array across both
    ends, so the tied-grad allreduce is implicit."""
    import paddle_tpu.nn.functional as F

    lm = model
    core = lm.model            # LlamaModel
    layers = list(core.layers)
    template = layers[0]
    cos, sin = core._cos, core._sin
    tied = lm.cfg.tie_word_embeddings

    def pre_fn(params, input_ids):
        emb = params["model.embed_tokens.weight"]
        return jnp.take(emb, input_ids, axis=0)

    def block_fn(blk, x):
        out = functional_call(template, blk, Tensor(x), cos, sin)
        return out.value if isinstance(out, Tensor) else out

    def post_fn(params, h, labels):
        out = functional_call(core.norm, {"weight": params["model.norm.weight"]},
                              Tensor(h))
        h = out.value if isinstance(out, Tensor) else out
        w = params["model.embed_tokens.weight"] if tied \
            else params["lm_head.weight"]
        if lm.cfg.fused_lm_head_ce:
            # chunked fused lm-head+CE: never materializes [B,S,V] logits
            # (same memory design as the non-pipelined engine path, incl.
            # the shared long-S chunk cap)
            from ..ops.fused_ce import (capped_chunk_size,
                                        fused_linear_cross_entropy)

            return fused_linear_cross_entropy(
                h, w, labels,
                chunk_size=capped_chunk_size(lm.cfg.ce_chunk_size,
                                             labels.shape[-1]),
                transpose_weight=tied)
        logits = h @ (w.T if tied else w)
        return F.cross_entropy(Tensor(logits), Tensor(labels),
                               reduction="mean")

    return PipelineEngine(lm, layers, "model.layers", pre_fn, block_fn, post_fn,
                          optimizer=optimizer, mesh=mesh, num_micro=num_micro,
                          remat=remat, abstract=abstract, fsdp=fsdp,
                          num_chunks=num_chunks, schedule=schedule)


def gpt_pipeline_engine(model, optimizer=None, mesh=None, num_micro: int = 2,
                        remat: bool = True, abstract: bool = False,
                        fsdp: bool = False, num_chunks: int = 1,
                        schedule: str = "gpipe") -> PipelineEngine:
    """Wire a ``GPTForCausalLM`` into the pipeline engine (second model
    family through the same generic pre/block/post decomposition): token+pos
    embedding before the pipe region, GPT blocks inside, final LayerNorm +
    tied-embedding head + CE after (tied wte grads sum across both uses
    automatically)."""
    import paddle_tpu.nn.functional as F

    core = model.transformer
    layers = list(core.h)
    template = layers[0]
    assert model.cfg.hidden_dropout_prob == 0.0, \
        "gpt_pipeline_engine: embedding dropout lives outside the pipe " \
        "region and is not replicated here — train with " \
        "hidden_dropout_prob=0 (the usual large-model setting)"

    def pre_fn(params, input_ids):
        wte = params["transformer.wte.weight"]
        wpe = params["transformer.wpe.weight"]
        S = input_ids.shape[1]
        return jnp.take(wte, input_ids, axis=0) + wpe[None, :S]

    def block_fn(blk, x):
        out = functional_call(template, blk, Tensor(x))
        return out.value if isinstance(out, Tensor) else out

    def post_fn(params, h, labels):
        out = functional_call(
            core.ln_f, {"weight": params["transformer.ln_f.weight"],
                        "bias": params["transformer.ln_f.bias"]}, Tensor(h))
        hn = out.value if isinstance(out, Tensor) else out
        logits = hn @ params["transformer.wte.weight"].T
        return F.cross_entropy(Tensor(logits), Tensor(labels),
                               reduction="mean")

    return PipelineEngine(model, layers, "transformer.h", pre_fn, block_fn,
                          post_fn, optimizer=optimizer, mesh=mesh,
                          num_micro=num_micro, remat=remat, abstract=abstract,
                          fsdp=fsdp, num_chunks=num_chunks, schedule=schedule)
