"""paddle_tpu: a TPU-native deep-learning framework with PaddlePaddle's
capabilities, built on JAX/XLA/Pallas/pjit.

Top-level namespace mirrors `paddle` (ref: python/paddle/__init__.py): tensor
ops are flat functions here, `nn`/`optimizer`/`distributed`/... are
subpackages. Everything executes eagerly op-by-op (dygraph parity) and traces
into a single XLA program under `paddle_tpu.jit.to_static`.
"""
from __future__ import annotations

from . import version  # noqa: F401

__version__ = version.full_version

# On CPU (tests / local dev) match the reference's numerics: true-f32 matmuls
# and 64-bit int/float dtypes. On TPU keep JAX performance defaults (bf16
# MXU passes) — models run bf16 there anyway.
import os as _os

if _os.environ.get("JAX_PLATFORMS", "").split(",")[0] == "cpu":
    import jax as _jax

    # Some TPU plugins (axon) ignore the JAX_PLATFORMS env var and hang
    # initializing the TPU backend in subprocesses.  Honor the env var's
    # intent by forcing the config knob in-process — this is the only
    # reliable way to pin the platform, and it makes every child process
    # that imports paddle_tpu (launch trainers, store clients, test
    # scripts) safe on hosts with a broken TPU plugin installed.
    _jax.config.update("jax_platforms", "cpu")
    _jax.config.update("jax_enable_x64", True)
    _jax.config.update("jax_default_matmul_precision", "highest")

# framework core
from .framework import (Tensor, Parameter, EagerParamBase, no_grad, enable_grad,
                        is_grad_enabled, set_default_dtype, get_default_dtype, set_flags,
                        get_flags, seed, get_rng_state, set_rng_state)
from .framework.dtype import (bfloat16, bool_ as bool, complex64, complex128, float16, float32,
                              float64, int8, int16, int32, int64, uint8)

# the whole tensor-op surface re-exported flat (paddle.<op> style)
from .tensor import *  # noqa: F401,F403
from .tensor import (abs, add, matmul, mean, ones, zeros, to_tensor, concat, reshape,
                     transpose)  # explicit for linters

# subpackages
from . import amp  # noqa: F401
from . import autograd  # noqa: F401
from . import device  # noqa: F401
from . import distributed  # noqa: F401
from . import framework  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import linalg  # noqa: F401
from . import metric  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import profiler  # noqa: F401
from . import static  # noqa: F401
from . import tensor  # noqa: F401
from . import utils  # noqa: F401
from . import vision  # noqa: F401
from . import incubate  # noqa: F401
from . import sparse  # noqa: F401
from . import fft  # noqa: F401
from . import distribution  # noqa: F401
from . import audio  # noqa: F401
from . import geometric  # noqa: F401
from . import inference  # noqa: F401
from . import autotune  # noqa: F401
from . import quantization  # noqa: F401
from . import signal  # noqa: F401
from . import text  # noqa: F401
from . import regularizer  # noqa: F401
from . import reader  # noqa: F401
from . import dataset  # noqa: F401
from .batch import batch  # noqa: F401
from .regularizer import L1Decay, L2Decay  # noqa: F401


from . import sysconfig  # noqa: F401
from . import onnx  # noqa: F401

from .framework.io_state import save, load  # paddle.save/paddle.load

# device helpers (paddle.set_device / get_device)
from .device import get_device, set_device, is_compiled_with_cuda, is_compiled_with_xpu

# hapi Model at top level (paddle.Model)
from .hapi import Model  # noqa: F401
from .hapi import callbacks  # noqa: F401
from .hapi import summary  # noqa: F401
from . import hub  # noqa: F401
from .cost_model import flops  # noqa: F401
from .compat import (CPUPlace, CUDAPinnedPlace, CUDAPlace, CustomPlace, IPUPlace,
                     LazyGuard, MLUPlace, NPUPlace, TPUPlace, XPUPlace,
                     add_n, batch, cast, check_shape, create_parameter, diagonal,
                     disable_signal_handler, dsplit, dtype, finfo, frexp,
                     get_cuda_rng_state, hsplit, iinfo, index_add_, is_complex,
                     is_floating_point, is_integer, logcumsumexp, mv, reverse,
                     set_cuda_rng_state, set_grad_enabled, set_printoptions, sgn,
                     squeeze_, tanh_, tolist, unsqueeze_, vsplit)
from .distributed.parallel import DataParallel  # noqa: F401
from .framework.param_attr import ParamAttr  # noqa: F401


def is_compiled_with_tpu() -> bool:
    import jax

    try:
        return any(d.platform in ("tpu", "axon") for d in jax.devices())
    except RuntimeError:
        return False


def in_dynamic_mode() -> bool:
    """False while static-graph building is enabled (paddle.enable_static)."""
    from .static.graph import in_static_mode

    return not in_static_mode()


def disable_static(place=None):
    from .static.graph import disable_static_mode

    disable_static_mode()


def enable_static():
    """Switch to static-graph building: subsequent ops on static Variables
    record into the default main Program (see paddle_tpu/static/graph.py)."""
    from .static.graph import enable_static_mode

    enable_static_mode()


def grad(*args, **kwargs):
    from .framework.core import grad as _grad

    return _grad(*args, **kwargs)
