"""paddle.text parity (ref: python/paddle/text/ — dataset wrappers + viterbi).

Zero-egress environment: the canned datasets (Imdb/Imikolov/Conll05/...)
yield deterministic synthetic samples with the real schema when source files
are absent.
"""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op
from ..io import Dataset


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF viterbi decoding (ref text/viterbi_decode.py / viterbi_decode op)."""
    import jax
    import jax.numpy as jnp

    def f(emissions, trans):
        B, T, N = emissions.shape

        def step(score, emit_t):
            # score[b, j] = max_i score[b,i] + trans[i,j] + emit[b,j]
            cand = score[:, :, None] + trans[None, :, :]
            best = jnp.max(cand, axis=1) + emit_t
            idx = jnp.argmax(cand, axis=1)  # idx[b, j] = best prev tag for j
            return best, idx

        init = emissions[:, 0]
        final, hist = jax.lax.scan(step, init, jnp.swapaxes(emissions[:, 1:], 0, 1))
        scores = jnp.max(final, axis=-1)
        last = jnp.argmax(final, axis=-1)  # tag at time T-1

        def backtrack(cur, idx_t):
            prev = jnp.take_along_axis(idx_t, cur[:, None], 1)[:, 0]
            return prev, cur  # emit the tag at this timestep

        first, path_tail = jax.lax.scan(backtrack, last, hist, reverse=True)
        path = jnp.concatenate([first[None], path_tail], axis=0)  # (T, B)
        return scores, jnp.swapaxes(path, 0, 1).astype(jnp.int64)

    return apply_op(f, potentials, transition_params)


class _SyntheticTextDataset(Dataset):
    def __init__(self, n, seq_len, vocab, num_classes, seed=0):
        self._n, self._seq_len, self._vocab, self._nc, self._seed = \
            n, seq_len, vocab, num_classes, seed

    def __len__(self):
        return self._n

    def __getitem__(self, i):
        rng = np.random.RandomState(self._seed + i)
        return (rng.randint(0, self._vocab, self._seq_len).astype(np.int64),
                np.asarray(rng.randint(0, self._nc), np.int64))


class Imdb(_SyntheticTextDataset):
    def __init__(self, data_file=None, mode="train", cutoff=150):
        super().__init__(1024, 128, 5000, 2)


class Imikolov(_SyntheticTextDataset):
    def __init__(self, data_file=None, data_type="NGRAM", window_size=5, mode="train",
                 min_word_freq=50):
        super().__init__(1024, window_size, 2000, 2000)


class UCIHousing(Dataset):
    def __init__(self, data_file=None, mode="train"):
        rng = np.random.RandomState(0)
        self.x = rng.randn(404 if mode == "train" else 102, 13).astype(np.float32)
        w = rng.randn(13, 1).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.randn(len(self.x), 1)).astype(np.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


from . import datasets  # noqa: E402,F401
from .datasets import (Conll05st, Movielens, ViterbiDecoder, WMT14,  # noqa: E402,F401
                       WMT16)
