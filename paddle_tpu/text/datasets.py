"""paddle.text.datasets — map-style Dataset classes over the legacy reader
modules (ref python/paddle/text/datasets/: Conll05st, Imdb, Imikolov,
Movielens, UCIHousing, WMT14, WMT16 — same names, backed by
paddle_tpu.dataset's reader functions, synthetic corpora in this offline
image)."""
from __future__ import annotations

import numpy as np

from ..io import Dataset
from . import Imdb, Imikolov, UCIHousing  # noqa: F401  (already map-style)


class _ReaderDataset(Dataset):
    """Materialize a legacy reader() generator into a map-style dataset."""

    def __init__(self, reader):
        self._rows = [tuple(np.asarray(c) for c in row) if isinstance(
            row, (list, tuple)) else (np.asarray(row),) for row in reader()]

    def __len__(self):
        return len(self._rows)

    def __getitem__(self, idx):
        return self._rows[idx]


class Conll05st(_ReaderDataset):
    """ref text/datasets/conll05.py Conll05st (SRL): numeric 9-field rows
    (word_ids, ctx_n2..ctx_p2, pred_ids, mark, label_ids)."""

    def __init__(self, data_file=None, word_dict_file=None, verb_dict_file=None,
                 target_dict_file=None, emb_file=None, download=True):
        from ..dataset import conll05

        self.word_dict, self.verb_dict, self.label_dict = conll05.get_dict()
        super().__init__(conll05.reader_creator(
            conll05.corpus_reader(), self.word_dict, self.verb_dict,
            self.label_dict))

    def get_dict(self):
        return self.word_dict, self.verb_dict, self.label_dict

    def get_embedding(self):
        from ..dataset import conll05

        return conll05.get_embedding()


class Movielens(_ReaderDataset):
    """ref text/datasets/movielens.py Movielens rating rows."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0, download=True):
        from ..dataset import movielens

        super().__init__(movielens.__reader_creator__(
            rand_seed=rand_seed, test_ratio=test_ratio,
            is_test=(mode != "train")))


class WMT14(_ReaderDataset):
    """ref text/datasets/wmt14.py — (src_ids, trg_ids, trg_ids_next) rows."""

    def __init__(self, data_file=None, mode="train", dict_size=1000,
                 download=True):
        from ..dataset import wmt14

        reader = wmt14.train(dict_size) if mode == "train" else \
            wmt14.test(dict_size)
        super().__init__(reader)


class WMT16(_ReaderDataset):
    """ref text/datasets/wmt16.py."""

    def __init__(self, data_file=None, mode="train", src_dict_size=1000,
                 trg_dict_size=1000, lang="en", download=True):
        from ..dataset import wmt16

        reader = wmt16.train(src_dict_size, trg_dict_size, src_lang=lang) \
            if mode == "train" else \
            wmt16.test(src_dict_size, trg_dict_size, src_lang=lang)
        super().__init__(reader)


class ViterbiDecoder:
    """ref paddle.text.ViterbiDecoder — callable layer-style wrapper over
    viterbi_decode."""

    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        self.transitions = transitions

    def __call__(self, potentials, lengths=None):
        from . import viterbi_decode

        return viterbi_decode(potentials, self.transitions, lengths)
