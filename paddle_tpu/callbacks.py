"""paddle.callbacks — re-export of hapi callbacks
(ref python/paddle/callbacks.py → python/paddle/hapi/callbacks.py)."""
from .hapi.callbacks import (Callback, EarlyStopping, LRScheduler,  # noqa: F401
                             ModelCheckpoint, ProgBarLogger, ReduceLROnPlateau,
                             VisualDL)
