"""Device API (ref: python/paddle/device/__init__.py).

On TPU the device model is trivial compared to the reference's
DeviceManager/DeviceContextPool (ref paddle/phi/backends/device_manager.h):
XLA owns placement; this module surfaces enumeration + the stream/event API
as no-op-compatible shims (XLA streams are compiler-managed).
"""
from __future__ import annotations

import jax

_current_device = None


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except RuntimeError:
        return "cpu"


def get_device() -> str:
    if _current_device is not None:
        return _current_device
    p = _platform()
    if p in ("tpu", "axon"):
        return "tpu:0"
    if p == "gpu":
        return "gpu:0"
    return "cpu"


def set_device(device: str) -> str:
    global _current_device
    _current_device = device
    return device


def get_all_custom_device_type():
    return ["tpu"] if _platform() in ("tpu", "axon") else []


def device_count() -> int:
    try:
        return jax.device_count()
    except RuntimeError:
        return 0


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_mlu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str) -> bool:
    return device_type == "tpu" and _platform() in ("tpu", "axon")


class Stream:
    """Compat shim: XLA schedules its own streams on TPU (ref
    paddle/phi/backends/stream.h). Exists so stream-annotated user code runs."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        for d in jax.live_arrays():
            pass

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        pass


def current_stream(device=None):
    return Stream(device)


def synchronize(device=None):
    (jax.device_put(0) + 0).block_until_ready()


def stream_guard(stream):
    import contextlib

    @contextlib.contextmanager
    def _guard():
        yield

    return _guard()


def _resolve_jax_device(device=None):
    """None | int | 'tpu:3'/'gpu:1'/'xpu:0' | jax.Device → a jax.Device."""
    if device is None:
        return jax.devices()[0]
    if isinstance(device, int):
        return jax.devices()[device]
    if isinstance(device, str):
        plat, _, idx = device.partition(":")
        if plat == "cpu":
            try:
                pool = jax.devices("cpu")
            except RuntimeError:
                pool = jax.devices()
        else:
            # shim convention: 'gpu'/'xpu'/'tpu' all mean "the accelerator"
            # (Tensor.cuda() is likewise a no-op on the TPU array)
            pool = jax.devices()
        return pool[int(idx) if idx else 0]
    return device  # already a jax.Device


def memory_stats(device=None) -> dict:
    """Per-device memory statistics (ref memory/stats.h) via PJRT."""
    try:
        d = _resolve_jax_device(device)
        return dict(d.memory_stats() or {})
    except (RuntimeError, AttributeError, IndexError, ValueError):
        return {}


def max_memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_allocated(device=None) -> int:
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_reserved(device=None) -> int:
    # PJRT has no allocator-reservation counter distinct from usage; the peak
    # in-use high-water mark is the closest honest analogue (NOT bytes_limit,
    # which is the constant device capacity).
    stats = memory_stats(device)
    return int(stats.get("peak_bytes_reserved", stats.get("peak_bytes_in_use", 0)))


def memory_reserved(device=None) -> int:
    stats = memory_stats(device)
    return int(stats.get("bytes_reserved", stats.get("bytes_in_use", 0)))


def empty_cache():
    pass


class cuda:
    """paddle.device.cuda shim — reports no CUDA (we are a TPU build); the
    memory-stat APIs report the TPU's PJRT stats so monitoring code ports."""

    @staticmethod
    def device_count():
        return 0

    max_memory_allocated = staticmethod(max_memory_allocated)
    memory_allocated = staticmethod(memory_allocated)
    max_memory_reserved = staticmethod(max_memory_reserved)
    memory_reserved = staticmethod(memory_reserved)
    empty_cache = staticmethod(empty_cache)
    Stream = Stream
    Event = Event
