"""paddle.distribution parity (ref: python/paddle/distribution/ — 20+
distributions, kl registry, transforms)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, to_array
from ..framework.dispatch import apply_op
from ..framework.random import next_key

from . import constraint  # noqa: F401  (ref distribution/constraint.py)


def _v(x):
    if isinstance(x, Tensor):
        return x.value
    return jnp.asarray(x, jnp.float32)


def _shape(shape):
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return apply_op(jnp.exp, self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        return kl_divergence(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(jnp.square(self.scale), self.batch_shape))

    @property
    def stddev(self):
        return Tensor(jnp.broadcast_to(self.scale, self.batch_shape))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.normal(next_key(), s))

    def log_prob(self, value):
        def f(v):
            var = jnp.square(self.scale)
            return -jnp.square(v - self.loc) / (2 * var) - jnp.log(self.scale) \
                - 0.5 * math.log(2 * math.pi)

        return apply_op(f, value)

    def entropy(self):
        return Tensor(jnp.broadcast_to(
            0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale), self.batch_shape))

    def cdf(self, value):
        return apply_op(
            lambda v: 0.5 * (1 + jax.scipy.special.erf(
                (v - self.loc) / (self.scale * math.sqrt(2)))), value)


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _v(low)
        self.high = _v(high)
        super().__init__(jnp.broadcast_shapes(self.low.shape, self.high.shape))

    @property
    def mean(self):
        return Tensor((self.low + self.high) / 2)

    @property
    def variance(self):
        return Tensor(jnp.square(self.high - self.low) / 12)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), s)
        return Tensor(self.low + (self.high - self.low) * u)

    def log_prob(self, value):
        def f(v):
            inside = (v >= self.low) & (v < self.high)
            return jnp.where(inside, -jnp.log(self.high - self.low), -jnp.inf)

        return apply_op(f, value)

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low) + jnp.zeros(self.batch_shape))


class Bernoulli(Distribution):
    def __init__(self, probs=None, logits=None, name=None):
        if probs is not None:
            self.probs = _v(probs)
            self.logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        else:
            self.logits = _v(logits)
            self.probs = jax.nn.sigmoid(self.logits)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return Tensor(self.probs)

    @property
    def variance(self):
        return Tensor(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(next_key(), self.probs, s).astype(jnp.float32))

    def log_prob(self, value):
        return apply_op(
            lambda v: v * jax.nn.log_sigmoid(self.logits)
            + (1 - v) * jax.nn.log_sigmoid(-self.logits), value)

    def entropy(self):
        p = self.probs
        return Tensor(-(p * jnp.log(jnp.clip(p, 1e-12, None))
                        + (1 - p) * jnp.log(jnp.clip(1 - p, 1e-12, None))))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is not None:
            self.logits = _v(logits)
        else:
            self.logits = jnp.log(jnp.clip(_v(probs), 1e-30, None))
        self._probs = jax.nn.softmax(self.logits, -1)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return Tensor(self._probs)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.categorical(next_key(), self.logits, shape=s))

    def log_prob(self, value):
        def f(v):
            logp = jax.nn.log_softmax(self.logits, -1)
            return jnp.take_along_axis(logp, v.astype(jnp.int32)[..., None], -1)[..., 0]

        return apply_op(f, value)

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return Tensor(-jnp.sum(self._probs * logp, -1))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_ = _v(probs)
        super().__init__(self.probs_.shape[:-1], self.probs_.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs_)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        logits = jnp.log(jnp.clip(self.probs_, 1e-30, None))
        draws = jax.random.categorical(next_key(), logits,
                                       shape=(self.total_count,) + s)
        k = self.probs_.shape[-1]
        return Tensor(jnp.sum(jax.nn.one_hot(draws, k), axis=0))

    def log_prob(self, value):
        def f(v):
            logp = jnp.log(jnp.clip(self.probs_, 1e-30, None))
            coeff = jax.scipy.special.gammaln(self.total_count + 1.0) - jnp.sum(
                jax.scipy.special.gammaln(v + 1.0), -1)
            return coeff + jnp.sum(v * logp, -1)

        return apply_op(f, value)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _v(alpha)
        self.beta = _v(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        t = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (t * t * (t + 1)))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.beta(next_key(), self.alpha, self.beta, s))

    def log_prob(self, value):
        def f(v):
            return ((self.alpha - 1) * jnp.log(v) + (self.beta - 1) * jnp.log1p(-v)
                    - (jax.scipy.special.gammaln(self.alpha)
                       + jax.scipy.special.gammaln(self.beta)
                       - jax.scipy.special.gammaln(self.alpha + self.beta)))

        return apply_op(f, value)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _v(concentration)
        self.rate = _v(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape, self.rate.shape))

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / jnp.square(self.rate))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.gamma(next_key(), self.concentration, s) / self.rate)

    def log_prob(self, value):
        def f(v):
            a, b = self.concentration, self.rate
            return a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v \
                - jax.scipy.special.gammaln(a)

        return apply_op(f, value)


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _v(concentration)
        super().__init__(self.concentration.shape[:-1], self.concentration.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration / jnp.sum(self.concentration, -1, keepdims=True))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(next_key(), self.concentration, s))

    def log_prob(self, value):
        def f(v):
            a = self.concentration
            return (jnp.sum((a - 1) * jnp.log(v), -1)
                    + jax.scipy.special.gammaln(jnp.sum(a, -1))
                    - jnp.sum(jax.scipy.special.gammaln(a), -1))

        return apply_op(f, value)


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / jnp.square(self.rate))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.exponential(next_key(), s) / self.rate)

    def log_prob(self, value):
        return apply_op(lambda v: jnp.log(self.rate) - self.rate * v, value)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return Tensor(jnp.broadcast_to(2 * jnp.square(self.scale), self.batch_shape))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.laplace(next_key(), s))

    def log_prob(self, value):
        return apply_op(
            lambda v: -jnp.abs(v - self.loc) / self.scale
            - jnp.log(2 * self.scale), value)


class LogNormal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        self._normal = Normal(loc, scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + jnp.square(self.scale) / 2))

    def sample(self, shape=()):
        return apply_op(jnp.exp, self._normal.sample(shape))

    def log_prob(self, value):
        def f(v):
            logv = jnp.log(v)
            var = jnp.square(self.scale)
            return -jnp.square(logv - self.loc) / (2 * var) - logv \
                - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi)

        return apply_op(f, value)


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * np.euler_gamma)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.gumbel(next_key(), s))

    def log_prob(self, value):
        def f(v):
            z = (v - self.loc) / self.scale
            return -(z + jnp.exp(-z)) - jnp.log(self.scale)

        return apply_op(f, value)


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _v(probs)
        super().__init__(self.probs_.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.probs_)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        u = jax.random.uniform(next_key(), s)
        return Tensor(jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs_)) + 1)

    def log_prob(self, value):
        return apply_op(
            lambda v: (v - 1) * jnp.log1p(-self.probs_) + jnp.log(self.probs_), value)


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.cauchy(next_key(), s))

    def log_prob(self, value):
        def f(v):
            z = (v - self.loc) / self.scale
            return -jnp.log(math.pi * self.scale * (1 + z * z))

        return apply_op(f, value)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _v(df)
        self.loc = _v(loc)
        self.scale = _v(scale)
        super().__init__(jnp.broadcast_shapes(self.df.shape, self.loc.shape,
                                              self.scale.shape))

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(self.loc + self.scale * jax.random.t(next_key(), self.df, s))

    def log_prob(self, value):
        def f(v):
            d = self.df
            z = (v - self.loc) / self.scale
            return (jax.scipy.special.gammaln((d + 1) / 2)
                    - jax.scipy.special.gammaln(d / 2)
                    - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                    - (d + 1) / 2 * jnp.log1p(z * z / d))

        return apply_op(f, value)


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _v(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.poisson(next_key(), self.rate, s).astype(jnp.float32))

    def log_prob(self, value):
        return apply_op(
            lambda v: v * jnp.log(self.rate) - self.rate
            - jax.scipy.special.gammaln(v + 1), value)


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = _v(total_count)
        self.probs_ = _v(probs)
        super().__init__(jnp.broadcast_shapes(self.total_count.shape, self.probs_.shape))

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs_)

    def sample(self, shape=()):
        s = _shape(shape) + self.batch_shape
        return Tensor(jax.random.binomial(next_key(), self.total_count, self.probs_, s))

    def log_prob(self, value):
        def f(v):
            n, p = self.total_count, self.probs_
            return (jax.scipy.special.gammaln(n + 1)
                    - jax.scipy.special.gammaln(v + 1)
                    - jax.scipy.special.gammaln(n - v + 1)
                    + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

        return apply_op(f, value)


# --------------------------------------------------------------------------- #
# Transforms + TransformedDistribution (subset of ref transform.py)
# --------------------------------------------------------------------------- #


class Transform:
    def forward(self, x):
        raise NotImplementedError

    def inverse(self, y):
        raise NotImplementedError

    def forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _v(loc)
        self.scale = _v(scale)

    def forward(self, x):
        return apply_op(lambda v: self.loc + self.scale * v, x)

    def inverse(self, y):
        return apply_op(lambda v: (v - self.loc) / self.scale, y)

    def forward_log_det_jacobian(self, x):
        return apply_op(lambda v: jnp.broadcast_to(jnp.log(jnp.abs(self.scale)),
                                                   v.shape), x)


class ExpTransform(Transform):
    def forward(self, x):
        return apply_op(jnp.exp, x)

    def inverse(self, y):
        return apply_op(jnp.log, y)

    def forward_log_det_jacobian(self, x):
        return apply_op(lambda v: v, x)


class SigmoidTransform(Transform):
    def forward(self, x):
        return apply_op(jax.nn.sigmoid, x)

    def inverse(self, y):
        return apply_op(lambda v: jnp.log(v) - jnp.log1p(-v), y)

    def forward_log_det_jacobian(self, x):
        return apply_op(lambda v: jax.nn.log_sigmoid(v) + jax.nn.log_sigmoid(-v), x)


class TanhTransform(Transform):
    def forward(self, x):
        return apply_op(jnp.tanh, x)

    def inverse(self, y):
        return apply_op(jnp.arctanh, y)

    def forward_log_det_jacobian(self, x):
        return apply_op(lambda v: 2.0 * (math.log(2.0) - v - jax.nn.softplus(-2.0 * v)),
                        x)


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        self.base = base
        self.transforms = transforms if isinstance(transforms, (list, tuple)) \
            else [transforms]
        super().__init__(base.batch_shape, base.event_shape)

    def sample(self, shape=()):
        x = self.base.sample(shape)
        for t in self.transforms:
            x = t.forward(x)
        return x

    def log_prob(self, value):
        y = value
        ldj_total = None
        for t in reversed(self.transforms):
            x = t.inverse(y)
            ldj = t.forward_log_det_jacobian(x)
            ldj_total = ldj if ldj_total is None else ldj_total + ldj
            y = x
        base_lp = self.base.log_prob(y)
        return base_lp - ldj_total


# --------------------------------------------------------------------------- #
# KL divergence registry (ref kl.py)
# --------------------------------------------------------------------------- #

_KL_REGISTRY = {}


def register_kl(type_p, type_q):
    def decorator(fn):
        _KL_REGISTRY[(type_p, type_q)] = fn
        return fn

    return decorator


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise NotImplementedError(f"KL({type(p).__name__} || {type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal(p, q):
    var_ratio = jnp.square(p.scale / q.scale)
    t1 = jnp.square((p.loc - q.loc) / q.scale)
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Categorical, Categorical)
def _kl_categorical(p, q):
    logp = jax.nn.log_softmax(p.logits, -1)
    logq = jax.nn.log_softmax(q.logits, -1)
    return Tensor(jnp.sum(jnp.exp(logp) * (logp - logq), -1))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli(p, q):
    pr = jnp.clip(p.probs, 1e-7, 1 - 1e-7)
    qr = jnp.clip(q.probs, 1e-7, 1 - 1e-7)
    return Tensor(pr * (jnp.log(pr) - jnp.log(qr))
                  + (1 - pr) * (jnp.log1p(-pr) - jnp.log1p(-qr)))


@register_kl(Uniform, Uniform)
def _kl_uniform(p, q):
    return Tensor(jnp.log((q.high - q.low) / (p.high - p.low)))


@register_kl(Exponential, Exponential)
def _kl_exponential(p, q):
    r = q.rate / p.rate
    return Tensor(jnp.log(1.0 / r) + r - 1.0)


# --------------------------------------------------------------------------- #
# Independent (ref: python/paddle/distribution/independent.py:18)
# --------------------------------------------------------------------------- #


class Independent(Distribution):
    """Reinterpret the rightmost ``reinterpreted_batch_rank`` batch dims of
    ``base`` as event dims: log_prob/entropy sum over them (ref
    independent.py:18)."""

    def __init__(self, base, reinterpreted_batch_rank):
        if not isinstance(base, Distribution):
            raise TypeError(
                f"Expected type of 'base' is Distribution, got {type(base)}")
        if not (0 < reinterpreted_batch_rank <= len(base.batch_shape)):
            raise ValueError(
                f"Expected 0 < reinterpreted_batch_rank <= "
                f"{len(base.batch_shape)}, got {reinterpreted_batch_rank}")
        self._base = base
        self._reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        shape = tuple(base.batch_shape) + tuple(base.event_shape)
        cut = len(base.batch_shape) - self._reinterpreted_batch_rank
        super().__init__(batch_shape=shape[:cut], event_shape=shape[cut:])

    @property
    def mean(self):
        return self._base.mean

    @property
    def variance(self):
        return self._base.variance

    def sample(self, shape=()):
        return self._base.sample(shape)

    def rsample(self, shape=()):
        return self._base.rsample(shape)

    def log_prob(self, value):
        return self._sum_rightmost(self._base.log_prob(value),
                                   self._reinterpreted_batch_rank)

    def entropy(self):
        return self._sum_rightmost(self._base.entropy(),
                                   self._reinterpreted_batch_rank)

    def _sum_rightmost(self, value, n):
        # through apply_op so the tape records the reduction: ELBO-style
        # training differentiates through Independent.log_prob
        if n <= 0:
            return value if isinstance(value, Tensor) else Tensor(value)
        return apply_op(lambda v: v.sum(tuple(range(-n, 0))), value)


@register_kl(Independent, Independent)
def _kl_independent(p, q):
    if p._reinterpreted_batch_rank != q._reinterpreted_batch_rank:
        raise NotImplementedError(
            "KL between Independents of different reinterpreted ranks")
    inner = kl_divergence(p._base, q._base)
    return p._sum_rightmost(inner, p._reinterpreted_batch_rank)


class ExponentialFamily(Distribution):
    """Base for exponential-family distributions (ref
    distribution/exponential_family.py:20): p(x;θ) = exp(<t(x),θ> - F(θ) +
    k(x)).  Subclasses provide ``_natural_parameters`` and
    ``_log_normalizer``; entropy comes from the Bregman identity
    H = F(θ) - Σ θ·∇F(θ) - E[k(x)] computed with jax.grad (the reference
    uses paddle.grad with create_graph)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_parameters):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        raise NotImplementedError

    def entropy(self):
        nats = [jnp.asarray(_v(p), jnp.float32)
                for p in self._natural_parameters]

        def F(*ps):
            out = self._log_normalizer(*ps)
            return jnp.sum(_v(out))

        log_norm = self._log_normalizer(*nats)
        grads = jax.grad(F, argnums=tuple(range(len(nats))))(*nats)
        ent = -self._mean_carrier_measure + _v(log_norm)
        for p, g in zip(nats, grads):
            ent = ent - p * g
        return Tensor(ent)
