"""Constraint system (ref: python/paddle/distribution/constraint.py —
Constraint:17, Real:24, Range:29, Positive:39, Simplex:44).

A constraint is a predicate over parameter/sample space; ``__call__``
returns a boolean array marking in-support entries.  Distributions use
these for argument validation (`variable.py` in the reference wires them
into transformed variables)."""
from __future__ import annotations

import jax.numpy as jnp


class Constraint:
    """Base: callable value -> bool array (ref constraint.py:17)."""

    def __call__(self, value):
        raise NotImplementedError


class Real(Constraint):
    def __call__(self, value):
        return value == value  # finite-dtype NaN check, ref semantics


class Range(Constraint):
    def __init__(self, lower, upper):
        self._lower = lower
        self._upper = upper
        super().__init__()

    def __call__(self, value):
        return (self._lower <= value) & (value <= self._upper)


class Positive(Constraint):
    def __call__(self, value):
        return value >= 0.0


class Simplex(Constraint):
    def __call__(self, value):
        return jnp.all(value >= 0, -1) & (
            jnp.abs(value.sum(-1) - 1.0) < 1e-6)


real = Real()
positive = Positive()
simplex = Simplex()
