"""Weight-only int8 matmul for HBM-bound decode.

Reference analogue: the int8 variants of the fused transformer ops
(ref paddle/fluid/operators/fused/fused_multi_transformer_int8_op.cu,
quant_dequant kernels) and the PTQ weight-only path. On TPU the motivation
is sharper: single-token decode re-reads every weight per token, so tokens/s
is bounded by HBM bandwidth / parameter bytes — int8 weights halve the bytes
and nearly double the decode roofline.

Scheme: symmetric per-output-channel absmax. w ≈ w_q(int8) * scale(f32)[N],
and since scale is per *column*, dot(x, w_q·scale) == dot(x, w_q) · scale —
the kernel dots in bf16 (int8 values up to 127 are exact in bf16) and applies
the scale to the fp32 accumulator. The Pallas kernel streams int8 weight
blocks through VMEM (half the bytes of the bf16 path); on CPU the plain jnp
dequant path runs, except under PT_FLASH_INTERPRET=1 where the Pallas
kernel itself executes interpreted (same gate as flash_attention — CI
coverage of the kernel logic without a chip).
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

_LANE = 128
_WARNED_FALLBACK = False


def quantize_per_channel(w) -> Tuple[jax.Array, jax.Array]:
    """[K, N] float → ([K, N] int8, [N] f32 scale); symmetric absmax."""
    w = jnp.asarray(w)
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    w_q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127)
    return w_q.astype(jnp.int8), scale


def _use_pallas() -> bool:
    from .flash_attention import _use_pallas as f

    return f()


def _interpret() -> bool:
    from .flash_attention import _interpret as f

    return f()


def _w8_kernel(x_ref, w_ref, s_ref, o_ref, *, out_dtype):
    x = x_ref[...]
    w = w_ref[...].astype(x.dtype)
    s = s_ref[...]  # (1, bn) — 2-D so Mosaic/XLA agree on the layout
    acc = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    o_ref[...] = (acc * s).astype(o_ref.dtype)


def _w8_matmul_pallas(x2, w_q, scale, out_dtype, block_n: int = 0):
    import os

    from jax.experimental import pallas as pl

    M, K = x2.shape
    N = w_q.shape[1]
    if not block_n:
        try:
            block_n = int(os.environ.get("PT_W8_BLOCK_N", 512))
        except ValueError:
            block_n = 512
        # round down to a power of two in [_LANE, ...]; bad values would
        # either ZeroDivide (0) or shred the grid into tiny blocks
        block_n = max(_LANE, 1 << max(block_n, _LANE).bit_length() - 1)
    bn = min(block_n, N)
    while N % bn:
        bn //= 2
    grid = (N // bn,)
    return pl.pallas_call(
        functools.partial(_w8_kernel, out_dtype=out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((M, K), lambda j: (0, 0)),
            pl.BlockSpec((K, bn), lambda j: (0, j)),
            pl.BlockSpec((1, bn), lambda j: (0, j)),
        ],
        out_specs=pl.BlockSpec((M, bn), lambda j: (0, j)),
        out_shape=jax.ShapeDtypeStruct((M, N), out_dtype),
        # interpret-mode knob mirrors flash_attention: CPU CI runs the same
        # kernel logic interpreted (compiled Mosaic lowering is TPU-only and
        # its error escapes the caller's try/except at jit-compile time)
        interpret=_interpret(),
    )(x2, w_q, scale.reshape(1, N))


def w8_matmul(x, w_q, scale):
    """x [..., K] @ dequant(w_q [K, N], scale [N]) -> [..., N] in x.dtype."""
    x = jnp.asarray(x)
    lead = x.shape[:-1]
    K = x.shape[-1]
    N = w_q.shape[1]
    x2 = x.reshape(-1, K)
    M = x2.shape[0]
    out_dtype = x.dtype
    # the streaming int8 kernel only wins when the matmul is weight-read
    # bound (single-token decode, M = decode batch). Prefill/training
    # shapes re-use each weight block M times — there the dequantize-once
    # XLA path is the right program. The old M<=256 gate let per-request
    # SERVER prefills (M = one prompt bucket, 32-128) onto the streaming
    # kernel and collapsed under-load int8 serving to 62 tok/s (r5,
    # BASELINE.md); decode batches are <=16 in every shipped config.
    usable = (_use_pallas() and K % _LANE == 0 and N % _LANE == 0 and
              M <= 16)
    if usable:
        try:
            out = _w8_matmul_pallas(x2, w_q, scale, out_dtype)
            return out.reshape(*lead, N)
        except Exception as e:  # noqa: BLE001 — Mosaic raises many types
            global _WARNED_FALLBACK
            if not _WARNED_FALLBACK:
                import warnings

                warnings.warn(
                    f"w8_matmul: Pallas kernel failed ({type(e).__name__}: "
                    f"{e}); falling back to full dequantization — the int8 "
                    "bandwidth advantage is LOST", RuntimeWarning)
                _WARNED_FALLBACK = True
    deq = (w_q.astype(jnp.float32) * scale[None, :]).astype(out_dtype)
    return jnp.matmul(x2, deq).reshape(*lead, N)
