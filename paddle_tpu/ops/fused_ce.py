"""Fused lm-head + cross-entropy, chunked over tokens.

The standard pretrain loss materializes fp32 logits of shape [B, S, V]
(bench config: 8*2048*32000*4 B = 2 GB) and saves them for backward — the
single largest HBM tensor in the train step. This op computes the loss in
token chunks (logits for one chunk at a time, discarded after the logsumexp
and label gather) and recomputes the chunk logits in the hand-written
backward, so the residuals are O(N) instead of O(N*V).

Reference analogue: paddle/phi/kernels/fusion (fused softmax+CE kernels) and
mp_ops.py:_c_softmax_with_cross_entropy:375 — there fused for TP numerics,
here fused for HBM traffic. The vocab ("tensor"-sharded) dimension stays a
plain dot so GSPMD inserts the TP collectives exactly as it does for the
unfused path.

Backward per chunk: p = softmax(logits); dlogits = (p - onehot(label)) * g / n_valid;
dh = dlogits @ W^T; dW += h^T @ dlogits. Extra cost is one logits recompute
(+2NHV FLOPs, ~1/3 of the lm-head's 6NHV) in exchange for never storing NV.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _vma_zeros(shape, dtype, operands):
    """Zero scan-carry whose varying-manual-axis type is the UNION of the
    operands' (shard_map check_vma): a fresh jnp constant would be unvarying
    and fail scan's carry type check when any operand is varying (e.g. the
    pipe-manual 1F1B region).  Value-independent — never mixes operand
    values into the zero, so non-finite garbage at masked positions cannot
    poison the carry."""
    from .flash_attention import _vma_of

    z = jnp.zeros(shape, dtype)
    vma = _vma_of(*operands)
    return jax.lax.pcast(z, tuple(vma), to="varying") if vma else z


def _chunk(h2, labels, chunk_size, ignore_index):
    """Pad [N,H]/[N] to a multiple of chunk_size and reshape to chunks."""
    n = h2.shape[0]
    c = min(chunk_size, n)
    nchunk = -(-n // c)
    pad = nchunk * c - n
    if pad:
        h2 = jnp.pad(h2, ((0, pad), (0, 0)))
        labels = jnp.pad(labels, (0, pad), constant_values=ignore_index)
    return h2.reshape(nchunk, c, h2.shape[-1]), labels.reshape(nchunk, c), pad


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flce(h2, w, labels, ignore_index, chunk_size, rows=0):
    (loss_sum, cnt), _ = _flce_scan(h2, w, labels, ignore_index, chunk_size,
                                    rows)
    return loss_sum / jnp.maximum(cnt.astype(jnp.float32), 1.0)


def _flce_scan(h2, w, labels, ignore_index, chunk_size, rows=0):
    hc, lc, _ = _chunk(h2, labels, chunk_size, ignore_index)
    c = hc.shape[1]
    # CEGeometry row sub-tile (forward only): compute the row-local
    # quantities — logits row, logsumexp, label gather — in r-row
    # sub-tiles so the f32 [c, V] transient shrinks to [r, V]. Each
    # output row's contraction and reduction is untouched and the loss
    # sum below stays at whole-chunk granularity, so any sub-tile is
    # bit-exact vs the default (rows=0 keeps today's whole-chunk path,
    # byte-identical jaxpr).
    r = c if rows <= 0 else _largest_divisor_ce(c, rows)

    def row_local(hk, lk):
        logits = jnp.dot(hk, w, preferred_element_type=jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        li = jnp.clip(lk, 0, logits.shape[-1] - 1).astype(jnp.int32)
        gold = jnp.take_along_axis(logits, li[:, None], axis=-1)[:, 0]
        valid = lk != ignore_index
        loss = jnp.where(valid, lse - gold, 0.0)
        return loss, lse, valid

    def body(carry, xs):
        s_loss, s_cnt = carry
        hk, lk = xs
        if r < c:
            loss, lse, valid = jax.lax.map(
                lambda t: row_local(*t),
                (hk.reshape(c // r, r, hk.shape[-1]),
                 lk.reshape(c // r, r)))
            loss, lse, valid = (loss.reshape(c), lse.reshape(c),
                                valid.reshape(c))
        else:
            loss, lse, valid = row_local(hk, lk)
        return (s_loss + loss.sum().astype(jnp.float32),
                s_cnt + valid.sum().astype(jnp.int32)), lse

    z_loss = _vma_zeros((), jnp.float32, (h2, w, labels))
    z_cnt = _vma_zeros((), jnp.int32, (h2, w, labels))
    return lax.scan(body, (z_loss, z_cnt), (hc, lc))


def _largest_divisor_ce(n: int, want: int) -> int:
    from ..autotune.kernel_geometry import _largest_divisor

    return _largest_divisor(n, want)


def _flce_fwd(h2, w, labels, ignore_index, chunk_size, rows=0):
    (loss_sum, cnt), lses = _flce_scan(h2, w, labels, ignore_index,
                                       chunk_size, rows)
    mean = loss_sum / jnp.maximum(cnt.astype(jnp.float32), 1.0)
    return mean, (h2, w, labels, lses, cnt)


def _flce_bwd(ignore_index, chunk_size, rows, res, g):
    # the CEGeometry row sub-tile is forward-only; backward recomputes
    # at whole-chunk granularity regardless (rows is unused)
    h2, w, labels, lses, cnt = res
    hc, lc, _ = _chunk(h2, labels, chunk_size, ignore_index)
    scale = g / jnp.maximum(cnt.astype(jnp.float32), 1.0)
    v = w.shape[-1]

    def body(dw, xs):
        hk, lk, lsek = xs
        logits = jnp.dot(hk, w, preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lsek[:, None])
        li = jnp.clip(lk, 0, v - 1).astype(jnp.int32)
        valid = (lk != ignore_index)[:, None]
        onehot = jax.nn.one_hot(li, v, dtype=jnp.float32)
        dlog = jnp.where(valid, (p - onehot) * scale, 0.0)
        dh_k = jnp.dot(dlog.astype(w.dtype), w.T).astype(hk.dtype)
        # mask ignored rows' activations before the token-contraction: the
        # dot sums hk[t]*dlog[t] over t, and inf*0 at a masked row would
        # NaN-poison every dw entry
        hk_safe = jnp.where(valid, hk.astype(jnp.float32), 0.0)
        dw = dw + jnp.dot(hk_safe.T, dlog)
        return dw, dh_k

    dw0 = _vma_zeros(w.shape, jnp.float32, (h2, w, labels, lses, g))
    dw, dhc = lax.scan(body, dw0, (hc, lc, lses))
    dh2 = dhc.reshape(-1, h2.shape[-1])[: h2.shape[0]]
    return dh2, dw.astype(w.dtype), None


_flce.defvjp(_flce_fwd, _flce_bwd)


def capped_chunk_size(chunk_size: int, seq_len: int) -> int:
    """Long-sequence cap, shared by EVERY fused-CE caller (llama forward,
    pipeline post_fn): at S>8192 the streaming-flash residuals peak
    together with the CE's transient f32 [c, V] logits — chunk 16384 OOMs
    the S=16384 B=1 config on v5e (measured 2026-08-01) while 8192
    reproduces the recorded 0.4185 MFU."""
    return chunk_size if seq_len <= 8192 else min(chunk_size, 8192)


def fused_linear_cross_entropy(hidden, weight, labels, ignore_index: int = -100,
                               chunk_size: int = 1024,
                               transpose_weight: bool = False,
                               geometry=None):
    """Mean next-token CE of ``softmax(hidden @ weight)`` vs integer ``labels``
    without materializing the full logits tensor.

    hidden: [..., H]; weight: [H, V] ([V, H] with transpose_weight, for tied
    embeddings); labels: integer [...] matching hidden's leading dims.
    ``geometry`` (:class:`CEGeometry`): forward row sub-tile; None consults
    the process-wide winner cache at trace time (key: the hidden width).
    """
    import os

    # PT_CE_CHUNK overrides at the single entry point so EVERY caller
    # (llama loss, pipeline-engine post_fn) honors the on-hardware A/B knob.
    # Only a positive-int value applies; anything else (empty string, 0,
    # garbage) would surface later as an opaque trace-time error with no
    # hint it came from the env knob, so warn and keep the caller's value.
    override = os.environ.get("PT_CE_CHUNK")
    if override is not None:
        try:
            parsed = int(override)
        except ValueError:
            parsed = 0
        if parsed > 0:
            chunk_size = parsed
        else:
            import warnings

            warnings.warn(f"PT_CE_CHUNK={override!r} is not a positive int; "
                          f"keeping chunk_size={chunk_size}")
    if transpose_weight:
        weight = weight.T
    h2 = hidden.reshape(-1, hidden.shape[-1])
    l1 = labels.reshape(-1)
    if geometry is None:
        from ..autotune.kernel_geometry import resolve_geometry

        geometry = resolve_geometry("fused_ce", str(hidden.dtype),
                                    hidden.shape[-1])[0]
    else:
        from ..autotune.kernel_geometry import CEGeometry

        if not isinstance(geometry, CEGeometry):
            raise ValueError(f"fused CE wants a CEGeometry, got "
                             f"{type(geometry).__name__}")
        geometry.validate()
    return _flce(h2, weight, l1, ignore_index, chunk_size, geometry.rows)
