"""Pallas TPU kernel library — the replacement for the reference's fused CUDA
ops (ref paddle/fluid/operators/fused/: fused_attention_op.cu,
fused_multi_transformer_op.cu, fmha_ref.h) and hand-written PHI GPU kernels.
"""
from .flash_attention import flash_attention, flash_attention_bshd
from .fused_norm import fused_rms_norm, fused_layer_norm

__all__ = ["flash_attention", "flash_attention_bshd", "fused_rms_norm",
           "fused_layer_norm"]
