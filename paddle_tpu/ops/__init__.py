"""Pallas TPU kernel library — the replacement for the reference's fused CUDA
ops (ref paddle/fluid/operators/fused/: fused_attention_op.cu,
fused_multi_transformer_op.cu, fmha_ref.h) and hand-written PHI GPU kernels.

Kernel dispatch contract (shared by flash_attention, paged_attention, and the
fused LoRA projections):

* ``use_megakernel()`` — True when the whole-tick decode megakernel
  (ops/decode_megakernel.py) is the requested top rung: the process-wide mode
  was pinned to ``"megakernel"`` via :func:`set_kernel_mode`. The megakernel's
  shape guards fall back to the per-layer Pallas kernels (``use_pallas()``
  stays True under megakernel mode), which themselves fall back to the jnp
  reference — the three-rung dispatch ladder.
* ``use_pallas()`` — True when a Pallas code path should run: on a real TPU
  backend, when ``PT_FLASH_INTERPRET=1`` (interpret mode on CPU), or when the
  process-wide mode was pinned to ``"pallas"`` or ``"megakernel"`` via
  :func:`set_kernel_mode`. ``"reference"`` pins the jnp compositions
  regardless of backend.
* ``pallas_interpret()`` — True when ``pl.pallas_call`` must run interpreted
  (no Mosaic compiler available), i.e. Pallas was requested on a non-TPU
  backend.

All three are read at TRACE time, so flipping the mode between compiled
program invocations has no effect — set it before the first trace
(GenerationServer does this in its constructor via ``kernels=``).
"""
import os as _os

import jax as _jax

KERNEL_MODES = ("auto", "pallas", "megakernel", "reference")

_KERNEL_MODE = "auto"


def set_kernel_mode(mode: str) -> None:
    """Pin the process-wide kernel dispatch: ``"megakernel"`` requests the
    whole-tick persistent kernel (falling back per the ladder),
    ``"pallas"`` forces the per-layer Pallas kernels (interpret mode
    off-TPU), ``"reference"`` forces the jnp compositions, ``"auto"``
    restores backend-based dispatch."""
    global _KERNEL_MODE
    if mode not in KERNEL_MODES:
        raise ValueError(
            f"kernel mode must be one of {KERNEL_MODES}, got {mode!r}")
    _KERNEL_MODE = mode


def kernel_mode() -> str:
    return _KERNEL_MODE


def use_megakernel() -> bool:
    """Top rung of the ladder: only an explicit ``kernels="megakernel"``
    opts in (never ``"auto"`` — the tick-level fusion changes program
    structure, so it is a deliberate serving configuration)."""
    return _KERNEL_MODE == "megakernel"


def use_pallas() -> bool:
    if _KERNEL_MODE == "reference":
        return False
    if _KERNEL_MODE in ("pallas", "megakernel"):
        return True
    return (_jax.default_backend() in ("tpu", "axon")
            or _os.environ.get("PT_FLASH_INTERPRET") == "1")


def pallas_interpret() -> bool:
    """Interpret mode: the Pallas path was requested on a non-TPU backend."""
    if _jax.default_backend() in ("tpu", "axon"):
        return False
    return (_os.environ.get("PT_FLASH_INTERPRET") == "1"
            or _KERNEL_MODE in ("pallas", "megakernel"))


from .flash_attention import flash_attention, flash_attention_bshd
from .fused_norm import fused_rms_norm, fused_layer_norm
from .paged_attention import (gather_block_kv, gather_block_scales,
                              paged_decode_attention,
                              paged_decode_attention_q,
                              paged_prefill_attention,
                              paged_prefill_attention_q,
                              quantize_block_kv, write_chunk_kv,
                              write_chunk_kv_q, write_decode_kv,
                              write_decode_kv_q)

__all__ = ["flash_attention", "flash_attention_bshd", "fused_rms_norm",
           "fused_layer_norm", "gather_block_kv", "gather_block_scales",
           "kernel_mode", "paged_decode_attention",
           "paged_decode_attention_q", "paged_prefill_attention",
           "paged_prefill_attention_q", "pallas_interpret",
           "quantize_block_kv", "set_kernel_mode", "use_megakernel",
           "use_pallas",
           "write_chunk_kv", "write_chunk_kv_q", "write_decode_kv",
           "write_decode_kv_q"]
