"""Pallas TPU kernel library — the replacement for the reference's fused CUDA
ops (ref paddle/fluid/operators/fused/: fused_attention_op.cu,
fused_multi_transformer_op.cu, fmha_ref.h) and hand-written PHI GPU kernels.
"""
from .flash_attention import flash_attention, flash_attention_bshd
from .fused_norm import fused_rms_norm, fused_layer_norm
from .paged_attention import (gather_block_kv, gather_block_scales,
                              paged_decode_attention,
                              paged_decode_attention_q,
                              paged_prefill_attention,
                              paged_prefill_attention_q,
                              quantize_block_kv, write_chunk_kv,
                              write_chunk_kv_q, write_decode_kv,
                              write_decode_kv_q)

__all__ = ["flash_attention", "flash_attention_bshd", "fused_rms_norm",
           "fused_layer_norm", "gather_block_kv", "gather_block_scales",
           "paged_decode_attention", "paged_decode_attention_q",
           "paged_prefill_attention", "paged_prefill_attention_q",
           "quantize_block_kv", "write_chunk_kv", "write_chunk_kv_q",
           "write_decode_kv", "write_decode_kv_q"]
