"""Flash attention (Pallas TPU kernel).

Replaces the reference's CUDA FMHA stack (ref
paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h,
fused_softmax_mask kernels) with a blockwise online-softmax kernel that never
materialises the S×S score matrix in HBM.

Forward is a Pallas kernel (grid over batch·heads × query blocks; inner scan
over KV blocks with running max/denominator in VMEM scratch). Backward uses
recompute: jax.custom_vjp replays the jnp reference composition under remat,
so residual memory is O(S·D) not O(S²) — XLA fuses the replayed backward into
two matmul chains, which is the right TPU tradeoff (backward flash kernels
win mainly when HBM-bound; revisit after profiling).

Falls back to the jnp composition on non-TPU backends (CPU tests).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _ref_bhsd(q, k, v, causal: bool, scale: float):
    """Reference composition, (B, H, S, D) layout, fp32 softmax. GQA: k/v may
    have Hkv | H heads."""
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum("bhsd,bhtd->bhst", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bhtd->bhsd", probs, v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k, seq_k):
    """One (batch·head, q-block) program: stream KV blocks, online softmax."""
    from jax.experimental import pallas as pl

    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    block_q = q.shape[0]
    d = q.shape[-1]
    q_blk = pl.program_id(1)

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = seq_k // block_k

    def body(i, carry):
        m, l, acc = carry
        k = pl.load(k_ref, (0, pl.dslice(i * block_k, block_k), slice(None))
                    ).astype(jnp.float32)
        v = pl.load(v_ref, (0, pl.dslice(i * block_k, block_k), slice(None))
                    ).astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            q_pos = q_blk * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = i * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only stream blocks up to (and including) the diagonal
        last = (q_blk + 1) * block_q
        n_needed = (last + block_k - 1) // block_k
        upper = jnp.minimum(n_needed, num_k_blocks)
    else:
        upper = num_k_blocks
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def _flash_fwd_bhsd(q, k, v, causal: bool, scale: float, block_q: int = 128,
                    block_k: int = 128):
    """GQA-native: k/v may have fewer heads (Hkv | Hq); the kv BlockSpec
    index map routes each q head to its shared kv head — zero HBM copies
    (the reference materializes repeated KV; ref fmha_ref.h)."""
    from jax.experimental import pallas as pl

    B, H, Sq, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    Sk = k.shape[2]
    bq = min(block_q, Sq)
    bk = min(block_k, Sk)
    q_r = q.reshape(B * H, Sq, D)
    k_r = k.reshape(B * Hkv, Sk, D)
    v_r = v.reshape(B * Hkv, Sk, D)

    def kv_index(b, i):
        return (b // H) * Hkv + (b % H) // rep, 0, 0

    grid = (B * H, Sq // bq)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, block_k=bk, seq_k=Sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), kv_index),
            pl.BlockSpec((1, Sk, D), kv_index),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
    )(q_r, k_r, v_r)
    return out.reshape(B, H, Sq, D)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    """(B, H, S, D) flash attention. scale defaults to 1/sqrt(D)."""
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if jax.default_backend() in ("tpu", "axon"):
        try:
            return _flash_fwd_bhsd(q, k, v, causal, s)
        except Exception:
            pass
    return _ref_bhsd(q, k, v, causal, s)


def _fa_fwd(q, k, v, causal, scale):
    out = flash_attention(q, k, v, causal, scale)
    return out, (q, k, v)


def _fa_bwd(causal, scale, res, g):
    q, k, v = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    # recompute-based backward: grad of the reference composition (XLA fuses)
    _, vjp_fn = jax.vjp(lambda q_, k_, v_: _ref_bhsd(q_, k_, v_, causal, s), q, k, v)
    return vjp_fn(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def flash_attention_bshd(q, k, v, causal=False, scale=None):
    """Paddle head layout (B, S, H, D) wrapper. GQA-aware: k/v may carry
    fewer heads (Hkv | Hq) — the kernel routes q heads to shared kv heads via
    its index map, no repeat."""
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    out = flash_attention(qh, kh, vh, causal, scale)
    return jnp.swapaxes(out, 1, 2)
