"""Flash attention (Pallas TPU kernels).

Replaces the reference's CUDA FMHA stack (ref
paddle/fluid/operators/fused/fused_attention_op.cu, fmha_ref.h,
fused_softmax_mask kernels) with blockwise online-softmax kernels that never
materialise the S×S score matrix in HBM.

Kernel structure: 3-axis grids with the KV (resp. Q) block dimension as the
innermost "arbitrary" axis and fp32 VMEM scratch accumulators — KV streams
through VMEM block-by-block (Mosaic double-buffers the grid axis), so
sequence length is bounded by HBM, not by a resident full-K block. Forward
also emits per-row logsumexp; backward is the standard flash pair (dQ kernel
streaming KV; dK/dV kernel streaming Q/dO) using the saved LSE and
delta = rowsum(dO·O) precomputed by XLA. Causal variants skip fully-masked
blocks via pl.when (~2x at long S) and handle Sq != Sk with bottom-right
alignment. GQA: q heads route to shared kv heads through the BlockSpec index
map — no HBM repeat of K/V.

Falls back to the jnp composition on non-TPU backends (CPU tests); set
PT_FLASH_INTERPRET=1 to exercise the Pallas kernels in interpreter mode on
CPU.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _use_pallas() -> bool:
    # Delegates to the shared dispatch helper in ops/__init__ (one env-flag
    # contract for flash, paged, and LoRA kernels). Kept under its old name:
    # fused_adamw and the TPU suite import it from here.
    from . import use_pallas

    return use_pallas()


def _interpret() -> bool:
    from . import pallas_interpret

    return pallas_interpret()


def _vma_of(*arrays):
    """Union of varying-mesh-axes of traced inputs (shard_map check_vma):
    pallas out_shapes must declare how outputs vary across mesh axes."""
    vma = frozenset()
    for a in arrays:
        try:
            vma = vma | jax.typeof(a).vma
        except Exception:
            pass
    return vma


def _sds(shape, dtype, vma):
    return jax.ShapeDtypeStruct(shape, dtype, vma=vma) if vma else \
        jax.ShapeDtypeStruct(shape, dtype)


def _ref_bhsd(q, k, v, causal: bool, scale: float):
    """Reference composition, (B, H, S, D) layout, fp32 softmax. GQA: k/v may
    have Hkv | H heads."""
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    logits = jnp.einsum("bhsd,bhtd->bhst", q, k).astype(jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(mask, logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhst,bhtd->bhsd", probs, v)


def _causal_mask(s, q_blk, kk, block_q, block_k, offs):
    q_pos = offs + q_blk * block_q + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, 0)
    k_pos = kk * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, NEG_INF)


def _lanes(x):
    """Broadcast a (rows,) vector across the 128-lane scratch dim."""
    return jnp.broadcast_to(x[:, None], (x.shape[0], 128))


def _pick_block(n: int, want: int) -> int:
    """Largest of (want, 256, 128, n) that divides n — big blocks keep the
    MXU busy (512x512 measured ~2.3x over 128x128 at S=2048 on v5e), but the
    grid needs exact tiling."""
    for b in (want, 256, 128):
        if n % b == 0:
            return min(b, n)
    return n


def _mxu(x):
    """MXU operand dtype: keep bf16/f32 native; fold f64 (x64 test mode) to
    f32 so fp32 accumulators and carries type-match."""
    return x.astype(jnp.float32) if x.dtype == jnp.float64 else x


# --------------------------------------------------------------------------- #
# forward
# --------------------------------------------------------------------------- #


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref, *,
                scale, causal, block_q, block_k, nk, seq_q, seq_k):
    """One (batch·head, q-block, k-block) program; k innermost with VMEM
    scratch (m, l, acc) carrying the online softmax across k steps."""
    from jax.experimental import pallas as pl
    scale = jnp.float32(scale)  # np.float64 scale must not promote f32 math

    q_blk = pl.program_id(1)
    kk = pl.program_id(2)
    offs = seq_k - seq_q

    @pl.when(kk == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if causal:
        needed = offs + (q_blk + 1) * block_q - 1 >= kk * block_k
    else:
        needed = True

    @pl.when(needed)
    def _compute():
        # keep matmul operands in the input dtype (bf16 hits the MXU at full
        # rate; an fp32 cast here runs ~7x slower) — fp32 only for softmax
        q = _mxu(q_ref[0])
        k = _mxu(k_ref[0])
        v = _mxu(v_ref[0])
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_blk, kk, block_q, block_k, offs)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = _lanes(l_prev * alpha + jnp.sum(p, axis=-1))
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = _lanes(m_new)

    @pl.when(kk == nk - 1)
    def _finish():
        m = m_ref[:, 0]
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m + jnp.log(l_safe)


def _flash_fwd_bhsd_stream(q, k, v, causal: bool, scale: float,
                           block_q: int = 512, block_k: int = 512):
    """GQA-native: k/v may have fewer heads (Hkv | Hq); the kv BlockSpec
    index map routes each q head to its shared kv head — zero HBM copies
    (the reference materializes repeated KV; ref fmha_ref.h)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Sq, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    Sk = k.shape[2]
    bq = _pick_block(Sq, block_q)
    bk = _pick_block(Sk, block_k)
    nk = Sk // bk
    q_r = q.reshape(B * H, Sq, D)
    k_r = k.reshape(B * Hkv, Sk, D)
    v_r = v.reshape(B * Hkv, Sk, D)

    def kv_head(b):
        return (b // H) * Hkv + (b % H) // rep

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, block_q=bq,
                          block_k=bk, nk=nk, seq_q=Sq, seq_k=Sk),
        grid=(B * H, Sq // bq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, kk: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, kk: (kv_head(b), kk, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, kk: (kv_head(b), kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, kk: (b, i, 0)),
            # (BH, 1, Sq) with a singleton sublane dim satisfies the TPU
            # (8, 128) tiling rule for 1D-per-row outputs
            pl.BlockSpec((1, 1, bq), lambda b, i, kk: (b, 0, i)),
        ],
        out_shape=[
            _sds((B * H, Sq, D), q.dtype, _vma_of(q, k, v)),
            _sds((B * H, 1, Sq), jnp.float32, _vma_of(q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),  # m (lane-replicated)
            pltpu.VMEM((bq, 128), jnp.float32),  # l (lane-replicated)
            pltpu.VMEM((bq, D), jnp.float32),    # acc
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q_r, k_r, v_r)
    return out.reshape(B, H, Sq, D), lse.reshape(B, H, Sq)


# --------------------------------------------------------------------------- #
# backward
# --------------------------------------------------------------------------- #


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_acc_ref, *, scale, causal, block_q, block_k, nk,
                   seq_q, seq_k):
    """dQ for one (batch·head, q-block): k blocks stream on the innermost
    grid axis. dS = P ∘ (dO·Vᵀ − delta); dQ = scale · dS·K."""
    from jax.experimental import pallas as pl
    scale = jnp.float32(scale)  # np.float64 scale must not promote f32 math

    q_blk = pl.program_id(1)
    kk = pl.program_id(2)
    offs = seq_k - seq_q

    @pl.when(kk == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    if causal:
        needed = offs + (q_blk + 1) * block_q - 1 >= kk * block_k
    else:
        needed = True

    @pl.when(needed)
    def _compute():
        q = _mxu(q_ref[0])
        do = _mxu(do_ref[0])
        lse = lse_ref[0, 0]
        delta = delta_ref[0, 0]
        k = _mxu(k_ref[0])
        v = _mxu(v_ref[0])
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_blk, kk, block_q, block_k, offs)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(k.dtype)
        dq_acc_ref[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _finish():
        dq_ref[0] = (dq_acc_ref[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref, *, scale, causal,
                    block_q, block_k, nq, seq_q, seq_k, rep):
    """dK/dV for one (batch·kv-head, k-block): the grid's two inner axes walk
    the ``rep`` q heads sharing this kv head, then stream q/dO blocks.
    dV = Pᵀ·dO; dK = scale · dSᵀ·Q (scale applied per-block on the dk dot).
    GQA gradients accumulate in VMEM scratch across the whole (rep, qi)
    plane — no redundant per-q-head kernel runs, no HBM rep-reduction."""
    from jax.experimental import pallas as pl
    scale = jnp.float32(scale)  # np.float64 scale must not promote f32 math

    k_blk = pl.program_id(1)
    r = pl.program_id(2)
    qi = pl.program_id(3)
    offs = seq_k - seq_q

    @pl.when(jnp.logical_and(r == 0, qi == 0))
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    if causal:
        needed = offs + (qi + 1) * block_q - 1 >= k_blk * block_k
    else:
        needed = True

    @pl.when(needed)
    def _compute():
        k = _mxu(k_ref[0])
        v = _mxu(v_ref[0])
        q = _mxu(q_ref[0, 0])
        do = _mxu(do_ref[0, 0])
        lse = lse_ref[0, 0, 0]
        delta = delta_ref[0, 0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, qi, k_blk, block_q, block_k, offs)
        p = jnp.exp(s - lse[:, None])
        dv_acc_ref[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
        dk_acc_ref[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(jnp.logical_and(r == rep - 1, qi == nq - 1))
    def _finish():
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd_bhsd_stream(q, k, v, do, lse, delta, causal: bool, scale: float,
                           block_q: int = 512, block_k: int = 512):
    """Pallas flash backward. GQA-native: dq routes kv blocks per q head (no
    HBM repeat of K/V); dk/dv accumulate over the rep q heads inside the
    kernel grid (see _bwd_dkv_kernel) — no [B,H,Sk,D] intermediate."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, Sq, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    Sk = k.shape[2]
    bq = _pick_block(Sq, block_q)
    bk = _pick_block(Sk, block_k)
    nq = Sq // bq
    nk = Sk // bk
    q_r = q.reshape(B * H, Sq, D)
    k_r = k.reshape(B * Hkv, Sk, D)
    v_r = v.reshape(B * Hkv, Sk, D)
    do_r = do.reshape(B * H, Sq, D)
    lse_r = lse.reshape(B * H, 1, Sq)
    delta_r = delta.reshape(B * H, 1, Sq)
    vma = _vma_of(q, k, v, do, lse, delta)

    def kv_head(b):
        return (b // H) * Hkv + (b % H) // rep

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nk=nk, seq_q=Sq, seq_k=Sk),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, kk: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, kk: (kv_head(b), kk, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i, kk: (kv_head(b), kk, 0)),
            pl.BlockSpec((1, bq, D), lambda b, i, kk: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i, kk: (b, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, i, kk: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, kk: (b, i, 0)),
        out_shape=_sds((B * H, Sq, D), q.dtype, vma),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q_r, k_r, v_r, do_r, lse_r, delta_r)

    q_g = q.reshape(B * Hkv, rep, Sq, D)
    do_g = do.reshape(B * Hkv, rep, Sq, D)
    lse_g = lse.reshape(B * Hkv, rep, 1, Sq)
    delta_g = delta.reshape(B * Hkv, rep, 1, Sq)
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, nq=nq, seq_q=Sq, seq_k=Sk,
                          rep=rep),
        grid=(B * Hkv, nk, rep, nq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, kb, r, qi: (b, r, qi, 0)),
            pl.BlockSpec((1, bk, D), lambda b, kb, r, qi: (b, kb, 0)),
            pl.BlockSpec((1, bk, D), lambda b, kb, r, qi: (b, kb, 0)),
            pl.BlockSpec((1, 1, bq, D), lambda b, kb, r, qi: (b, r, qi, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, kb, r, qi: (b, r, 0, qi)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, kb, r, qi: (b, r, 0, qi)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, kb, r, qi: (b, kb, 0)),
            pl.BlockSpec((1, bk, D), lambda b, kb, r, qi: (b, kb, 0)),
        ],
        out_shape=[
            _sds((B * Hkv, Sk, D), k.dtype, vma),
            _sds((B * Hkv, Sk, D), v.dtype, vma),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary",
                                 "arbitrary")),
        interpret=_interpret(),
    )(q_g, k_r, v_r, do_g, lse_g, delta_g)

    dq = dq.reshape(B, H, Sq, D)
    dk = dk.reshape(B, Hkv, Sk, D)
    dv = dv.reshape(B, Hkv, Sk, D)
    return dq, dk, dv


# --------------------------------------------------------------------------- #
# full-K fori-loop variants — faster when K/V fit VMEM (better block reuse
# than the streaming grid); dispatcher picks by Sk (see _flash_dispatch)
# --------------------------------------------------------------------------- #

def _fwd_kernel_loop(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k, seq_k, seq_q):
    """One (batch·head, q-block) program: stream KV blocks, online softmax.
    Also writes the per-row logsumexp (flash backward needs it)."""
    from jax.experimental import pallas as pl
    scale = jnp.float32(scale)  # np.float64 scale must not promote f32 math

    q = _mxu(q_ref[0])  # (block_q, d) — native dtype: bf16 operands hit the MXU at
    block_q = q.shape[0]  # full rate (fp32-cast dots run ~7x slower)
    d = q.shape[-1]
    q_blk = pl.program_id(1)

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    num_k_blocks = seq_k // block_k

    def body(i, carry):
        m, l, acc = carry
        k = _mxu(k_ref[0, pl.dslice(i * block_k, block_k), :])
        v = _mxu(v_ref[0, pl.dslice(i * block_k, block_k), :])
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            # bottom-right alignment for Sq != Sk (ref tril k=Sk-Sq)
            s = _causal_mask(s, q_blk, i, block_q, block_k, seq_k - seq_q)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    if causal:
        # only stream blocks up to (and including) the diagonal
        last = (seq_k - seq_q) + (q_blk + 1) * block_q
        n_needed = (last + block_k - 1) // block_k
        upper = jnp.minimum(n_needed, num_k_blocks)
    else:
        upper = num_k_blocks
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l_safe)


def _flash_fwd_bhsd_loop(q, k, v, causal: bool, scale: float, block_q: int = 512,
                    block_k: int = 512):
    """GQA-native: k/v may have fewer heads (Hkv | Hq); the kv BlockSpec
    index map routes each q head to its shared kv head — zero HBM copies
    (the reference materializes repeated KV; ref fmha_ref.h)."""
    from jax.experimental import pallas as pl

    B, H, Sq, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    Sk = k.shape[2]
    bq = _pick_block(Sq, block_q)
    bk = _pick_block(Sk, block_k)
    q_r = q.reshape(B * H, Sq, D)
    k_r = k.reshape(B * Hkv, Sk, D)
    v_r = v.reshape(B * Hkv, Sk, D)

    def kv_index(b, i):
        return (b // H) * Hkv + (b % H) // rep, 0, 0

    grid = (B * H, Sq // bq)
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel_loop, scale=scale, causal=causal, block_k=bk,
                          seq_k=Sk, seq_q=Sq),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), kv_index),
            pl.BlockSpec((1, Sk, D), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            # (BH, 1, Sq) with a singleton sublane dim satisfies the TPU
            # (8, 128) tiling rule for 1D-per-row outputs
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),
        ],
        out_shape=[
            _sds((B * H, Sq, D), q.dtype, _vma_of(q, k, v)),
            _sds((B * H, 1, Sq), jnp.float32, _vma_of(q, k, v)),
        ],
        interpret=_interpret(),
    )(q_r, k_r, v_r)
    return out.reshape(B, H, Sq, D), lse.reshape(B, H, Sq)


def _bwd_dq_kernel_loop(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
                   scale, causal, block_k, seq_k, seq_q):
    """dQ for one (batch·head, q-block): stream KV, use saved LSE.
    dS = P ∘ (dO·Vᵀ − delta); dQ = scale · dS·K  (flash-attention backward)."""
    from jax.experimental import pallas as pl
    scale = jnp.float32(scale)  # np.float64 scale must not promote f32 math

    q = _mxu(q_ref[0])
    do = _mxu(do_ref[0])
    lse = lse_ref[0, 0]
    delta = delta_ref[0, 0]
    block_q, d = q.shape
    q_blk = pl.program_id(1)
    num_k_blocks = seq_k // block_k

    def body(i, dq_acc):
        k = _mxu(k_ref[0, pl.dslice(i * block_k, block_k), :])
        v = _mxu(v_ref[0, pl.dslice(i * block_k, block_k), :])
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, q_blk, i, block_q, block_k, seq_k - seq_q)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(k.dtype)
        return dq_acc + jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    if causal:
        last = (seq_k - seq_q) + (q_blk + 1) * block_q
        upper = jnp.minimum((last + block_k - 1) // block_k, num_k_blocks)
    else:
        upper = num_k_blocks
    dq = jax.lax.fori_loop(0, upper, body,
                           jnp.zeros((block_q, d), jnp.float32))
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel_loop(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, seq_q, seq_k):
    """dK/dV for one (batch·head, k-block): stream Q/dO blocks.
    dV = Pᵀ·dO; dK = scale · dSᵀ·Q.

    GQA note: this full-K form runs per *query* head and reduces dk/dv over
    the rep group afterwards. Folding the rep axis into the grid (as the
    stream form does) was measured 2026-07: rep-innermost refetches the full
    Sq·D q/dO slab Sk/bk times — a net HBM regression; rep-outermost breaks
    the consecutive-revisit rule for the output accumulator. The redundant
    [B,H,Sk,D] intermediate is ~16 MB at the S≤8192 sizes this form serves."""
    from jax.experimental import pallas as pl
    scale = jnp.float32(scale)  # np.float64 scale must not promote f32 math

    k = _mxu(k_ref[0])
    v = _mxu(v_ref[0])
    block_k, d = k.shape
    k_blk = pl.program_id(1)
    num_q_blocks = seq_q // block_q

    def body(i, carry):
        dk_acc, dv_acc = carry
        q = _mxu(q_ref[0, pl.dslice(i * block_q, block_q), :])
        do = _mxu(do_ref[0, pl.dslice(i * block_q, block_q), :])
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q)]
        delta = delta_ref[0, 0, pl.dslice(i * block_q, block_q)]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = _causal_mask(s, i, k_blk, block_q, block_k, seq_k - seq_q)
        p = jnp.exp(s - lse[:, None])
        dv_new = dv_acc + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta[:, None])).astype(q.dtype)
        dk_new = dk_acc + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        return dk_new, dv_new

    if causal:
        # first q block that can see this k block (bottom-right aligned)
        lower = jnp.maximum(k_blk * block_k - (seq_k - seq_q), 0) // block_q
    else:
        lower = 0
    dk, dv = jax.lax.fori_loop(lower, num_q_blocks, body,
                               (jnp.zeros((block_k, d), jnp.float32),
                                jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)  # scale applied per-block in body
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_bhsd_loop(q, k, v, do, lse, delta, causal: bool, scale: float,
                    block_q: int = 512, block_k: int = 512):
    """Pallas flash backward. GQA: dk/dv are computed per q-head with the
    same kv BlockSpec routing as forward (no HBM repeat of K/V), then summed
    over the rep group."""
    from jax.experimental import pallas as pl

    B, H, Sq, D = q.shape
    Hkv = k.shape[1]
    rep = H // Hkv
    Sk = k.shape[2]
    bq = _pick_block(Sq, block_q)
    bk = _pick_block(Sk, block_k)
    q_r = q.reshape(B * H, Sq, D)
    k_r = k.reshape(B * Hkv, Sk, D)
    v_r = v.reshape(B * Hkv, Sk, D)
    do_r = do.reshape(B * H, Sq, D)
    lse_r = lse.reshape(B * H, 1, Sq)
    delta_r = delta.reshape(B * H, 1, Sq)

    def kv_index(b, i):
        return (b // H) * Hkv + (b % H) // rep, 0, 0

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel_loop, scale=scale, causal=causal,
                          block_k=bk, seq_k=Sk, seq_q=Sq),
        grid=(B * H, Sq // bq),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Sk, D), kv_index),
            pl.BlockSpec((1, Sk, D), kv_index),
            pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),
            pl.BlockSpec((1, 1, bq), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i: (b, i, 0)),
        out_shape=_sds((B * H, Sq, D), q.dtype,
                       _vma_of(q, k, v, do, lse, delta)),
        interpret=_interpret(),
    )(q_r, k_r, v_r, do_r, lse_r, delta_r)

    dk_h, dv_h = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel_loop, scale=scale, causal=causal,
                          block_q=bq, seq_q=Sq, seq_k=Sk),
        grid=(B * H, Sk // bk),
        in_specs=[
            pl.BlockSpec((1, Sq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i: (kv_index(b, i)[0], i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i: (kv_index(b, i)[0], i, 0)),
            pl.BlockSpec((1, Sq, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, Sq), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, 1, Sq), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, bk, D), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            _sds((B * H, Sk, D), k.dtype, _vma_of(q, k, v, do, lse, delta)),
            _sds((B * H, Sk, D), v.dtype, _vma_of(q, k, v, do, lse, delta)),
        ],
        interpret=_interpret(),
    )(q_r, k_r, v_r, do_r, lse_r, delta_r)

    dq = dq.reshape(B, H, Sq, D)
    dk = dk_h.reshape(B, Hkv, rep, Sk, D).sum(axis=2).astype(k.dtype)
    dv = dv_h.reshape(B, Hkv, rep, Sk, D).sum(axis=2).astype(v.dtype)
    return dq, dk, dv



# K/V longer than this stream block-by-block through the 3-axis grid; below
# it the full-K loop kernels win (K/V stay resident in VMEM across q blocks)
_FULL_K_MAX = 8192


#: per-sequence-length-regime (block_q, block_k) defaults — populated from
#: tools/bench_flash_sweep.py winners measured on a real v5e chip
#: (2026-07-31, dispatch-chain differencing).  Key = max seq len of the
#: regime (entries ascending).  Forward and backward want DIFFERENT blocks:
#: at S=2048 GQA the fwd kernel runs 1.2-1.9 ms at 512x1024 vs 2.05 ms at
#: 512x512, while fwd+bwd is fastest with the bwd kernel at 512x512
#: (4.57 ms vs 4.98 ms uniform 512x1024) — so the tables are split.
#: S=16384 streaming regime: 1024x1024 measured 7.47 ms fwd (147 TFLOP/s,
#: 75% of v5e peak) and 32.3 ms fwd+bwd — best for both directions.  The
#: 8192 boundary (largest shape still on the full-K LOOP kernels, see
#: _FULL_K_MAX) was swept separately: 512x512 wins both directions there
#: (5.02 ms fwd / 18.6 ms fwd+bwd at B=2; 1024x1024 fails on the loop
#: kernels' VMEM residency).
_BLOCK_REGIMES_FWD = {
    4096: (512, 1024),
    8192: (512, 512),
    16384: (1024, 1024),
}
#: MHA (KV == H, GPT family) wants smaller K blocks at short S than GQA:
#: measured 2026-07-31 on v5e, H16/KV16 S=2048 fwd — 512x512 at 2.05 ms
#: (pairwise median) vs 8% slower at the GQA winner 512x1024.  Long-S
#: entries inherit the GQA table (the streaming regime is
#: head-ratio-insensitive), so retunes there propagate automatically.
_BLOCK_REGIMES_FWD_MHA = {**_BLOCK_REGIMES_FWD, 4096: (512, 512)}
_BLOCK_REGIMES_BWD = {
    4096: (512, 512),
    8192: (512, 512),
    16384: (1024, 1024),
}


def _block_defaults(seq_len: int = 0, kind: str = "fwd", mha: bool = False):
    """Tuning knobs per shape regime (benchmarked via bench.py A/B and
    tools/bench_flash_sweep.py).  Override order: PT_FLASH_BLOCK_Q/K
    (global, both directions) > PT_FLASH_BLOCKS (forward ONLY) /
    PT_FLASH_BLOCKS_BWD (backward ONLY) regime maps
    ("4096:512x512,16384:1024x512") > the split _BLOCK_REGIMES_FWD/_BWD
    tables, with the forward table keyed on the KV/H ratio (MHA gets its
    own measured column — tables exist so users don't need env overrides).
    The fwd env var deliberately does NOT leak into the backward kernel:
    adopting a fwd-sweep winner must not undo the measured bwd default
    (bwd prefers smaller K blocks than fwd on every swept shape)."""
    import os

    if os.environ.get("PT_FLASH_BLOCK_Q") or os.environ.get("PT_FLASH_BLOCK_K"):
        return (int(os.environ.get("PT_FLASH_BLOCK_Q", 512)),
                int(os.environ.get("PT_FLASH_BLOCK_K", 512)))
    regimes = dict(_BLOCK_REGIMES_BWD if kind == "bwd" else
                   (_BLOCK_REGIMES_FWD_MHA if mha else _BLOCK_REGIMES_FWD))
    env_map = os.environ.get(
        "PT_FLASH_BLOCKS_BWD" if kind == "bwd" else "PT_FLASH_BLOCKS")
    if env_map:
        try:
            for part in env_map.split(","):
                s, blocks = part.split(":")
                bq, bk = blocks.lower().split("x")
                regimes[int(s)] = (int(bq), int(bk))
        except ValueError:
            pass  # malformed override: keep the table
    for cap in sorted(regimes):
        if seq_len <= cap:
            return regimes[cap]
    return regimes[max(regimes)]


def _geometry_blocks(q):
    """Profile-resolved FlashAttentionGeometry override, consulted at
    trace time when the caller left block_q/block_k unset. Precedence:
    explicit args > PT_FLASH_BLOCK_Q/K and PT_FLASH_BLOCKS env overrides
    > the winner cache > the measured regime tables. Forward only — a
    fwd-swept winner must not undo the measured bwd defaults (same rule
    the env vars follow). Zero fields mean "no opinion" and fall through
    to the tables; ``_pick_block`` still clamps onto the shape."""
    import os

    if (os.environ.get("PT_FLASH_BLOCK_Q")
            or os.environ.get("PT_FLASH_BLOCK_K")
            or os.environ.get("PT_FLASH_BLOCKS")):
        return None, None
    from ..autotune.kernel_geometry import (active_geometry_cache,
                                            resolve_geometry)

    if active_geometry_cache() is None:
        return None, None
    geom, src = resolve_geometry("flash_attention", str(q.dtype), q.shape[3])
    if src == "default":
        return None, None
    return geom.block_q or None, geom.block_kv or None


def _flash_fwd_bhsd(q, k, v, causal, scale, block_q=None, block_k=None):
    if block_q is None and block_k is None:
        block_q, block_k = _geometry_blocks(q)
    dq, dk = _block_defaults(k.shape[2], mha=k.shape[1] == q.shape[1])
    block_q, block_k = block_q or dq, block_k or dk
    if k.shape[2] <= _FULL_K_MAX:
        return _flash_fwd_bhsd_loop(q, k, v, causal, scale, block_q, block_k)
    return _flash_fwd_bhsd_stream(q, k, v, causal, scale, block_q, block_k)


def _flash_bwd_bhsd(q, k, v, do, lse, delta, causal, scale,
                    block_q=None, block_k=None):
    dq, dk = _block_defaults(k.shape[2], kind="bwd")
    block_q, block_k = block_q or dq, block_k or dk
    if k.shape[2] <= _FULL_K_MAX:
        return _flash_bwd_bhsd_loop(q, k, v, do, lse, delta, causal, scale,
                                    block_q, block_k)
    return _flash_bwd_bhsd_stream(q, k, v, do, lse, delta, causal, scale,
                                  block_q, block_k)


# --------------------------------------------------------------------------- #
# public custom-vjp entry points
# --------------------------------------------------------------------------- #


def _pallas_shapes_ok(q, k) -> bool:
    Sq, Sk = q.shape[2], k.shape[2]
    return Sq % min(128, Sq) == 0 and Sk % min(128, Sk) == 0


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention(q, k, v, causal=False, scale=None):
    """(B, H, S, D) flash attention. scale defaults to 1/sqrt(D)."""
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if _use_pallas() and _pallas_shapes_ok(q, k):
        try:
            return _flash_fwd_bhsd(q, k, v, causal, s)[0]
        except Exception:
            pass
    return _ref_bhsd(q, k, v, causal, s)


def _fa_fwd(q, k, v, causal, scale):
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if _use_pallas() and _pallas_shapes_ok(q, k):
        try:
            out, lse = _flash_fwd_bhsd(q, k, v, causal, s)
            return out, (q, k, v, out, lse)
        except Exception:
            pass
    return _ref_bhsd(q, k, v, causal, s), (q, k, v, None, None)


def _fa_bwd(causal, scale, res, g):
    q, k, v, out, lse = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if lse is not None:
        delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1)  # rowsum(dO·O); XLA fuses this reduction
        try:
            return _flash_bwd_bhsd(q, k, v, g, lse, delta, causal, s)
        except Exception:
            pass
    # fallback: grad of the reference composition (XLA fuses)
    _, vjp_fn = jax.vjp(lambda q_, k_, v_: _ref_bhsd(q_, k_, v_, causal, s), q, k, v)
    return vjp_fn(g)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def flash_attention_bshd(q, k, v, causal=False, scale=None):
    """Paddle head layout (B, S, H, D) wrapper. GQA-aware: k/v may carry
    fewer heads (Hkv | Hq) — the kernel routes q heads to shared kv heads via
    its index map, no repeat."""
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    out = flash_attention(qh, kh, vh, causal, scale)
    return jnp.swapaxes(out, 1, 2)
