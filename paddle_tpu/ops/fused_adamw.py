"""Fused Adam/AdamW parameter update — one Pallas pass over HBM.

Why: profiling the 509M bench step on a real v5e (2026-07-31, Chrome trace
via jax.profiler) showed XLA's per-tensor `subtract_convert_fusion`
optimizer updates taking ~102 ms of a 377 ms train step — ~12x the
~0.3 ms/tensor HBM bound for what is a purely bandwidth-limited
elementwise pass (read p/g/m/v, write p/m/v).  This kernel streams
(block_k, block_n) tiles through VMEM once and emits all three (or four,
with a master weight) outputs from the same pass.

Reference analogue: the fused Adam/AdamW CUDA kernels
(ref paddle/phi/kernels/gpu/adamw_kernel.cu, fused multi-tensor adam) —
on TPU the fusion is a Pallas elementwise kernel instead of a
multi-tensor CUDA launch.

Semantics match `AdamW._apply_adamw` / `Adam._apply_one` exactly
(decoupled decay applied to the master/param BEFORE the moment update,
bias correction by traced step count).  `_reference_update` is the source
of truth for the XLA fallback and the tests; the kernel body re-expresses
the same math with the bias corrections precomputed (Mosaic cannot
legalize powf with a traced exponent) — edits to the update rule must
touch BOTH, and the interpreted test pins them together.

Measured outcome (2026-07-31, same-window A/B on the 509M bench step):
fused 0.6344 MFU vs unfused 0.6727 — the fused kernel is ~6% SLOWER end
to end despite each XLA update fusion running ~12x its isolated HBM
bound, because XLA *overlaps* those per-tensor updates with backward
compute (trace: 430 ms of device-op time inside a 377 ms step) and ~50
custom calls break that overlap.  The kernel is therefore OPT-IN ONLY
(PT_FUSED_ADAMW=1); the default path stays on XLA's fusions.

The overlap-preserving candidate — ONE multi-tensor launch for all params
(flat_adamw_update + AdamW PT_MT_ADAMW=1, the reference's
multi_tensor_adam / distributed_fused_lamb.py design) — was built and
measured round 4 (2026-07-31, bracketed same-window A/B, identical loss):
default 0.6755 / 0.6756 MFU vs flat 0.5911 / 0.5916.  It loses ~12.5%:
the single launch can only start after the LAST gradient exists, adding
~53 ms of serialized grad-concat + flat-kernel + param-split traffic
(~36 B/param ≈ 18 GB at 509M) to a 376 ms step, while XLA's per-tensor
updates cost ~nothing on the critical path because they overlap backward.
CONCLUSION (thread closed): on TPU + XLA, optimizer updates are not a
launch-count problem — scheduling beats fusion.  Both kernels stay
opt-in for profiling; the default path is XLA's overlapped fusions.

Sharding caveat: a pallas_call is not GSPMD-partitionable, so inside a
pjit over a multi-device mesh it would force a gather of the (possibly
ZeRO-sharded) optimizer state — another reason the kernel never
self-enables.
"""
from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

_LANE = 128
_SUBLANE = 8
_WARNED_FALLBACK = False


def _use_pallas() -> bool:
    from .flash_attention import _use_pallas as f

    return f()


def _interpret() -> bool:
    from .flash_attention import _interpret as f

    return f()


def usable(shape) -> bool:
    if os.environ.get("PT_FUSED_ADAMW") != "1":
        return False  # opt-in only; measured slower than XLA's overlapped
        # per-tensor fusions on the full train step (see module docstring)
    if jax.device_count() != 1 and not _interpret():
        return False  # non-partitionable custom call would gather
        # ZeRO-sharded state under a multi-device pjit (interpret mode is
        # the CPU-CI seam and exempt: it never runs on real sharded state)
    return (_use_pallas() and len(shape) == 2 and
            shape[0] % _SUBLANE == 0 and shape[1] % _LANE == 0)


def _reference_update(param_f32, grad_f32, m, v, lr, b1, b2, eps, decay,
                      step):
    """The exact Adam(W) math both paths implement.  ``decay=0`` is plain
    Adam; ``param_f32`` is the master weight (or the upcast param)."""
    master = param_f32 * (1.0 - lr * decay)
    m2 = b1 * m + (1.0 - b1) * grad_f32
    v2 = b2 * v + (1.0 - b2) * grad_f32 * grad_f32
    mhat = m2 / (1.0 - b1 ** step)
    vhat = v2 / (1.0 - b2 ** step)
    new_master = master - lr * mhat / (jnp.sqrt(vhat) + eps)
    return new_master, m2, v2


def _make_kernel(b1, b2, eps, decay, has_master):
    # the bias corrections 1/(1 - beta**step) arrive precomputed in the
    # scalar block: Mosaic cannot legalize powf with a traced exponent
    def kernel(*refs):
        if has_master:
            (sc_ref, p_ref, g_ref, m_ref, v_ref, mw_ref,
             po_ref, mo_ref, vo_ref, mwo_ref) = refs
            pf = mw_ref[...]
        else:
            (sc_ref, p_ref, g_ref, m_ref, v_ref,
             po_ref, mo_ref, vo_ref) = refs
            pf = p_ref[...].astype(jnp.float32)
        lr = sc_ref[0, 0]
        inv_bc1 = sc_ref[0, 1]
        inv_bc2 = sc_ref[0, 2]
        gf = g_ref[...].astype(jnp.float32)
        master = pf * (1.0 - lr * decay)
        m2 = b1 * m_ref[...] + (1.0 - b1) * gf
        v2 = b2 * v_ref[...] + (1.0 - b2) * gf * gf
        mhat = m2 * inv_bc1
        vhat = v2 * inv_bc2
        new_master = master - lr * mhat / (jnp.sqrt(vhat) + eps)
        po_ref[...] = new_master.astype(po_ref.dtype)
        mo_ref[...] = m2
        vo_ref[...] = v2
        if has_master:
            mwo_ref[...] = new_master
    return kernel


def _pick(dim: int, target: int, unit: int) -> int:
    b = min(target, dim)
    while dim % b:
        b -= unit
        if b < unit:
            return dim
    return b


@functools.partial(jax.jit, static_argnames=("b1", "b2", "eps", "decay",
                                             "has_master"))
def _fused_call(param, grad, m, v, master, scalars, b1, b2, eps, decay,
                has_master):
    from jax.experimental import pallas as pl

    K, N = param.shape
    bn = _pick(N, 512, _LANE)
    # working set ~30 bytes/elem (f32 grad) x2 double buffering must stay
    # well under the 16M scoped-vmem limit
    bk = _pick(K, max(_SUBLANE, (3 * 1024 * 1024 // (30 * bn))
                      // _SUBLANE * _SUBLANE), _SUBLANE)
    grid = (K // bk, N // bn)
    tile = pl.BlockSpec((bk, bn), lambda i, j: (i, j))
    sc = pl.BlockSpec((1, 4), lambda i, j: (0, 0))

    ins = [scalars, param, grad, m, v]
    in_specs = [sc, tile, tile, tile, tile]
    outs = [jax.ShapeDtypeStruct((K, N), param.dtype),
            jax.ShapeDtypeStruct((K, N), jnp.float32),
            jax.ShapeDtypeStruct((K, N), jnp.float32)]
    out_specs = [tile, tile, tile]
    if has_master:
        ins.append(master)
        in_specs.append(tile)
        outs.append(jax.ShapeDtypeStruct((K, N), jnp.float32))
        out_specs.append(tile)
    return pl.pallas_call(
        _make_kernel(b1, b2, eps, decay, has_master),
        grid=grid, in_specs=in_specs, out_specs=out_specs, out_shape=outs,
        interpret=_interpret(),
    )(*ins)


def multi_tensor_usable(shape) -> bool:
    """The FLAT multi-tensor apply has its own knob (PT_MT_ADAMW, read by
    the optimizer) — this only checks kernel viability: TPU backend, tiled
    2-D shape, single device (a pallas custom call is not
    GSPMD-partitionable; interpret mode is the CPU-CI seam)."""
    return (_use_pallas() and len(shape) == 2 and
            shape[0] % _SUBLANE == 0 and shape[1] % _LANE == 0 and
            (jax.device_count() == 1 or _interpret()))


def flat_adamw_update(param, grad, m, v, *, lr, step, b1, b2, eps, decay
                      ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """ONE kernel launch updating the whole model: operands are the
    CONCATENATED flat (K, N) views of every trainable tensor (built once by
    the optimizer; zero-padded tail rows are fixed points of the update).

    This is the overlap-preserving alternative the round-3 per-tensor
    experiment identified (ref distributed_fused_lamb.py / multi_tensor_adam
    — the reference's multi-tensor precedent): ~50 per-tensor custom calls
    broke XLA's backward/update overlap; a single launch pays one
    serialization point and streams all state at the HBM roofline.
    Falls back to the identical XLA math off-TPU (CPU tests train through
    this path bit-compatibly).
    """
    param = jnp.asarray(param)
    grad = jnp.asarray(grad)
    if multi_tensor_usable(param.shape):
        try:
            step_f = jnp.asarray(step, jnp.float32)
            scalars = jnp.stack(
                [jnp.asarray(lr, jnp.float32),
                 1.0 / (1.0 - jnp.asarray(b1, jnp.float32) ** step_f),
                 1.0 / (1.0 - jnp.asarray(b2, jnp.float32) ** step_f),
                 jnp.float32(0.0)]).reshape(1, 4)
            out = _fused_call(param, grad, m, v, None, scalars,
                              float(b1), float(b2), float(eps), float(decay),
                              False)
            return out[0], out[1], out[2]
        except Exception as e:  # noqa: BLE001 — Mosaic raises many types
            global _WARNED_FALLBACK
            if not _WARNED_FALLBACK:
                import warnings

                warnings.warn(
                    f"flat_adamw: kernel failed ({type(e).__name__}: {e}); "
                    f"running the XLA fallback", RuntimeWarning)
                _WARNED_FALLBACK = True
    new_master, m2, v2 = _reference_update(
        param.astype(jnp.float32), grad.astype(jnp.float32), m, v, lr, b1,
        b2, eps, decay, step)
    return new_master.astype(param.dtype), m2, v2


def fused_adamw_update(param, grad, m, v, *, lr, step, b1, b2, eps,
                       decay, master: Optional[jax.Array] = None
                       ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                  Optional[jax.Array]]:
    """(new_param, new_m, new_v, new_master|None); falls back to the XLA
    elementwise path off-TPU / on unsupported shapes / multi-device.

    Caveat: only TRACE-time kernel failures are caught here.  When the
    pallas_call is traced inside an outer jit (the engine train step), a
    Mosaic failure surfaces at that outer compile and propagates — with
    the opt-in flag set, a loud error beats silently benchmarking the
    wrong path.

    ``grad`` is consumed in float32 either way (the kernel upcasts
    internally), so both paths compute identical math.
    """
    param = jnp.asarray(param)
    grad = jnp.asarray(grad)
    if usable(param.shape):
        try:
            step_f = jnp.asarray(step, jnp.float32)
            scalars = jnp.stack(
                [jnp.asarray(lr, jnp.float32),
                 1.0 / (1.0 - jnp.asarray(b1, jnp.float32) ** step_f),
                 1.0 / (1.0 - jnp.asarray(b2, jnp.float32) ** step_f),
                 jnp.float32(0.0)]).reshape(1, 4)
            res = _fused_call(param, grad, m, v, master, scalars,
                              float(b1), float(b2), float(eps), float(decay),
                              master is not None)
            if master is not None:
                return res[0], res[1], res[2], res[3]
            return res[0], res[1], res[2], None
        except Exception as e:  # noqa: BLE001 — Mosaic raises many types
            global _WARNED_FALLBACK
            if not _WARNED_FALLBACK:
                import warnings

                warnings.warn(
                    f"fused_adamw: PT_FUSED_ADAMW=1 but the kernel failed "
                    f"({type(e).__name__}: {e}); running the XLA fallback — "
                    f"any 'fused' A/B label on this run is wrong",
                    RuntimeWarning)
                _WARNED_FALLBACK = True
    pf = master if master is not None else param.astype(jnp.float32)
    # scalars stay in the caller's types (python floats in eager mode) so
    # the fallback is bit-identical to the pre-fusion XLA path
    new_master, m2, v2 = _reference_update(
        pf, grad.astype(jnp.float32), m, v, lr, b1, b2, eps, decay, step)
    return (new_master.astype(param.dtype), m2, v2,
            new_master if master is not None else None)
