"""Whole-tick decode megakernel: every transformer layer of one decode (or
speculative-verify) tick as ONE persistent Pallas program.

The per-layer kernels (paged_attention_pallas.py) already fuse attention
over the paged pool, but a tick is still N separately-launched XLA
programs and the residual stream makes 2N HBM round trips per trip. MPK
(PAPERS.md) shows the fix: fuse ACROSS layers into one persistent kernel.
This module is that kernel for the serving hot path —

- the layer schedule is the kernel's own instruction stream: the Pallas
  grid is degenerate (one program instance) and the layer loop unrolls
  inside the kernel body, because the paged KV pools must stay the
  executor's flat list of per-layer, separately-donatable HBM buffers
  (stacking them into one grid-indexable array would copy the whole KV
  cache every trip). The double-buffered DMA pipeline below does by hand
  what a grid's automatic pipelining would otherwise do;
- activations (residual stream, q/k/v, attention context) live in VMEM
  scratch across ALL layers — the residual never touches HBM mid-tick;
- per-layer weights stay in HBM (``memory_space=ANY``) and stream
  HBM→VMEM with ``prefetch_depth``-deep double buffering, one chunk per
  layer (FFN weights optionally tiled along the intermediate dim by
  ``ffn_tile`` so a layer's MLP weights never need to fit VMEM at once);
- paged KV lookups walk the block table exactly like the per-layer
  kernel: the (B, M) table rides in SMEM and each context block is a
  manual double-buffered DMA ``pool.at[tbl[b, m]] → VMEM tile``, the
  scalar-prefetch idiom without a grid;
- the int8 KV path DMAs the code pool + per-(block, kv-head) scales and
  dequantizes on the VMEM tile (``dequant="scores"`` mirrors the
  reference order: k-scale on the fp32 QK accumulator, v-scale folded
  into the probabilities); KV WRITES reproduce ``_insert_token_q``'s
  whole-block requantization in-kernel (read block → insert token →
  absmax → re-code → write back);
- the fused LoRA BGMV delta is applied per batch row right after each
  base projection, factors streamed per layer like the weights.

Numerics mirror ``ops/paged_attention.py`` / ``models/llama.py`` closely
enough that greedy decode tokens are IDENTICAL to ``kernels="reference"``
(the online softmax is ~1e-6 off the two-pass reference, same as the
per-layer kernel); tests/test_megakernel.py pins token identity for
fp/int8/±LoRA/±spec.

Geometry (tile sizes, prefetch depth, dequant placement) is DATA — a
:class:`MegakernelGeometry` the autotuner can search (autotune/space.py
registers the knobs with VMEM-budget validity arithmetic).

Dispatch is the third rung of the ``ops`` kernel contract:
``set_kernel_mode("megakernel")`` → the executor routes decode and
spec-verify through :func:`decode_tick`; shape guards raise
``NotImplementedError`` and the caller falls back to the per-layer
Pallas kernels (``use_pallas()`` stays True under megakernel mode), which
themselves fall back to the jnp reference.
"""
from __future__ import annotations

import dataclasses
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
_QEPS = 1e-8   # scale floor — must match paged_attention._QEPS exactly

# jax >= 0.5 renamed TPUCompilerParams -> CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams

# canonical stream order for LoRA targets inside the kernel (subset used
# follows the adapter pool's configured targets)
LORA_TARGETS = ("q", "k", "v", "o", "gate", "up", "down")

DEQUANT_MODES = ("scores", "tile")


def _interpret() -> bool:
    from . import pallas_interpret

    return pallas_interpret()


def _lanes(x):
    """(rows,) → (rows, 128): keep running max/sum scratch in a TPU-native
    lanes-broadcast layout (same idiom as the per-layer kernel)."""
    return jnp.broadcast_to(x[:, None], (x.shape[0], 128))


# --------------------------------------------------------------- geometry
@dataclasses.dataclass(frozen=True)
class MegakernelGeometry:
    """The megakernel's tunable schedule, expressed as data.

    ``ffn_tile``: tile width along the FFN intermediate dim — 0 streams
    each layer's full gate/up/down weights as one chunk (default; keeps
    the down-projection contraction order identical to the reference),
    >0 streams ``ffn_tile``-wide column/row tiles and accumulates the
    down-projection partials in fp32 (bounds VMEM for big MLPs; not
    combinable with LoRA — the delta needs the full intermediate dim).

    ``prefetch_depth``: weight-stream lookahead in chunks (VMEM buffers
    per stream). 1 = no overlap, 2 = classic double buffering.

    ``dequant``: where the int8 KV scales land — ``"scores"`` applies
    k-scale to the fp32 QK accumulator and folds v-scale into the
    probabilities (the reference/per-layer-kernel order, token-exact vs
    ``kernels="reference"``), ``"tile"`` dequantizes the whole VMEM tile
    before the matmuls (one multiply per element, different rounding —
    NOT token-pinned).
    """

    ffn_tile: int = 0
    prefetch_depth: int = 2
    dequant: str = "scores"

    def validate(self) -> None:
        if self.ffn_tile < 0:
            raise ValueError(f"ffn_tile must be >= 0, got {self.ffn_tile}")
        if not 1 <= self.prefetch_depth <= 8:
            raise ValueError("prefetch_depth must be in [1, 8], got "
                             f"{self.prefetch_depth}")
        if self.dequant not in DEQUANT_MODES:
            raise ValueError(f"dequant must be one of {DEQUANT_MODES}, "
                             f"got {self.dequant!r}")

    def vmem_bytes(self, *, hidden: int, heads: int, kv_heads: int,
                   head_dim: int, intermediate: int, layers: int,
                   batch: int, window: int, block_size: int,
                   dtype_bytes: int = 4, quantized: bool = False) -> int:
        """Worst-case VMEM residency of the kernel's scratch + VMEM
        inputs — the validity arithmetic the autotuner's ConfigSpace
        checks against the per-core VMEM budget."""
        BW = batch * window
        Hq = heads * head_dim
        KVD = kv_heads * head_dim
        T = self.ffn_tile or intermediate
        d = self.prefetch_depth
        rep = max(heads // max(kv_heads, 1), 1)
        rows = kv_heads * window * rep
        n = 0
        # VMEM inputs: x, cos, sin (f32), per-layer norm weights
        n += BW * hidden * dtype_bytes + 2 * BW * (head_dim // 2) * 4
        n += 2 * layers * hidden * dtype_bytes
        # activation scratch (xres, xn, qs, kls, vls, ao, mlp_acc f32)
        n += BW * (2 * hidden + 2 * Hq + 2 * KVD) * dtype_bytes
        n += BW * hidden * 4
        # weight stream buffers
        n += d * (hidden * Hq + 2 * hidden * KVD + Hq * hidden
                  + 2 * hidden * T + T * hidden) * dtype_bytes
        # KV read tiles (+ scales) and write staging
        kv_item = 1 if quantized else dtype_bytes
        n += 2 * 2 * block_size * kv_heads * head_dim * kv_item
        n += 2 * kv_heads * head_dim * dtype_bytes
        if quantized:
            n += 2 * 2 * kv_heads * 4
            n += block_size * kv_heads * head_dim + kv_heads * 4
        # online-softmax scratch
        n += rows * (2 * 128 + head_dim) * 4
        return n

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


# ----------------------------------------------------------- shape guards
def _check_tick_shapes(*, D: int, bs: int, Hd: int, Hq: int, KVD: int,
                       I: int, T: int) -> None:
    """Mosaic alignment on real hardware; interpret mode takes any shape.
    Raises NotImplementedError — the dispatch ladder's fall-to-pallas
    signal (same contract as paged_attention_pallas._check_tpu_shapes)."""
    if _interpret():
        return
    if D % 128 != 0:
        raise NotImplementedError(f"head_dim {D} not lane-aligned (128)")
    if bs % 8 != 0:
        raise NotImplementedError(f"block_size {bs} not sublane-aligned (8)")
    for name, dim in (("hidden", Hd), ("q_width", Hq), ("kv_width", KVD),
                      ("intermediate", I), ("ffn_tile", T)):
        if dim % 128 != 0:
            raise NotImplementedError(
                f"{name} dim {dim} not lane-aligned (128)")


def megakernel_supported(model, cfg, *, tp: int = 1, cp: int = 1,
                         block_size: int = 16,
                         geometry: Optional[MegakernelGeometry] = None,
                         lora: bool = False) -> Optional[str]:
    """Structural/shape guard for the whole-tick kernel, checked EAGERLY
    at executor construction (all shapes are static there). Returns None
    when the megakernel can serve this model, else a human-readable
    reason — the executor records it and jits the per-layer programs
    instead (megakernel → pallas → reference, no error)."""
    geometry = geometry or MegakernelGeometry()
    geometry.validate()
    if tp > 1 or cp > 1:
        return f"multi-chip serving (tp={tp}, cp={cp}) keeps the " \
               "per-layer programs — GSPMD shards those"
    if getattr(cfg, "moe_num_experts", 0) > 0:
        return "MoE FFN layers route per token; the megakernel streams " \
               "dense gate/up/down weights"
    try:
        layers = model.model.layers
    except AttributeError:
        return "model is not LlamaForCausalLM-shaped"
    from ..nn.layer.common import Linear

    for i, layer in enumerate(layers):
        attn = layer.self_attn
        if getattr(attn, "_w8_split", None):
            return f"layer {i}: weight-only int8 attention " \
                   "(quantize_int8) is served by the per-layer path"
        mlp = layer.mlp
        for pname in ("gate_proj", "up_proj", "down_proj"):
            if type(getattr(mlp, pname, None)) is not Linear:
                return f"layer {i}: {pname} is not a plain Linear " \
                       "(weight-only int8 or LoRA-wrapped MLP)"
        for pname in ("q_proj", "k_proj", "v_proj", "o_proj"):
            if type(getattr(attn, pname, None)) is not Linear:
                return f"layer {i}: {pname} is not a plain Linear"
    I = cfg.intermediate_size
    if geometry.ffn_tile:
        if I % geometry.ffn_tile != 0:
            return f"ffn_tile {geometry.ffn_tile} does not divide " \
                   f"intermediate_size {I}"
        if lora:
            return "ffn_tile > 0 is incompatible with pooled LoRA (the " \
                   "gate/up/down delta needs the full intermediate dim)"
    D = cfg.hidden_size // cfg.num_attention_heads
    if D * cfg.num_attention_heads != cfg.hidden_size:
        return "hidden_size is not num_attention_heads * head_dim"
    if D % 2:
        return f"head_dim {D} is odd — rope splits it in half"
    try:
        _check_tick_shapes(D=D, bs=block_size, Hd=cfg.hidden_size,
                           Hq=cfg.num_attention_heads * D,
                           KVD=cfg.num_key_value_heads * D, I=I,
                           T=geometry.ffn_tile or I)
    except NotImplementedError as e:
        return str(e)
    return None


# ------------------------------------------------------- weight stacking
def stack_layer_weights(model):
    """One-time (L, in, out) stacking of the per-layer projection weights
    plus (L, hidden) norm weights — the HBM arrays the kernel streams.
    This DOUBLES the megakernel-served model's weight HBM (the per-layer
    params stay alive for prefill); the tradeoff is one contiguous
    stream-friendly layout per projection. Built once at executor init."""
    layers = model.model.layers

    def stk(get):
        return jnp.stack([jnp.asarray(get(l)) for l in layers])

    return {
        "wq": stk(lambda l: l.self_attn.q_proj.weight.value),
        "wk": stk(lambda l: l.self_attn.k_proj.weight.value),
        "wv": stk(lambda l: l.self_attn.v_proj.weight.value),
        "wo": stk(lambda l: l.self_attn.o_proj.weight.value),
        "wg": stk(lambda l: l.mlp.gate_proj.weight.value),
        "wu": stk(lambda l: l.mlp.up_proj.weight.value),
        "wd": stk(lambda l: l.mlp.down_proj.weight.value),
        "ln1": stk(lambda l: l.input_layernorm.weight.value),
        "ln2": stk(lambda l: l.post_attention_layernorm.weight.value),
    }


def stack_lora(lora):
    """Per-layer gathered factor dicts (AdapterPool.gather_rows) →
    per-target (L, B, in, R)/(L, B, R, out) stacks + the shared (B,)
    scale, the layout the kernel streams per layer. None passes through
    (LoRA off compiles the no-factor program)."""
    if lora is None:
        return None
    targets = tuple(t for t in LORA_TARGETS if t in lora[0])
    stacked = {}
    for t in targets:
        stacked[t] = (jnp.stack([ld[t][0] for ld in lora]),
                      jnp.stack([ld[t][1] for ld in lora]))
    scale = lora[0][targets[0]][2]
    return stacked, scale


def gather_rope_rows(cos, sin, pos, W: int):
    """Pre-gather the (B, W, D/2) rope rows for window positions
    ``clip(pos + arange(W), 0, len-1)`` — layer-invariant, so gathered
    once per tick outside the kernel (matches _apply_rope_window; the
    clamp is a no-op for in-range decode positions)."""
    idx = jnp.clip(pos[:, None] + jnp.arange(W)[None, :], 0,
                   cos.shape[0] - 1)
    return jnp.take(cos, idx, axis=0), jnp.take(sin, idx, axis=0)


# -------------------------------------------------------- HBM accounting
def hbm_bytes_per_trip(cfg, *, batch: int, window: int, block_size: int,
                       avg_ctx_blocks: int, kv_quant: str = "none",
                       megakernel: bool = True,
                       dtype_bytes: int = 4) -> int:
    """Per-trip HBM byte estimate for the bench row: weight stream (all
    layers once) + KV block reads/writes + (per-layer path only) the 2L
    residual-stream round trips the megakernel eliminates."""
    L = cfg.num_hidden_layers
    Hd = cfg.hidden_size
    D = Hd // cfg.num_attention_heads
    Hq = cfg.num_attention_heads * D
    KVD = cfg.num_key_value_heads * D
    I = cfg.intermediate_size
    BW = batch * window
    w = L * (Hd * Hq + 2 * Hd * KVD + Hq * Hd + 3 * Hd * I) * dtype_bytes
    kv_item = 1 if kv_quant == "int8" else dtype_bytes
    blk = block_size * cfg.num_key_value_heads * D * kv_item
    if kv_quant == "int8":
        blk += cfg.num_key_value_heads * 4
    kv = L * batch * (2 * avg_ctx_blocks * blk          # context reads
                      + 2 * window * (2 if kv_quant == "int8" else 1) * blk)
    n = w + kv
    if not megakernel:
        n += 2 * L * BW * Hd * dtype_bytes              # residual round trips
    return int(n)


# ------------------------------------------------------------ DMA stream
class _Stream:
    """Double-buffered HBM→VMEM chunk stream: ``depth`` VMEM slots +
    dedicated DMA semaphores, chunks issued ``depth`` ahead. All chunk
    ids are trace-time Python ints, so the schedule fully unrolls."""

    def __init__(self, buf, sem, sem_base, depth, nchunks, src_fn):
        self.buf = buf
        self.sem = sem
        self.base = sem_base
        self.depth = depth
        self.n = nchunks
        self.src = src_fn

    def _copy(self, c):
        slot = c % self.depth
        return pltpu.make_async_copy(self.src(c), self.buf.at[slot],
                                     self.sem.at[self.base + slot])

    def start(self, c):
        if 0 <= c < self.n:
            self._copy(c).start()

    def wait(self, c):
        self._copy(c).wait()

    def prestart(self):
        for c in range(min(self.depth, self.n)):
            self.start(c)

    def slot(self, c):
        return c % self.depth


# ------------------------------------------------------------ the kernel
def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps)
            * w.astype(jnp.float32)).astype(x.dtype)


def _tick_kernel(*refs, L, B, W, nH, KV, D, I, T, nT, bs, M, depth, eps,
                 quantized, dequant, lora_targets):
    nt_lora = len(lora_targets)
    lora_idx = {t: i for i, t in enumerate(lora_targets)}
    rep = nH // KV
    Wr = W * rep
    BW = B * W
    Hd = nH * D  # hidden == heads * head_dim for this model family
    Hq = nH * D
    KVD = KV * D
    D2 = D // 2
    P = (4 if quantized else 2) * L
    i = 0
    tables_ref, pos_ref = refs[i], refs[i + 1]
    i += 2
    lscale_ref = None
    if nt_lora:
        lscale_ref = refs[i]
        i += 1
    x_ref, cos_ref, sin_ref, ln1_ref, ln2_ref = refs[i:i + 5]
    i += 5
    wq_h, wk_h, wv_h, wo_h, wg_h, wu_h, wd_h = refs[i:i + 7]
    i += 7
    i += P                                   # aliased pool INPUT refs
    la_h = refs[i:i + 2 * nt_lora]
    i += 2 * nt_lora
    xo_ref = refs[i]
    i += 1
    pool_out = refs[i:i + P]                 # all pool access goes here
    i += P
    (xres, xn, qs, kls, vls, ao, mlp_acc) = refs[i:i + 7]
    i += 7
    wbufs = refs[i:i + 7]
    i += 7
    kblk, vblk = refs[i], refs[i + 1]
    i += 2
    kscl = vscl = iqblk = iscl = None
    if quantized:
        kscl, vscl, iqblk, iscl = refs[i:i + 4]
        i += 4
    ktok, vtok = refs[i], refs[i + 1]
    i += 2
    macc, lacc, oacc = refs[i:i + 3]
    i += 3
    lbufs = refs[i:i + 2 * nt_lora]
    i += 2 * nt_lora
    wsem = refs[i]
    i += 1
    rsem = refs[i]
    i += 1
    iosem = None
    if quantized:
        iosem = refs[i]
        i += 1
    lsem = refs[i] if nt_lora else None

    dtype = x_ref.dtype

    # ---- weight streams: attention kinds chunk per layer, FFN kinds
    # chunk per (layer, tile)
    def attn_src(h):
        return lambda c: h.at[c]

    def col_tile_src(h):
        return lambda c: h.at[c // nT, :, pl.ds((c % nT) * T, T)]

    def row_tile_src(h):
        return lambda c: h.at[c // nT, pl.ds((c % nT) * T, T), :]

    streams = {
        "wq": _Stream(wbufs[0], wsem, 0 * depth, depth, L, attn_src(wq_h)),
        "wk": _Stream(wbufs[1], wsem, 1 * depth, depth, L, attn_src(wk_h)),
        "wv": _Stream(wbufs[2], wsem, 2 * depth, depth, L, attn_src(wv_h)),
        "wo": _Stream(wbufs[3], wsem, 3 * depth, depth, L, attn_src(wo_h)),
        "wg": _Stream(wbufs[4], wsem, 4 * depth, depth, L * nT,
                      col_tile_src(wg_h)),
        "wu": _Stream(wbufs[5], wsem, 5 * depth, depth, L * nT,
                      col_tile_src(wu_h)),
        "wd": _Stream(wbufs[6], wsem, 6 * depth, depth, L * nT,
                      row_tile_src(wd_h)),
    }
    lstreams = []
    for t in range(nt_lora):
        lstreams.append((
            _Stream(lbufs[2 * t], lsem, (2 * t) * depth, depth, L,
                    attn_src(la_h[2 * t])),
            _Stream(lbufs[2 * t + 1], lsem, (2 * t + 1) * depth, depth, L,
                    attn_src(la_h[2 * t + 1])),
        ))

    xres[...] = x_ref[...]
    for st in streams.values():
        st.prestart()
    for sa, sb in lstreams:
        sa.prestart()
        sb.prestart()

    def lora_delta(tname, rows_fn, l):
        """Stacked per-row BGMV delta (BW, out) for target ``tname`` —
        lora_matmul's jnp branch order: d = ((x32 @ A[b]) @ B[b]) * s[b],
        computed in fp32 and cast by the caller. ``rows_fn(b)`` yields
        that row's (W, in) fp32 projection input."""
        t = lora_idx[tname]
        sa, sb = lstreams[t]
        sa.wait(l)
        sb.wait(l)
        sl = sa.slot(l)
        deltas = []
        for b in range(B):
            d = jnp.matmul(jnp.matmul(rows_fn(b), lbufs[2 * t][sl, b]),
                           lbufs[2 * t + 1][sl, b]) * lscale_ref[b]
            deltas.append(d)
        sa.start(l + depth)
        sb.start(l + depth)
        return jnp.concatenate(deltas, axis=0)

    def xn_rows(b):
        return xn[b * W:(b + 1) * W, :].astype(jnp.float32)

    def rope_inplace(dst, heads):
        c = cos_ref[...]
        s = sin_ref[...]
        for h in range(heads):
            s1 = slice(h * D, h * D + D2)
            s2 = slice(h * D + D2, (h + 1) * D)
            x1 = dst[:, s1].astype(jnp.float32)
            x2 = dst[:, s2].astype(jnp.float32)
            dst[:, s1] = (x1 * c - x2 * s).astype(dtype)
            dst[:, s2] = (x2 * c + x1 * s).astype(dtype)

    def layer(l):
        # ---------- attention projections on the normed residual
        xn[...] = _rms(xres[...], ln1_ref[l], eps)
        for name, dst in (("wq", qs), ("wk", kls), ("wv", vls)):
            st = streams[name]
            st.wait(l)
            dst[...] = jnp.matmul(xn[...], st.buf[st.slot(l)])
            st.start(l + depth)
        for t in ("q", "k", "v"):
            if t in lora_idx:
                dst = {"q": qs, "k": kls, "v": vls}[t]
                dst[...] = dst[...] + lora_delta(t, xn_rows, l).astype(dtype)
        rope_inplace(qs, nH)
        rope_inplace(kls, KV)

        # ---------- KV write through the block table (window tokens)
        if quantized:
            kq_o, ks_o = pool_out[4 * l], pool_out[4 * l + 1]
            vq_o, vs_o = pool_out[4 * l + 2], pool_out[4 * l + 3]
        else:
            kp_o, vp_o = pool_out[2 * l], pool_out[2 * l + 1]
        for b in range(B):
            for w in range(W):
                pj = pos_ref[b] + w
                bid = tables_ref[b, pj // bs]
                off = pj % bs
                krow = kls[b * W + w, :].reshape(KV, D)
                vrow = vls[b * W + w, :].reshape(KV, D)
                if not quantized:
                    ktok[...] = krow
                    vtok[...] = vrow
                    ck = pltpu.make_async_copy(ktok, kp_o.at[bid, off],
                                               rsem.at[4])
                    cv = pltpu.make_async_copy(vtok, vp_o.at[bid, off],
                                               rsem.at[5])
                    ck.start()
                    cv.start()
                    ck.wait()
                    cv.wait()
                else:
                    # _insert_token_q in-kernel: whole-block requant
                    for tok, q_o, s_o in ((krow, kq_o, ks_o),
                                          (vrow, vq_o, vs_o)):
                        ci = pltpu.make_async_copy(q_o.at[bid], iqblk,
                                                   iosem.at[0])
                        cs = pltpu.make_async_copy(s_o.at[bid], iscl,
                                                   iosem.at[1])
                        ci.start()
                        cs.start()
                        ci.wait()
                        cs.wait()
                        blk = iqblk[...].astype(jnp.float32) * \
                            iscl[...][None, :, None]
                        blk = jax.lax.dynamic_update_slice(
                            blk, tok.astype(jnp.float32)[None],
                            (off, jnp.int32(0), jnp.int32(0)))
                        amax = jnp.max(jnp.abs(blk), axis=(0, 2))
                        ns = jnp.maximum(amax, _QEPS) / 127.0
                        iqblk[...] = jnp.clip(
                            jnp.round(blk / ns[None, :, None]), -127,
                            127).astype(jnp.int8)
                        iscl[...] = ns
                        co = pltpu.make_async_copy(iqblk, q_o.at[bid],
                                                   iosem.at[2])
                        cso = pltpu.make_async_copy(iscl, s_o.at[bid],
                                                    iosem.at[3])
                        co.start()
                        cso.start()
                        co.wait()
                        cso.wait()

        # ---------- paged attention per row (online softmax over blocks)
        if quantized:
            k_src, ks_src = pool_out[4 * l], pool_out[4 * l + 1]
            v_src, vs_src = pool_out[4 * l + 2], pool_out[4 * l + 3]
        else:
            k_src, v_src = pool_out[2 * l], pool_out[2 * l + 1]

        def start_blk(b, m, slot):
            blk_id = tables_ref[b, m]
            pltpu.make_async_copy(k_src.at[blk_id], kblk.at[slot],
                                  rsem.at[0 + slot]).start()
            pltpu.make_async_copy(v_src.at[blk_id], vblk.at[slot],
                                  rsem.at[2 + slot]).start()
            if quantized:
                pltpu.make_async_copy(ks_src.at[blk_id], kscl.at[slot],
                                      rsem.at[6 + slot]).start()
                pltpu.make_async_copy(vs_src.at[blk_id], vscl.at[slot],
                                      rsem.at[8 + slot]).start()

        def wait_blk(b, m, slot):
            blk_id = tables_ref[b, m]
            pltpu.make_async_copy(k_src.at[blk_id], kblk.at[slot],
                                  rsem.at[0 + slot]).wait()
            pltpu.make_async_copy(v_src.at[blk_id], vblk.at[slot],
                                  rsem.at[2 + slot]).wait()
            if quantized:
                pltpu.make_async_copy(ks_src.at[blk_id], kscl.at[slot],
                                      rsem.at[6 + slot]).wait()
                pltpu.make_async_copy(vs_src.at[blk_id], vscl.at[slot],
                                      rsem.at[8 + slot]).wait()

        for b in range(B):
            macc[...] = jnp.full((KV * Wr, 128), NEG_INF, jnp.float32)
            lacc[...] = jnp.zeros((KV * Wr, 128), jnp.float32)
            oacc[...] = jnp.zeros((KV * Wr, D), jnp.float32)
            nb = jnp.minimum((pos_ref[b] + (W - 1)) // bs + 1, M)
            start_blk(b, 0, 0)

            def mbody(m, _, b=b):
                slot = jax.lax.rem(m, jnp.int32(2))

                @pl.when(m + 1 < nb)
                def _():
                    start_blk(b, m + 1, jax.lax.rem(m + 1, jnp.int32(2)))

                wait_blk(b, m, slot)
                for g in range(KV):
                    qt = qs[b * W:(b + 1) * W,
                            g * rep * D:(g + 1) * rep * D].reshape(
                        W, rep, D).reshape(Wr, D)
                    kt = kblk[slot][:, g, :]
                    vt = vblk[slot][:, g, :]
                    if quantized:
                        if dequant == "tile":
                            kt = kt.astype(jnp.float32) * kscl[slot, g]
                            vt = vt.astype(jnp.float32) * vscl[slot, g]
                            kt = kt.astype(qt.dtype)
                            vt = vt.astype(qt.dtype)
                        else:
                            kt = kt.astype(qt.dtype)
                            vt = vt.astype(qt.dtype)
                    s_ = jax.lax.dot_general(
                        qt, kt, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    if quantized and dequant == "scores":
                        s_ = s_ * kscl[slot, g]
                    s_ = s_ / jnp.float32(math.sqrt(D))
                    rows_i = jax.lax.broadcasted_iota(jnp.int32, s_.shape, 0)
                    cols_i = jax.lax.broadcasted_iota(jnp.int32, s_.shape, 1)
                    qpos = pos_ref[b] + rows_i // rep
                    s_ = jnp.where(m * bs + cols_i <= qpos, s_, NEG_INF)
                    gsl = slice(g * Wr, (g + 1) * Wr)
                    m_prev = macc[gsl, 0]
                    l_prev = lacc[gsl, 0]
                    m_new = jnp.maximum(m_prev, jnp.max(s_, axis=-1))
                    p = jnp.exp(s_ - m_new[:, None])
                    alpha = jnp.exp(m_prev - m_new)
                    lacc[gsl, :] = _lanes(l_prev * alpha
                                          + jnp.sum(p, axis=-1))
                    if quantized and dequant == "scores":
                        p = p * vscl[slot, g]
                    oacc[gsl, :] = oacc[gsl, :] * alpha[:, None] + \
                        jax.lax.dot_general(
                            p.astype(vt.dtype), vt, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
                    macc[gsl, :] = _lanes(m_new)
                return 0

            jax.lax.fori_loop(0, nb, mbody, 0)
            lsafe = jnp.maximum(lacc[:, 0], 1e-30)
            outv = (oacc[...] / lsafe[:, None]).astype(dtype)
            for g in range(KV):
                ao[b * W:(b + 1) * W, g * rep * D:(g + 1) * rep * D] = \
                    outv[g * Wr:(g + 1) * Wr, :].reshape(W, rep * D)

        # ---------- output projection + residual
        st = streams["wo"]
        st.wait(l)
        attn_o = jnp.matmul(ao[...], st.buf[st.slot(l)])
        st.start(l + depth)
        if "o" in lora_idx:
            # o-delta reads the ATTENTION OUTPUT rows, not xn
            attn_o = attn_o + lora_delta(
                "o", lambda b: ao[b * W:(b + 1) * W, :].astype(jnp.float32),
                l).astype(dtype)
        xres[...] = xres[...] + attn_o

        # ---------- MLP on the re-normed residual
        xn[...] = _rms(xres[...], ln2_ref[l], eps)
        sg, su, sd = streams["wg"], streams["wu"], streams["wd"]
        if nT == 1:
            sg.wait(l)
            su.wait(l)
            g_ = jnp.matmul(xn[...], sg.buf[sg.slot(l)])
            u_ = jnp.matmul(xn[...], su.buf[su.slot(l)])
            sg.start(l + depth)
            su.start(l + depth)
            if "gate" in lora_idx:
                g_ = g_ + lora_delta("gate", xn_rows, l).astype(dtype)
            if "up" in lora_idx:
                u_ = u_ + lora_delta("up", xn_rows, l).astype(dtype)
            h_ = jax.nn.silu(g_) * u_
            sd.wait(l)
            mo = jnp.matmul(h_, sd.buf[sd.slot(l)])
            sd.start(l + depth)
            if "down" in lora_idx:
                mo = mo + lora_delta(
                    "down",
                    lambda b: h_[b * W:(b + 1) * W, :].astype(jnp.float32),
                    l).astype(dtype)
            xres[...] = xres[...] + mo
        else:
            mlp_acc[...] = jnp.zeros((BW, Hd), jnp.float32)
            for t in range(nT):
                c = l * nT + t
                sg.wait(c)
                su.wait(c)
                g_ = jnp.matmul(xn[...], sg.buf[sg.slot(c)])
                u_ = jnp.matmul(xn[...], su.buf[su.slot(c)])
                sg.start(c + depth)
                su.start(c + depth)
                h_ = jax.nn.silu(g_) * u_
                sd.wait(c)
                mlp_acc[...] = mlp_acc[...] + jnp.matmul(
                    h_, sd.buf[sd.slot(c)]).astype(jnp.float32)
                sd.start(c + depth)
            xres[...] = xres[...] + mlp_acc[...].astype(dtype)

    for l in range(L):
        layer(l)
    xo_ref[...] = xres[...]


# ------------------------------------------------------------ the wrapper
def decode_tick(x, pools, tables, pos, weights, cos_rows, sin_rows, *,
                block_size: int, geometry: Optional[MegakernelGeometry]
                = None, eps: float = 1e-6, lora=None):
    """Run one whole decode/verify tick through the persistent kernel.

    ``x``: (B, W, hidden) embedded window activations; ``pools``: the
    executor's flat per-layer KV pool list (fp: 2/layer, int8: 4/layer) —
    ALIASED into the outputs, so callers treat them as donated; ``weights``
    from :func:`stack_layer_weights`; ``cos_rows``/``sin_rows``: (B, W,
    D/2) from :func:`gather_rope_rows`; ``lora`` from :func:`stack_lora`.

    Returns ``(x_out (B, W, hidden), new_pools list)`` — the tick's
    post-norm input is NOT applied here (the executor's final norm + head
    stay outside, like the per-layer path). Raises ``NotImplementedError``
    from the shape guard at trace time on Mosaic misalignment — the
    dispatch ladder's fall-to-pallas signal."""
    geometry = geometry or MegakernelGeometry()
    geometry.validate()
    B, W, Hd = x.shape
    BW = B * W
    L, _, Hq = weights["wq"].shape
    KVD = weights["wk"].shape[2]
    D2 = cos_rows.shape[-1]
    D = 2 * D2
    nH = Hq // D
    KV = KVD // D
    I = weights["wg"].shape[2]
    T = geometry.ffn_tile or I
    nT = I // T
    depth = geometry.prefetch_depth
    M = tables.shape[1]
    bs = block_size
    quantized = pools[0].dtype == jnp.int8
    P = (4 if quantized else 2) * L
    assert len(pools) == P, (len(pools), P)
    _check_tick_shapes(D=D, bs=bs, Hd=Hd, Hq=Hq, KVD=KVD, I=I, T=T)

    dtype = x.dtype
    kv_dtype = jnp.int8 if quantized else pools[0].dtype

    lora_targets = ()
    lora_inputs = []
    lscale_in = []
    if lora is not None:
        stacked, scale = lora
        lora_targets = tuple(t for t in LORA_TARGETS if t in stacked)
        lscale_in = [jnp.asarray(scale, jnp.float32)]
        for t in lora_targets:
            a, b_ = stacked[t]
            lora_inputs += [jnp.asarray(a, jnp.float32),
                            jnp.asarray(b_, jnp.float32)]
    nt = len(lora_targets)

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)
    any_ = pl.BlockSpec(memory_space=pltpu.ANY)

    inputs = [tables, pos, *lscale_in,
              x.reshape(BW, Hd),
              cos_rows.reshape(BW, D2).astype(jnp.float32),
              sin_rows.reshape(BW, D2).astype(jnp.float32),
              weights["ln1"], weights["ln2"],
              weights["wq"], weights["wk"], weights["wv"], weights["wo"],
              weights["wg"], weights["wu"], weights["wd"],
              *pools, *lora_inputs]
    in_specs = ([smem, smem] + [smem] * len(lscale_in) + [vmem] * 5
                + [any_] * 7 + [any_] * P + [any_] * (2 * nt))
    pool_base = 2 + len(lscale_in) + 5 + 7
    aliases = {pool_base + j: 1 + j for j in range(P)}

    out_shape = [jax.ShapeDtypeStruct((BW, Hd), dtype)] + \
        [jax.ShapeDtypeStruct(p.shape, p.dtype) for p in pools]
    out_specs = [vmem] + [any_] * P

    rep = nH // KV
    Wr = W * rep
    scratch = [
        pltpu.VMEM((BW, Hd), dtype),          # xres
        pltpu.VMEM((BW, Hd), dtype),          # xn
        pltpu.VMEM((BW, Hq), dtype),          # qs
        pltpu.VMEM((BW, KVD), dtype),         # kls
        pltpu.VMEM((BW, KVD), dtype),         # vls
        pltpu.VMEM((BW, Hq), dtype),          # ao
        pltpu.VMEM((BW, Hd), jnp.float32),    # mlp_acc
        pltpu.VMEM((depth, Hd, Hq), dtype),   # wq stream
        pltpu.VMEM((depth, Hd, KVD), dtype),  # wk
        pltpu.VMEM((depth, Hd, KVD), dtype),  # wv
        pltpu.VMEM((depth, Hq, Hd), dtype),   # wo
        pltpu.VMEM((depth, Hd, T), dtype),    # wg
        pltpu.VMEM((depth, Hd, T), dtype),    # wu
        pltpu.VMEM((depth, T, Hd), dtype),    # wd
        pltpu.VMEM((2, bs, KV, D), kv_dtype),  # kblk
        pltpu.VMEM((2, bs, KV, D), kv_dtype),  # vblk
    ]
    if quantized:
        scratch += [
            pltpu.VMEM((2, KV), jnp.float32),      # kscl
            pltpu.VMEM((2, KV), jnp.float32),      # vscl
            pltpu.VMEM((bs, KV, D), jnp.int8),     # iqblk (requant staging)
            pltpu.VMEM((KV,), jnp.float32),        # iscl
        ]
    scratch += [
        pltpu.VMEM((KV, D), dtype),                # ktok
        pltpu.VMEM((KV, D), dtype),                # vtok
        pltpu.VMEM((KV * Wr, 128), jnp.float32),   # macc
        pltpu.VMEM((KV * Wr, 128), jnp.float32),   # lacc
        pltpu.VMEM((KV * Wr, D), jnp.float32),     # oacc
    ]
    for t in lora_targets:
        a, b_ = lora[0][t]
        scratch += [pltpu.VMEM((depth,) + a.shape[1:], jnp.float32),
                    pltpu.VMEM((depth,) + b_.shape[1:], jnp.float32)]
    scratch += [pltpu.SemaphoreType.DMA((7 * depth,)),   # wsem
                pltpu.SemaphoreType.DMA((10,))]          # rsem
    if quantized:
        scratch.append(pltpu.SemaphoreType.DMA((4,)))    # iosem
    if nt:
        scratch.append(pltpu.SemaphoreType.DMA((2 * nt * depth,)))  # lsem

    kernel = functools.partial(
        _tick_kernel, L=L, B=B, W=W, nH=nH, KV=KV, D=D, I=I, T=T, nT=nT,
        bs=bs, M=M, depth=depth, eps=eps, quantized=quantized,
        dequant=geometry.dequant, lora_targets=lora_targets)

    outs = pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        input_output_aliases=aliases,
        interpret=_interpret(),
    )(*inputs)
    return outs[0].reshape(B, W, Hd), list(outs[1:])
