"""Fused RMS/Layer norm Pallas kernels (ref: the reference fuses norms into
decoder layers inside fused_multi_transformer_op.cu; standalone layer_norm is
phi/kernels/gpu/layer_norm_kernel.cu).

Single-pass row kernels: mean/var computed in VMEM, scaled output written
once. Fall back to jnp on non-TPU. Backward via recompute (jnp composition),
same policy as flash_attention.

The row tile is a :class:`~paddle_tpu.autotune.kernel_geometry.NormGeometry`
schedule knob resolved at trace time from the process-wide winner cache;
every row computes its own statistics, so any tile is bit-exact and the
default (rows=0) reproduces today's ``max(min(512, rows), 8)`` formula.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def _rms_ref(x, w, eps):
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(ms + eps) * w.astype(jnp.float32)).astype(x.dtype)


def _ln_ref(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out * w.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def _rms_kernel(x_ref, w_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    ms = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    o_ref[:] = (x * jax.lax.rsqrt(ms + eps) * w_ref[:].astype(jnp.float32)
                ).astype(o_ref.dtype)


def _ln_kernel(x_ref, w_ref, b_ref, o_ref, *, eps):
    x = x_ref[:].astype(jnp.float32)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    o_ref[:] = ((x - mu) * jax.lax.rsqrt(var + eps) * w_ref[:].astype(jnp.float32)
                + b_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _on_tpu(x):
    return jax.default_backend() in ("tpu", "axon")


def _block_rows(x, rows, geometry):
    """Row tile: geometry's opinion (clamped to a divisor) when it has
    one, else today's formula — which may not divide ``rows``; callers
    keep the divisibility guard, so the default fallback behavior is
    unchanged."""
    from ..autotune.kernel_geometry import NormGeometry, _largest_divisor, \
        resolve_geometry

    if geometry is None:
        geometry = resolve_geometry("fused_norm", str(x.dtype),
                                    x.shape[-1])[0]
    if not isinstance(geometry, NormGeometry):
        raise ValueError(f"fused norm wants a NormGeometry, got "
                         f"{type(geometry).__name__}")
    geometry.validate()
    if geometry.rows > 0:
        return _largest_divisor(rows, geometry.rows)
    return max(min(512, rows), 8)


def _rms_pallas(x, weight, eps, geometry=None, interpret=False):
    from jax.experimental import pallas as pl

    D = x.shape[-1]
    flat = x.reshape(-1, D)
    rows = flat.shape[0]
    block_rows = _block_rows(x, rows, geometry)
    if rows % block_rows:
        raise NotImplementedError(f"{rows} rows not tileable by {block_rows}")
    out = pl.pallas_call(
        functools.partial(_rms_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, D), lambda i: (i, 0)),
                  pl.BlockSpec((D,), lambda i: (0,))],
        out_specs=pl.BlockSpec((block_rows, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(flat.shape, x.dtype),
        interpret=interpret,
    )(flat, weight)
    return out.reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_rms_norm(x, weight, eps=1e-6):
    if _on_tpu(x):
        try:
            return _rms_pallas(x, weight, eps)
        except Exception:
            pass
    return _rms_ref(x, weight, eps)


def _rms_fwd(x, w, eps):
    return fused_rms_norm(x, w, eps), (x, w)


def _rms_bwd(eps, res, g):
    x, w = res
    _, vjp_fn = jax.vjp(lambda x_, w_: _rms_ref(x_, w_, eps), x, w)
    return vjp_fn(g)


fused_rms_norm.defvjp(_rms_fwd, _rms_bwd)


def _ln_pallas(x, weight, bias, eps, geometry=None, interpret=False):
    from jax.experimental import pallas as pl

    D = x.shape[-1]
    flat = x.reshape(-1, D)
    rows = flat.shape[0]
    block_rows = _block_rows(x, rows, geometry)
    if rows % block_rows:
        raise NotImplementedError(f"{rows} rows not tileable by {block_rows}")
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(rows // block_rows,),
        in_specs=[pl.BlockSpec((block_rows, D), lambda i: (i, 0)),
                  pl.BlockSpec((D,), lambda i: (0,)),
                  pl.BlockSpec((D,), lambda i: (0,))],
        out_specs=pl.BlockSpec((block_rows, D), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(flat.shape, x.dtype),
        interpret=interpret,
    )(flat, weight, bias)
    return out.reshape(x.shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def fused_layer_norm(x, weight, bias, eps=1e-5):
    if _on_tpu(x):
        try:
            return _ln_pallas(x, weight, bias, eps)
        except Exception:
            pass
    return _ln_ref(x, weight, bias, eps)


def _ln_fwd(x, w, b, eps):
    return fused_layer_norm(x, w, b, eps), (x, w, b)


def _ln_bwd(eps, res, g):
    x, w, b = res
    _, vjp_fn = jax.vjp(lambda x_, w_, b_: _ln_ref(x_, w_, b_, eps), x, w, b)
    return vjp_fn(g)


fused_layer_norm.defvjp(_ln_fwd, _ln_bwd)
