"""Pallas TPU kernels for the paged serving hot path.

One flash-style online-softmax kernel serves decode (W=1), speculative
verify (W=tick_window), and chunked prefill (B=1, W=chunk): the grid is
(batch, kv_head, kv_block) and the K/V ``BlockSpec`` index_map reads the
block table through ``PrefetchScalarGridSpec`` scalar-prefetch —
``tbl[b, m]`` picks the pool block to stream into VMEM, so the dense
``gather_block_kv`` copy of the context never materializes in HBM. Running
max/sum/accumulator live in VMEM scratch across the block axis;
``pl.when`` skips blocks past each row's causal frontier, which also
covers the all-zero scratch-block entries of short sequences. The int8
twin streams the code pool directly and applies the per-(block, kv-head)
scales on the VMEM tile — k-scale on the fp32 QK accumulator, v-scale
folded into the probabilities before PV — so a dequantized pool is never
built. ``fused_lora_matmul`` fuses the per-slot BGMV adapter delta
(gathered A/B/scale factors) into the base projection matmul, one program
per batch row.

The jnp compositions in ``ops/paged_attention.py`` remain the bit-exact
references; dispatch between them and these kernels follows the shared
``ops.use_pallas()`` / ``ops.pallas_interpret()`` contract (TPU backend,
``PT_FLASH_INTERPRET=1``, or ``set_kernel_mode``). The online softmax is
numerically equivalent but not bit-identical to the reference's two-pass
softmax (~1e-6 relative); greedy decode tokens are identical, which is
what the serving tests pin.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# jax >= 0.5 renamed TPUCompilerParams -> CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams


def _interpret() -> bool:
    from . import pallas_interpret

    return pallas_interpret()


def _lanes(x):
    """Broadcast a (rows,) vector across the 128-lane minor dim so the
    running max/sum scratch keeps a TPU-native (rows, 128) layout."""
    return jnp.broadcast_to(x[:, None], (x.shape[0], 128))


def _check_tpu_shapes(bs: int, D: int) -> None:
    """Alignment the Mosaic compiler needs on real hardware; interpret mode
    takes any shape. Callers catch and fall back to the jnp reference."""
    if _interpret():
        return
    if D % 128 != 0:
        raise NotImplementedError(f"head_dim {D} not lane-aligned (128)")
    if bs % 8 != 0:
        raise NotImplementedError(f"block_size {bs} not sublane-aligned (8)")


# ------------------------------------------------------------------ attention
def _attn_kernel(tbl_ref, pos_ref, q_ref, k_ref, v_ref, *rest, bs, W, rep, M,
                 quantized):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    m = pl.program_id(2)

    @pl.when(m == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Skip blocks entirely past the last query row's causal frontier — this
    # also covers block-table tail entries that still point at scratch
    # block 0.
    needed = m * bs <= pos_ref[b] + (W - 1)

    @pl.when(needed)
    def _compute():
        q = q_ref[0, 0]                       # (W*rep, D)
        k = k_ref[0, :, 0, :]                 # (bs, D)
        v = v_ref[0, :, 0, :]
        if quantized:
            k = k.astype(q.dtype)
            v = v.astype(q.dtype)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if quantized:
            # reference order: scores * k_scale, then / sqrt(D)
            s = s * ks_ref[0, 0]
        s = s / jnp.float32(math.sqrt(q.shape[-1]))
        rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        qpos = pos_ref[b] + rows // rep       # row -> absolute query position
        s = jnp.where(m * bs + cols <= qpos, s, NEG_INF)
        m_prev = m_ref[:, 0]
        l_prev = l_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = _lanes(l_prev * alpha + jnp.sum(p, axis=-1))
        if quantized:
            p = p * vs_ref[0, 0]              # fold v scale into probs
        acc_ref[...] = acc_ref[...] * alpha[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = _lanes(m_new)

    @pl.when(m == M - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def _paged_attention_call(q, k_pool, v_pool, tables, pos, k_scales=None,
                          v_scales=None):
    B, W, H, D = q.shape
    N, bs, KV, _ = k_pool.shape
    rep = H // KV
    M = tables.shape[1]
    Wr = W * rep
    _check_tpu_shapes(bs, D)
    quantized = k_scales is not None
    # GQA: group query heads with their shared kv head so one kernel
    # instance covers the whole group — (B, KV, W*rep, D).
    qt = q.reshape(B, W, KV, rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, KV, Wr, D)
    kv_spec = pl.BlockSpec((1, bs, 1, D),
                           lambda b, g, m, tbl, ps: (tbl[b, m], 0, g, 0))
    in_specs = [
        pl.BlockSpec((1, 1, Wr, D), lambda b, g, m, tbl, ps: (b, g, 0, 0)),
        kv_spec, kv_spec,
    ]
    args = [tables.astype(jnp.int32), pos.astype(jnp.int32), qt, k_pool,
            v_pool]
    if quantized:
        sc_spec = pl.BlockSpec((1, 1), lambda b, g, m, tbl, ps: (tbl[b, m], g))
        in_specs += [sc_spec, sc_spec]
        args += [k_scales.astype(jnp.float32), v_scales.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KV, M),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, Wr, D),
                               lambda b, g, m, tbl, ps: (b, g, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((Wr, 128), jnp.float32),   # running max
            pltpu.VMEM((Wr, 128), jnp.float32),   # running sum
            pltpu.VMEM((Wr, D), jnp.float32),     # output accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_attn_kernel, bs=bs, W=W, rep=rep, M=M,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, Wr, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    return out.reshape(B, KV, W, rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, W, H, D)


def paged_attention(q, k_pool, v_pool, block_tables, pos):
    """Fused paged decode/verify attention over an fp block pool.

    q: (B, W, H, D) — W=1 decode, W=tick_window verify, W=chunk prefill.
    pos: (B,) int — absolute position of each row's FIRST query token.
    """
    return _paged_attention_call(q, k_pool, v_pool, block_tables, pos)


def paged_attention_q(q, kq_pool, k_scales, vq_pool, v_scales, block_tables,
                      pos):
    """Int8 twin: streams the code pool and dequantizes on the VMEM tile."""
    return _paged_attention_call(q, kq_pool, vq_pool, block_tables, pos,
                                 k_scales=k_scales, v_scales=v_scales)


# ----------------------------------------------------------------- LoRA BGMV
def _lora_kernel(x_ref, w_ref, a_ref, b_ref, s_ref, o_ref):
    x = x_ref[0]                               # (S, in)
    y = jax.lax.dot_general(x, w_ref[...], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    xa = jax.lax.dot_general(x.astype(jnp.float32), a_ref[0],
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = jax.lax.dot_general(xa, b_ref[0], (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    o_ref[0] = (y + d * s_ref[0, 0]).astype(o_ref.dtype)


def fused_lora_matmul(x, w, a, b, s):
    """Base projection + per-row LoRA delta in one program per batch row:
    ``x @ w + ((x32 @ a[i]) @ b[i]) * s[i]``. The factors are the per-slot
    gathers from AdapterPool.gather_rows — a (B, in, R), b (B, R, out),
    s (B,); null adapters arrive as zero factors with s=0, making the delta
    exactly zero (bit-identical to the plain matmul)."""
    B, S, IN = x.shape
    OUT = w.shape[1]
    R = a.shape[2]
    if not _interpret() and (IN % 128 or OUT % 128):
        raise NotImplementedError("projection dims not lane-aligned")
    return pl.pallas_call(
        _lora_kernel,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, S, IN), lambda i: (i, 0, 0)),
            pl.BlockSpec((IN, OUT), lambda i: (0, 0)),
            pl.BlockSpec((1, IN, R), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, R, OUT), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, S, OUT), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, S, OUT), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(x, w, a, b, s.reshape(B, 1).astype(jnp.float32))
