"""Pallas TPU kernels for the paged serving hot path.

One flash-style online-softmax kernel serves decode (W=1), speculative
verify (W=tick_window), and chunked prefill (B=1, W=chunk): the grid is
(batch, kv_head, kv_block) and the K/V ``BlockSpec`` index_map reads the
block table through ``PrefetchScalarGridSpec`` scalar-prefetch —
``tbl[b, m]`` picks the pool block to stream into VMEM, so the dense
``gather_block_kv`` copy of the context never materializes in HBM. Running
max/sum/accumulator live in VMEM scratch across the block axis;
``pl.when`` skips blocks past each row's causal frontier, which also
covers the all-zero scratch-block entries of short sequences. The int8
twin streams the code pool directly and applies the per-(block, kv-head)
scales on the VMEM tile — k-scale on the fp32 QK accumulator, v-scale
folded into the probabilities before PV — so a dequantized pool is never
built. ``fused_lora_matmul`` fuses the per-slot BGMV adapter delta
(gathered A/B/scale factors) into the base projection matmul, one program
per batch row.

The kernel's SCHEDULE is parameterized by
:class:`~paddle_tpu.autotune.kernel_geometry.PagedAttentionGeometry`
(and the LoRA kernel's by :class:`~paddle_tpu.autotune.kernel_geometry
.LoRAGeometry`): KV streaming depth (blocks fetched per grid step),
q-row tiling (extra parallel axis over the W*rep GQA rows), grid
iteration order, and int8 cast placement. All geometry axes are
schedule-only — the per-block online-softmax update runs in the same
order on the same values, so every geometry is bit-exact against the
default, and the default geometry lowers to exactly the pre-geometry
kernel (one block per step, full row group, bgm order). ``geometry=``
is a trace-time parameter; when omitted, the process-wide winner cache
(``autotune.kernel_geometry.install_geometry_cache``) is consulted at
trace time, same contract as ``ops.set_kernel_mode``.

The jnp compositions in ``ops/paged_attention.py`` remain the bit-exact
references; dispatch between them and these kernels follows the shared
``ops.use_pallas()`` / ``ops.pallas_interpret()`` contract (TPU backend,
``PT_FLASH_INTERPRET=1``, or ``set_kernel_mode``). The online softmax is
numerically equivalent but not bit-identical to the reference's two-pass
softmax (~1e-6 relative); greedy decode tokens are identical, which is
what the serving tests pin.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30

# jax >= 0.5 renamed TPUCompilerParams -> CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams


def _interpret() -> bool:
    from . import pallas_interpret

    return pallas_interpret()


def _lanes(x):
    """Broadcast a (rows,) vector across the 128-lane minor dim so the
    running max/sum scratch keeps a TPU-native (rows, 128) layout."""
    return jnp.broadcast_to(x[:, None], (x.shape[0], 128))


def _check_tpu_shapes(bs: int, D: int) -> None:
    """Alignment the Mosaic compiler needs on real hardware; interpret mode
    takes any shape. Callers catch and fall back to the jnp reference."""
    if _interpret():
        return
    if D % 128 != 0:
        raise NotImplementedError(f"head_dim {D} not lane-aligned (128)")
    if bs % 8 != 0:
        raise NotImplementedError(f"block_size {bs} not sublane-aligned (8)")


def _resolve(op: str, dtype: str, key: int):
    from ..autotune.kernel_geometry import resolve_geometry

    return resolve_geometry(op, dtype, key)[0]


# ------------------------------------------------------------------ attention
def _attn_kernel(tbl_ref, pos_ref, q_ref, *rest, bs, W, rep, Mp, depth, R,
                 quantized, early, ib, ig, iq, im):
    d = depth
    k_refs = rest[:d]
    v_refs = rest[d:2 * d]
    n = 2 * d
    if quantized:
        ks_refs = rest[n:n + d]
        vs_refs = rest[n + d:n + 2 * d]
        n += 2 * d
    else:
        ks_refs = vs_refs = None
    o_ref, m_ref, l_ref, acc_ref = rest[n:]
    b = pl.program_id(ib)
    m = pl.program_id(im)
    # first global q row of this program's tile (0 unless q_rows tiles
    # the W*rep group across its own grid axis)
    row0 = pl.program_id(iq) * R if iq is not None else 0

    @pl.when(m == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    def _step(j):
        blk = m * d + j
        # Skip blocks entirely past the last query row's causal frontier
        # — this also covers block-table tail entries that still point at
        # scratch block 0. The frontier test is per batch row (not per
        # q tile) so the skip schedule is geometry-independent.
        needed = blk * bs <= pos_ref[b] + (W - 1)
        if quantized and early:
            # "early" dequant placement: the int8->fp cast is exact, so
            # hoisting it out of the skip branch changes the schedule
            # (branchless stream) but never the math
            k_pre = k_refs[j][0, :, 0, :].astype(q_ref.dtype)
            v_pre = v_refs[j][0, :, 0, :].astype(q_ref.dtype)

        @pl.when(needed)
        def _compute():
            q = q_ref[0, 0]                       # (R, D)
            if quantized and early:
                k, v = k_pre, v_pre
            else:
                k = k_refs[j][0, :, 0, :]         # (bs, D)
                v = v_refs[j][0, :, 0, :]
                if quantized:
                    k = k.astype(q.dtype)
                    v = v.astype(q.dtype)
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32)
            if quantized:
                # reference order: scores * k_scale, then / sqrt(D)
                s = s * ks_refs[j][0, 0]
            s = s / jnp.float32(math.sqrt(q.shape[-1]))
            rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            # row -> absolute query position
            qpos = pos_ref[b] + (row0 + rows) // rep
            s = jnp.where(blk * bs + cols <= qpos, s, NEG_INF)
            m_prev = m_ref[:, 0]
            l_prev = l_ref[:, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[:, None])
            alpha = jnp.exp(m_prev - m_new)
            l_ref[...] = _lanes(l_prev * alpha + jnp.sum(p, axis=-1))
            if quantized:
                p = p * vs_refs[j][0, 0]          # fold v scale into probs
            acc_ref[...] = acc_ref[...] * alpha[:, None] + \
                jax.lax.dot_general(
                    p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
            m_ref[...] = _lanes(m_new)

    for j in range(d):
        _step(j)

    @pl.when(m == Mp - 1)
    def _finish():
        l_safe = jnp.maximum(l_ref[:, 0], 1e-30)
        o_ref[0, 0] = (acc_ref[...] / l_safe[:, None]).astype(o_ref.dtype)


def _paged_attention_call(q, k_pool, v_pool, tables, pos, k_scales=None,
                          v_scales=None, geometry=None):
    from ..autotune.kernel_geometry import (PagedAttentionGeometry,
                                            _largest_divisor)

    B, W, H, D = q.shape
    N, bs, KV, _ = k_pool.shape
    rep = H // KV
    M = tables.shape[1]
    Wr = W * rep
    _check_tpu_shapes(bs, D)
    quantized = k_scales is not None
    if geometry is None:
        geometry = _resolve("paged_attention",
                            "int8" if quantized else str(q.dtype), D)
    if not isinstance(geometry, PagedAttentionGeometry):
        raise ValueError(f"paged attention wants a PagedAttentionGeometry, "
                         f"got {type(geometry).__name__}")
    geometry.validate()
    # geometry values quantize onto this shape deterministically
    depth = _largest_divisor(M, geometry.kv_block_depth)
    R = Wr if geometry.q_rows == 0 else _largest_divisor(Wr, geometry.q_rows)
    NQ = Wr // R
    Mp = M // depth
    early = quantized and geometry.dequant == "early"
    # GQA: group query heads with their shared kv head so one kernel
    # instance covers the whole group — (B, KV, W*rep, D).
    qt = q.reshape(B, W, KV, rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, KV, Wr, D)
    # grid axes: the two parallel axes in the geometry's order, the
    # optional q-row tile axis, then the sequential kv-block axis; the
    # default (depth=1, full rows, "bgm") is exactly the pre-geometry
    # (B, KV, M) lowering
    axes = (["b", "g"] if geometry.grid_order == "bgm" else ["g", "b"])
    if NQ > 1:
        axes.append("q")
    axes.append("m")
    sizes = {"b": B, "g": KV, "q": NQ, "m": Mp}
    grid = tuple(sizes[a] for a in axes)
    ib, ig, im = axes.index("b"), axes.index("g"), axes.index("m")
    iq = axes.index("q") if NQ > 1 else None

    def q_map(*a):
        ids = a[:-2]
        return (ids[ib], ids[ig], ids[iq] if iq is not None else 0, 0)

    def kv_map(j):
        def f(*a):
            ids, tbl = a[:-2], a[-2]
            return (tbl[ids[ib], ids[im] * depth + j], 0, ids[ig], 0)
        return f

    def sc_map(j):
        def f(*a):
            ids, tbl = a[:-2], a[-2]
            return (tbl[ids[ib], ids[im] * depth + j], ids[ig])
        return f

    in_specs = [pl.BlockSpec((1, 1, R, D), q_map)]
    in_specs += [pl.BlockSpec((1, bs, 1, D), kv_map(j))
                 for j in range(depth)]
    in_specs += [pl.BlockSpec((1, bs, 1, D), kv_map(j))
                 for j in range(depth)]
    args = [tables.astype(jnp.int32), pos.astype(jnp.int32), qt]
    args += [k_pool] * depth + [v_pool] * depth
    if quantized:
        in_specs += [pl.BlockSpec((1, 1), sc_map(j)) for j in range(depth)]
        in_specs += [pl.BlockSpec((1, 1), sc_map(j)) for j in range(depth)]
        args += [k_scales.astype(jnp.float32)] * depth
        args += [v_scales.astype(jnp.float32)] * depth
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, R, D), q_map),
        scratch_shapes=[
            pltpu.VMEM((R, 128), jnp.float32),   # running max
            pltpu.VMEM((R, 128), jnp.float32),   # running sum
            pltpu.VMEM((R, D), jnp.float32),     # output accumulator
        ],
    )
    out = pl.pallas_call(
        functools.partial(_attn_kernel, bs=bs, W=W, rep=rep, Mp=Mp,
                          depth=depth, R=R, quantized=quantized,
                          early=early, ib=ib, ig=ig, iq=iq, im=im),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KV, Wr, D), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=tuple(
                "arbitrary" if a == "m" else "parallel" for a in axes)),
        interpret=_interpret(),
    )(*args)
    return out.reshape(B, KV, W, rep, D).transpose(0, 2, 1, 3, 4).reshape(
        B, W, H, D)


def paged_attention(q, k_pool, v_pool, block_tables, pos, geometry=None):
    """Fused paged decode/verify attention over an fp block pool.

    q: (B, W, H, D) — W=1 decode, W=tick_window verify, W=chunk prefill.
    pos: (B,) int — absolute position of each row's FIRST query token.
    geometry: trace-time :class:`PagedAttentionGeometry` (None = the
    process-wide winner cache, falling back to the default schedule).
    """
    return _paged_attention_call(q, k_pool, v_pool, block_tables, pos,
                                 geometry=geometry)


def paged_attention_q(q, kq_pool, k_scales, vq_pool, v_scales, block_tables,
                      pos, geometry=None):
    """Int8 twin: streams the code pool and dequantizes on the VMEM tile."""
    return _paged_attention_call(q, kq_pool, vq_pool, block_tables, pos,
                                 k_scales=k_scales, v_scales=v_scales,
                                 geometry=geometry)


# ----------------------------------------------------------------- LoRA BGMV
def _lora_kernel(x_ref, w_ref, a_ref, b_ref, s_ref, o_ref, *,
                 delta_first=False):
    x = x_ref[0]                               # (S, in)

    def base():
        return jax.lax.dot_general(x, w_ref[...], (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    def delta():
        xa = jax.lax.dot_general(x.astype(jnp.float32), a_ref[0],
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        return jax.lax.dot_general(xa, b_ref[0], (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)

    # accumulation layout: which chain issues first — the final combine
    # is the same expression either way (bit-exact)
    if delta_first:
        d = delta()
        y = base()
    else:
        y = base()
        d = delta()
    o_ref[0] = (y + d * s_ref[0, 0]).astype(o_ref.dtype)


def fused_lora_matmul(x, w, a, b, s, geometry=None):
    """Base projection + per-row LoRA delta in one program per batch row:
    ``x @ w + ((x32 @ a[i]) @ b[i]) * s[i]``. The factors are the per-slot
    gathers from AdapterPool.gather_rows — a (B, in, R), b (B, R, out),
    s (B,); null adapters arrive as zero factors with s=0, making the delta
    exactly zero (bit-identical to the plain matmul).

    ``geometry`` (:class:`LoRAGeometry`): rank padding (zero columns/rows
    contribute exact zeros — bit-exact, MXU-aligned contraction) and the
    matmul issue order."""
    from ..autotune.kernel_geometry import LoRAGeometry

    B, S, IN = x.shape
    OUT = w.shape[1]
    R = a.shape[2]
    if geometry is None:
        geometry = _resolve("fused_lora", str(x.dtype), R)
    if not isinstance(geometry, LoRAGeometry):
        raise ValueError(f"fused LoRA wants a LoRAGeometry, got "
                         f"{type(geometry).__name__}")
    geometry.validate()
    if not _interpret() and (IN % 128 or OUT % 128):
        raise NotImplementedError("projection dims not lane-aligned")
    rp = geometry.padded_rank(R)
    if rp != R:
        a = jnp.pad(a, ((0, 0), (0, 0), (0, rp - R)))
        b = jnp.pad(b, ((0, 0), (0, rp - R), (0, 0)))
        R = rp
    return pl.pallas_call(
        functools.partial(_lora_kernel,
                          delta_first=geometry.accum == "delta_first"),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, S, IN), lambda i: (i, 0, 0)),
            pl.BlockSpec((IN, OUT), lambda i: (0, 0)),
            pl.BlockSpec((1, IN, R), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, R, OUT), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((1, S, OUT), lambda i: (i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B, S, OUT), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(x, w, a, b, s.reshape(B, 1).astype(jnp.float32))
