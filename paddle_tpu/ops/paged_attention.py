"""Paged KV-cache attention — block-table decode, chunked-prefill and
multi-token (speculative verify) window ops.

The serving KV cache stops being a dense ``(max_batch, max_len, KV, D)``
slab and becomes a POOL of fixed-size blocks ``(num_blocks, block_size,
KV, D)`` plus per-request block tables (int32 rows of block ids). HBM is
then proportional to *active* tokens, not to ``max_batch · max_len``
(vLLM's PagedAttention, adapted to the XLA/TPU constraints: static shapes
everywhere, tables are data not shapes).

Layout is chosen Pallas-ready, mirroring the flash kernels in
``flash_attention.py``:

- pools are BLOCK-MAJOR ``(N, bs, KV, D)`` so one block's K (or V) is a
  contiguous ``(bs, KV, D)`` tile — exactly the unit a Mosaic kernel
  streams through VMEM;
- block tables are small int32 operands — on TPU they become
  ``PrefetchScalarGridSpec`` scalar-prefetch args feeding the K/V
  BlockSpec ``index_map`` (the kernel grid walks ``table[i]`` instead of
  ``i``, which is the whole trick of paged attention);
- the decode gather and the chunk scatter below are the pure-jnp
  REFERENCE path: CPU tier-1 runs it bit-for-bit.

The real kernels live in ``paged_attention_pallas.py``: one flash-style
online-softmax kernel covering decode (W=1), speculative verify
(W=tick_window) and chunked prefill (B=1), fp and int8-fused-dequant. The
public attention functions below dispatch to them under the shared
``ops.use_pallas()`` contract (TPU backend, ``PT_FLASH_INTERPRET=1``, or
``ops.set_kernel_mode("pallas")``) and otherwise run the jnp reference
via one parameterized ``_attention_core`` — a single seam instead of six
twins.

All masks/softmax run in fp32 with the same ``-1e30`` fill as the dense
decode path (``models/llama.py LlamaAttention.decode``) so greedy outputs
stay token-exact between dense and paged servers.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def gather_block_kv(pool, block_tables):
    """Gather per-row K (or V) context from the block pool.

    pool: (N, bs, KV, D); block_tables: int32 (B, M) (or (M,) for one
    row). Returns (B, M*bs, KV, D) — the dense-equivalent context window,
    where table entry 0 conventionally points at the scratch block and is
    masked out by the caller's position mask.
    """
    bt = block_tables if block_tables.ndim == 2 else block_tables[None]
    gathered = pool[bt]                       # (B, M, bs, KV, D)
    b, m, bs = gathered.shape[:3]
    return gathered.reshape(b, m * bs, *pool.shape[2:])


def write_window_kv(k_pool, v_pool, k, v, block_tables, pos):
    """Scatter a WINDOW of new tokens' K/V per row through the block table.

    k/v: (B, W, KV, D); block_tables: (B, M); pos: int32 (B,) — row ``b``'s
    token ``j`` lands at position ``pos[b] + j``, i.e. at
    ``(table[b, (pos+j)//bs], (pos+j)%bs)``. W = 1 is the plain decode
    write; W = k+1 is the speculative verify window (positions past the
    accepted prefix hold rejected-token K/V that the NEXT window
    overwrites before any query can attend it). Rows the server parked on
    the scratch block (idle/prefilling slots) harmlessly overwrite
    scratch.
    """
    bs = k_pool.shape[1]
    W = k.shape[1]
    pj = pos[:, None] + jnp.arange(W)[None, :]          # (B, W)
    bid = jnp.take_along_axis(block_tables, pj // bs, axis=1)
    off = pj % bs
    k_pool = k_pool.at[bid, off].set(k.astype(k_pool.dtype))
    v_pool = v_pool.at[bid, off].set(v.astype(v_pool.dtype))
    return k_pool, v_pool


def write_decode_kv(k_pool, v_pool, k, v, block_tables, pos):
    """Scatter ONE new token's K/V per row — :func:`write_window_kv` at
    W = 1. k/v: (B, KV, D)."""
    return write_window_kv(k_pool, v_pool, k[:, None], v[:, None],
                           block_tables, pos)


def write_chunk_kv(k_pool, v_pool, k, v, block_table, start):
    """Scatter a prefill CHUNK's K/V into consecutive table entries.

    k/v: (C, KV, D) with C a multiple of ``bs``; block_table: (M,);
    start: traced int32, block-aligned chunk origin. The chunk occupies
    table entries [start//bs, start//bs + C//bs) — a dynamic_slice of the
    table, then one blocked scatter (the Pallas version would walk the
    same slice as scalar-prefetch grid indices).
    """
    bs = k_pool.shape[1]
    nb = k.shape[0] // bs
    blocks = jax.lax.dynamic_slice_in_dim(block_table, start // bs, nb, 0)
    k_pool = k_pool.at[blocks].set(
        k.reshape(nb, bs, *k.shape[1:]).astype(k_pool.dtype))
    v_pool = v_pool.at[blocks].set(
        v.reshape(nb, bs, *v.shape[1:]).astype(v_pool.dtype))
    return k_pool, v_pool


def _attention_core(q, ck, cv, qpos, ksl=None, vsl=None):
    """The ONE parameterized jnp attention skeleton behind all six public
    attention entry points — grouped GQA einsum, fp32 scores, positional
    causal mask, fp32 softmax. ``qpos`` is the (B, S) absolute position of
    every query row/token; fused int8 dequant engages when the per-token
    ``ksl``/``vsl`` scale views (B, L, KV) are given — k's scale multiplies
    the fp32 QK accumulator ((q·k_q)·s == q·(k_q·s), per-kv-head scales
    commute with the D-contraction), v's scale folds into p before the V
    accumulation (p·(v_q·s) == (p·s)·v_q), so a dequantized pool is never
    materialized. Bit-identical to the pre-dedupe twins."""
    B, S, H, D = q.shape
    KV = ck.shape[2]
    rep = H // KV
    L = ck.shape[1]
    qg = q.reshape(B, S, KV, rep, D)
    quantized = ksl is not None
    ckc = ck.astype(q.dtype) if quantized else ck
    cvc = cv.astype(q.dtype) if quantized else cv
    scores = jnp.einsum("bsgrd,btgd->bgrst", qg, ckc).astype(jnp.float32)
    if quantized:
        scores = scores * jnp.transpose(ksl, (0, 2, 1))[:, :, None, None, :] \
            / math.sqrt(D)
    else:
        scores = scores / math.sqrt(D)
    mask = (jnp.arange(L)[None, None, :] <=
            qpos[:, :, None])[:, None, None, :, :]
    scores = jnp.where(mask, scores, NEG_INF)
    p = jax.nn.softmax(scores, -1).astype(q.dtype)
    if quantized:
        p = p * jnp.transpose(vsl, (0, 2, 1))[:, :, None, None, :].astype(
            p.dtype)
    out = jnp.einsum("bgrst,btgd->bsgrd", p, cvc)
    return out.reshape(B, S, H, D)


def _try_pallas(q, k_pool, v_pool, tables, pos, ks=None, vs=None):
    """Trace-time kernel dispatch: returns the Pallas result when the
    shared ``use_pallas()`` contract says so and the shapes compile, else
    None (caller runs the jnp reference). NotImplementedError is the
    kernels' unaligned-shape signal."""
    from . import use_pallas

    if not use_pallas():
        return None
    from . import paged_attention_pallas as pk

    try:
        if ks is None:
            return pk.paged_attention(q, k_pool, v_pool, tables, pos)
        return pk.paged_attention_q(q, k_pool, ks, v_pool, vs, tables, pos)
    except NotImplementedError:
        return None


def paged_verify_attention(q, k_pool, v_pool, block_tables, pos):
    """Multi-token verify attention through block tables (GQA-native) —
    the decode window generalized from 1 to W positions.

    q: (B, W, H, D) rope'd queries at positions ``pos[b] + arange(W)``;
    pools: (N, bs, KV, D); block_tables: (B, M); pos: int32 (B,) window
    start per row (the window's K/V must already be written at
    ``pos..pos+W-1``, :func:`write_window_kv`). IN-WINDOW CAUSAL MASK:
    query j attends context positions ``<= pos[b] + j`` — earlier window
    tokens are visible, later ones (and any stale rejected K/V beyond the
    window) are not. W = 1 reduces exactly to single-token decode.
    Dispatches to the Pallas kernel (``use_pallas()``); the jnp reference
    keeps scratch-block-0 masking — zeroed table rows write and read only
    scratch — and the same grouped einsum / fp32-softmax as the dense
    ``LlamaAttention.decode`` vector-pos path so greedy speculative output
    is token-exact vs the dense server.
    """
    W = q.shape[1]
    bt = block_tables if block_tables.ndim == 2 else block_tables[None]
    out = _try_pallas(q, k_pool, v_pool, bt, pos)
    if out is not None:
        return out
    ck = gather_block_kv(k_pool, bt)              # (B, L, KV, D)
    cv = gather_block_kv(v_pool, bt)
    qpos = pos[:, None] + jnp.arange(W)[None, :]  # (B, W)
    return _attention_core(q, ck, cv, qpos)


def paged_decode_attention(q, k_pool, v_pool, block_tables, pos):
    """Single-token decode attention — :func:`paged_verify_attention` at
    W = 1 (mask ``arange(L) <= pos + 0`` is the plain ``<= pos``).
    q: (B, 1, H, D)."""
    return paged_verify_attention(q, k_pool, v_pool, block_tables, pos)


def paged_prefill_attention(q, k_pool, v_pool, block_table, start):
    """Chunked-prefill attention: one chunk of queries against ALL paged
    context written so far (earlier chunks + shared prefix blocks) plus
    the causal part of the chunk itself.

    q: (1, C, H, D) rope'd queries at positions ``start + arange(C)``;
    block_table: (M,) single request row; the chunk's K/V must already be
    scattered into the pool (``write_chunk_kv``). Key positions beyond a
    query's position are masked, so right-pad garbage in the final chunk
    and unallocated (scratch) table entries never reach a real query.
    Prefill is the verify kernel at B=1, W=C, pos=[start].
    """
    C = q.shape[1]
    bt = block_table if block_table.ndim == 2 else block_table[None]
    start_v = jnp.full((1,), start, jnp.int32)
    out = _try_pallas(q, k_pool, v_pool, bt, start_v)
    if out is not None:
        return out
    ck = gather_block_kv(k_pool, bt)              # (1, L, KV, D)
    cv = gather_block_kv(v_pool, bt)
    qpos = (start + jnp.arange(C))[None, :]       # (1, C)
    return _attention_core(q, ck, cv, qpos)


# --------------------------------------------------------------------------- #
# Quantized pool (kv_quant="int8"): the pool stores int8 codes
# (num_blocks, bs, KV, D) plus one f32 scale per (block, kv-head)
# (num_blocks, KV) — symmetric absmax, value ≈ code · scale. Half the HBM
# bytes of bf16 (a quarter of f32), so ~2× resident blocks at the same pool
# budget AND ~2× less KV traffic per decode step. The attention twins below
# fold the dequant INTO the program — scale lands on the QK accumulator and
# on p before the V accumulation — so a full-precision pool is never
# materialized (XLA fuses the scale multiply into the surrounding einsum;
# a Pallas kernel would apply it on the VMEM tile). int8 codes (|q| ≤ 127)
# are exact in bf16/f32, so the only error is the quantization rounding.
# --------------------------------------------------------------------------- #

_QEPS = 1e-8   # scale floor: an all-zero block quantizes to scale ~0 with
               # zero codes instead of dividing by zero


def quantize_block_kv(x):
    """(N, bs, KV, D) float → ((N, bs, KV, D) int8, (N, KV) f32 scale);
    symmetric absmax per block per kv head."""
    xf = jnp.asarray(x).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=(1, 3))                  # (N, KV)
    scale = jnp.maximum(absmax, _QEPS) / 127.0
    q = jnp.clip(jnp.round(xf / scale[:, None, :, None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_block_kv(q, scale):
    """Inverse of :func:`quantize_block_kv` — TEST/reference helper only;
    the serving programs never materialize this."""
    return q.astype(jnp.float32) * scale[:, None, :, None]


def _insert_token_q(qpool, scales, tok, bid, off):
    """Insert one token's K (or V) (B, KV, D) at slot ``off`` of block
    ``bid`` per row, requantizing each touched block in ONE pass: the block
    is reconstructed (codes · scale), the token dropped in, the per-head
    absmax recomputed and the whole block re-coded. When the new token
    does not move a head's absmax the scale is unchanged and old codes
    round-trip exactly (round(q·s/s) == q); only a scale-raising outlier
    re-rounds its block, bounding the error at scale/2 per value."""
    B = tok.shape[0]
    rows = jnp.arange(B)
    blk = qpool[bid].astype(jnp.float32) * \
        scales[bid][:, None, :, None]                   # (B, bs, KV, D)
    blk = blk.at[rows, off].set(tok.astype(jnp.float32))
    amax = jnp.max(jnp.abs(blk), axis=(1, 3))           # (B, KV)
    ns = jnp.maximum(amax, _QEPS) / 127.0
    q = jnp.clip(jnp.round(blk / ns[:, None, :, None]), -127,
                 127).astype(jnp.int8)
    # duplicate bids only occur for masked rows parked on scratch block 0
    # (whichever row wins, the content stays finite and is never attended)
    return qpool.at[bid].set(q), scales.at[bid].set(ns)


def write_window_kv_q(kq, ks, vq, vs, k, v, block_tables, pos):
    """Quantizing twin of :func:`write_window_kv`: scatter a WINDOW of new
    tokens' K/V into the int8 pool, rescaling each touched block in one
    pass per token. k/v: (B, W, KV, D) float; kq/vq: (N, bs, KV, D) int8;
    ks/vs: (N, KV) f32. W is small and static (1 = decode, k+1 = verify
    window), so the per-token walk unrolls at trace time — a Pallas kernel
    would fold the whole window into one block pass."""
    bs = kq.shape[1]
    W = k.shape[1]
    for j in range(W):
        pj = pos + j
        bid = jnp.take_along_axis(block_tables, (pj // bs)[:, None],
                                  axis=1)[:, 0]
        off = pj % bs
        kq, ks = _insert_token_q(kq, ks, k[:, j], bid, off)
        vq, vs = _insert_token_q(vq, vs, v[:, j], bid, off)
    return kq, ks, vq, vs


def write_decode_kv_q(kq, ks, vq, vs, k, v, block_tables, pos):
    """Quantizing twin of :func:`write_decode_kv` — one token per row.
    k/v: (B, KV, D)."""
    return write_window_kv_q(kq, ks, vq, vs, k[:, None], v[:, None],
                             block_tables, pos)


def write_chunk_kv_q(kq, ks, vq, vs, k, v, block_table, start):
    """Quantizing twin of :func:`write_chunk_kv`: a prefill chunk fully
    overwrites its blocks, so each block is quantized FRESH (no rescale
    pass). k/v: (C, KV, D), C a multiple of ``bs``."""
    bs = kq.shape[1]
    nb = k.shape[0] // bs
    blocks = jax.lax.dynamic_slice_in_dim(block_table, start // bs, nb, 0)
    knew, ksn = quantize_block_kv(k.reshape(nb, bs, *k.shape[1:]))
    vnew, vsn = quantize_block_kv(v.reshape(nb, bs, *v.shape[1:]))
    return (kq.at[blocks].set(knew), ks.at[blocks].set(ksn),
            vq.at[blocks].set(vnew), vs.at[blocks].set(vsn))


def gather_block_scales(scales, block_tables, block_size):
    """Per-TOKEN scale view of the per-block scales: (N, KV) gathered
    through (B, M) tables and repeated across the block → (B, L, KV),
    L = M·block_size — aligned with :func:`gather_block_kv`'s context."""
    bt = block_tables if block_tables.ndim == 2 else block_tables[None]
    return jnp.repeat(scales[bt], block_size, axis=1)


def paged_verify_attention_q(q, kq, ks, vq, vs, block_tables, pos):
    """Fused-dequant twin of :func:`paged_verify_attention`: attention
    reads int8 K/V codes and applies the per-block-per-head scales INSIDE
    the program (see :func:`_attention_core`) — never materializing a
    dequantized pool. Masking / softmax semantics are identical to the fp
    twin. The Pallas kernel applies the same scales on the VMEM tile."""
    W = q.shape[1]
    bs = kq.shape[1]
    bt = block_tables if block_tables.ndim == 2 else block_tables[None]
    out = _try_pallas(q, kq, vq, bt, pos, ks=ks, vs=vs)
    if out is not None:
        return out
    ckq = gather_block_kv(kq, bt)                 # (B, L, KV, D) int8
    cvq = gather_block_kv(vq, bt)
    ksl = gather_block_scales(ks, bt, bs)         # (B, L, KV) f32
    vsl = gather_block_scales(vs, bt, bs)
    qpos = pos[:, None] + jnp.arange(W)[None, :]  # (B, W)
    return _attention_core(q, ckq, cvq, qpos, ksl=ksl, vsl=vsl)


def paged_decode_attention_q(q, kq, ks, vq, vs, block_tables, pos):
    """Single-token fused-dequant decode — :func:`paged_verify_attention_q`
    at W = 1. q: (B, 1, H, D)."""
    return paged_verify_attention_q(q, kq, ks, vq, vs, block_tables, pos)


def paged_prefill_attention_q(q, kq, ks, vq, vs, block_table, start):
    """Fused-dequant twin of :func:`paged_prefill_attention` — one prefill
    chunk of queries against the quantized paged context (the verify
    kernel at B=1, W=C, pos=[start])."""
    C = q.shape[1]
    bs = kq.shape[1]
    bt = block_table if block_table.ndim == 2 else block_table[None]
    start_v = jnp.full((1,), start, jnp.int32)
    out = _try_pallas(q, kq, vq, bt, start_v, ks=ks, vs=vs)
    if out is not None:
        return out
    ckq = gather_block_kv(kq, bt)                 # (1, L, KV, D) int8
    cvq = gather_block_kv(vq, bt)
    ksl = gather_block_scales(ks, bt, bs)         # (1, L, KV) f32
    vsl = gather_block_scales(vs, bt, bs)
    qpos = (start + jnp.arange(C))[None, :]       # (1, C)
    return _attention_core(q, ckq, cvq, qpos, ksl=ksl, vsl=vsl)
