"""paddle.linalg namespace (ref: python/paddle/linalg.py re-exports)."""
from ..tensor.linalg import (cdist, cholesky, cholesky_solve, cond, det, dist, eig, eigh,
                             eigvals, eigvalsh, householder_product, inv, lstsq, lu, lu_unpack,
                             matrix_exp, matrix_norm, matrix_power, matrix_rank, multi_dot,
                             norm, ormqr, pca_lowrank, pinv, qr, slogdet, solve, svd,
                             svd_lowrank, svdvals, triangular_solve, vector_norm, matmul, bmm,
                             mm, dot, corrcoef)
from ..tensor.math import cross

__all__ = [n for n in dir() if not n.startswith("_")]
