"""paddle.hub parity (ref: python/paddle/hub.py). Zero-egress environment:
only local-dir sources work; github sources raise with guidance."""
from __future__ import annotations

import importlib.util
import os
import sys


def _load_hubconf(repo_dir: str):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def list(repo_dir: str, source: str = "local", force_reload: bool = False):
    if source != "local":
        raise RuntimeError("paddle_tpu.hub supports source='local' only (no egress)")
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod) if not n.startswith("_") and callable(getattr(mod, n))]


def help(repo_dir: str, model: str, source: str = "local", force_reload: bool = False):
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model).__doc__


def load(repo_dir: str, model: str, source: str = "local", force_reload: bool = False,
         **kwargs):
    if source != "local":
        raise RuntimeError("paddle_tpu.hub supports source='local' only (no egress)")
    mod = _load_hubconf(repo_dir)
    return getattr(mod, model)(**kwargs)
