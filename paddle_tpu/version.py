"""paddle.version parity (ref python/paddle/version.py is build-generated).

Versioning note: `major.minor` tracks the reference API surface this build
targets (Paddle ~2.5 era, SURVEY.md header); the local build has no CUDA —
cuda()/cudnn() return the reference's "not compiled" sentinel 'False'.
"""

full_version = "2.5.0+tpu"
major = "2"
minor = "5"
patch = "0"
rc = "0"
istaged = False
commit = "unknown"
with_mkl = "OFF"


def show() -> None:
    print(f"full_version: {full_version}")
    print(f"major: {major}")
    print(f"minor: {minor}")
    print(f"patch: {patch}")
    print(f"commit: {commit}")


def cuda() -> str:
    return "False"


def cudnn() -> str:
    return "False"


def xpu() -> str:
    return "False"


def xpu_xccl() -> str:
    return "False"
