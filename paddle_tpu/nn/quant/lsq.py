"""LSQ/LSQ+ learned-step-size quantizers (ref: python/paddle/nn/quant/lsq.py).

TPU design: the straight-through estimator with learned scale (and offset for
activations) is expressed with jnp + stop_gradient, so the whole quantizer
stays inside the jitted graph — no PyLayer needed.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from ...framework.core import Parameter, Tensor
from ...framework.dispatch import apply_op
from ..initializer import Constant
from ..layer_base import Layer

__all__ = ["FakeQuantActLSQPlus", "FakeQuantWeightLSQPlus"]


def _round_ste(x):
    return x + lax.stop_gradient(jnp.round(x) - x)


def _grad_scale(x, scale):
    # y = x in value, but grad(y) = grad(x) * scale (LSQ gradient scaling)
    return x * scale + lax.stop_gradient(x - x * scale)


class FakeQuantActLSQPlus(Layer):
    """Activation LSQ+ quantizer with learned scale + offset (ref lsq.py:137)."""

    def __init__(self, quant_bits=8, all_postive=False, symmetric=True,
                 batch_init=20, dtype='float32', name=None, reduce_type=None):
        super().__init__()
        if all_postive:
            self.qmin, self.qmax = 0, 2 ** quant_bits - 1
        else:
            self.qmin = -2 ** (quant_bits - 1)
            self.qmax = 2 ** (quant_bits - 1) - 1
        self.symmetric = symmetric
        self.s = self.create_parameter([1], default_initializer=Constant(1.0))
        self.beta = self.create_parameter(
            [1], default_initializer=Constant(0.0))

    def forward(self, x):
        def _q(xv, s, beta):
            g = 1.0 / math.sqrt(xv.size * self.qmax) if xv.size else 1.0
            s_ = jnp.maximum(_grad_scale(s, g), 1e-7)
            if self.symmetric:
                q = jnp.clip(_round_ste(xv / s_), self.qmin, self.qmax)
                return q * s_
            b_ = _grad_scale(beta, g)
            q = jnp.clip(_round_ste((xv - b_) / s_), self.qmin, self.qmax)
            return q * s_ + b_

        return apply_op(_q, x, self.s, self.beta)


class FakeQuantWeightLSQPlus(Layer):
    """Weight LSQ+ quantizer, optionally per-channel (ref lsq.py:248)."""

    def __init__(self, quant_bits=8, all_postive=False, per_channel=False,
                 batch_init=20, channel_num=None, quant_linear=False,
                 dtype='float32', name=None, reduce_type=None):
        super().__init__()
        self.qmin = -2 ** (quant_bits - 1)
        self.qmax = 2 ** (quant_bits - 1) - 1
        self.per_channel = per_channel
        # channel axis: conv weights are [out, in, ...] (axis 0); Linear
        # weights in this codebase are [in, out] (quant_linear -> last axis)
        self.quant_axis = -1 if quant_linear else 0
        n = channel_num if (per_channel and channel_num) else 1
        self.s = self.create_parameter([n], default_initializer=Constant(1.0))
        self._initialized = False

    def forward(self, w):
        wv = w.value if isinstance(w, Tensor) else jnp.asarray(w)
        axis = self.quant_axis % wv.ndim
        if not self._initialized:
            # LSQ init: s = 2*mean(|w|)/sqrt(qmax)
            if self.per_channel and self.s.shape[0] > 1:
                axes = tuple(i for i in range(wv.ndim) if i != axis)
                init = 2 * jnp.mean(jnp.abs(wv), axis=axes) / math.sqrt(self.qmax)
            else:
                init = jnp.full((self.s.shape[0],),
                                2 * jnp.mean(jnp.abs(wv)) / math.sqrt(self.qmax))
            self.s._value = init.astype(self.s.value.dtype)
            self._initialized = True

        def _q(wv, s):
            g = 1.0 / math.sqrt(wv.size * self.qmax) if wv.size else 1.0
            s_ = jnp.maximum(_grad_scale(s, g), 1e-7)
            if self.per_channel and s_.shape[0] > 1:
                bshape = [1] * wv.ndim
                bshape[axis] = -1
                s_ = s_.reshape(bshape)
            q = jnp.clip(_round_ste(wv / s_), self.qmin, self.qmax)
            return q * s_

        return apply_op(_q, w, self.s)
