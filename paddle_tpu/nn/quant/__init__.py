"""paddle.nn.quant parity (ref: python/paddle/nn/quant/__init__.py)."""
from . import functional_layers  # noqa: F401
from .functional_layers import (  # noqa: F401
    add, concat, divide, flatten, matmul, multiply, reshape, subtract,
    transpose,
)
from .lsq import FakeQuantActLSQPlus, FakeQuantWeightLSQPlus  # noqa: F401
from .quant_layers import (  # noqa: F401
    Int8Linear,
    FakeQuantAbsMax, FakeQuantChannelWiseAbsMax, FakeQuantMAOutputScaleLayer,
    FakeQuantMovingAverageAbsMax, MAOutputScaleLayer, MovingAverageAbsMaxScale,
    QuantizedColumnParallelLinear, QuantizedConv2D, QuantizedConv2DTranspose,
    QuantizedLinear, QuantizedMatmul, QuantizedRowParallelLinear,
)

__all__ = []
