"""QAT layer wrappers (ref: python/paddle/nn/quant/quant_layers.py).

TPU design: fake-quant is a straight-through-estimator elementwise op that XLA
fuses into the surrounding matmul/conv; "quantized" layers are their float
layers with weight/activation fake-quant applied in forward. The
moving-average observers reuse paddle_tpu.quantization's observer machinery.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...quantization import FakeQuanterWithAbsMaxObserverLayer, fake_quant
from .. import functional as F
from ..layer_base import Layer

__all__ = [
    "FakeQuantAbsMax", "FakeQuantMovingAverageAbsMax",
    "FakeQuantChannelWiseAbsMax", "MovingAverageAbsMaxScale",
    "QuantizedConv2D", "QuantizedConv2DTranspose", "QuantizedLinear",
    "QuantizedColumnParallelLinear", "QuantizedRowParallelLinear",
    "QuantizedMatmul", "MAOutputScaleLayer", "FakeQuantMAOutputScaleLayer",
]


class FakeQuantAbsMax(Layer):
    """Per-tensor abs-max fake quant (ref quant_layers.py:50)."""

    def __init__(self, name=None, quant_bits=8, dtype='float32',
                 quant_on_weight=False, reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits

    def forward(self, x):
        return fake_quant(x, bits=self._quant_bits)


class FakeQuantChannelWiseAbsMax(Layer):
    """Per-channel abs-max fake quant (ref quant_layers.py:289)."""

    def __init__(self, name=None, channel_num=None, quant_bits=8,
                 quant_axis=0, dtype='float32', quant_on_weight=False,
                 reduce_type=None):
        super().__init__()
        self._quant_bits = quant_bits
        self._quant_axis = quant_axis

    def forward(self, x):
        axes = tuple(i for i in range(x.ndim) if i != self._quant_axis)
        return fake_quant(x, bits=self._quant_bits, axis=axes)


class FakeQuantMovingAverageAbsMax(FakeQuanterWithAbsMaxObserverLayer):
    """Moving-average abs-max activation fake quant (ref quant_layers.py:150)."""

    def __init__(self, name=None, moving_rate=0.9, quant_bits=8,
                 dtype='float32', reduce_type=None):
        super().__init__(moving_rate=moving_rate, bit_length=quant_bits)


class MovingAverageAbsMaxScale(Layer):
    """Records moving-average output scale without quantizing
    (ref quant_layers.py:399)."""

    def __init__(self, name=None, moving_rate=0.9, dtype='float32',
                 reduce_type=None):
        super().__init__()
        self._moving_rate = moving_rate
        self.register_buffer("scale", jnp.zeros([], dtype=dtype))

    def forward(self, x):
        import jax

        cur = jnp.max(jnp.abs(jnp.asarray(
            x.value if hasattr(x, "value") else x))).astype(self.scale.dtype)
        # trace-safe: under jit the update is skipped (a tracer must not leak
        # into layer state); eagerly the scale stays on-device, no host sync
        if not isinstance(cur, jax.core.Tracer):
            self.scale._value = (self._moving_rate * self.scale.value
                                 + (1 - self._moving_rate) * cur)
        return x


class _QuantPair(Layer):
    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, weight_quantize_type='abs_max',
                 activation_quantize_type='moving_average_abs_max'):
        super().__init__()
        self.inner = layer
        if activation_quantize_type == 'moving_average_abs_max':
            self._fake_quant_input = FakeQuantMovingAverageAbsMax(
                moving_rate=moving_rate, quant_bits=activation_bits)
        else:
            self._fake_quant_input = FakeQuantAbsMax(quant_bits=activation_bits)
        if weight_quantize_type == 'channel_wise_abs_max':
            self._fake_quant_weight = FakeQuantChannelWiseAbsMax(
                quant_bits=weight_bits)
        else:
            self._fake_quant_weight = FakeQuantAbsMax(quant_bits=weight_bits)

    def _qw(self):
        return self._fake_quant_weight(self.inner.weight)

    def _qx(self, x):
        return self._fake_quant_input(x)


class QuantizedConv2D(_QuantPair):
    """Conv2D with fake-quant on weight + input (ref quant_layers.py:515)."""

    def forward(self, x):
        c = self.inner
        return F.conv2d(self._qx(x), self._qw(), c.bias, stride=c._stride,
                        padding=c._padding, dilation=c._dilation,
                        groups=c._groups, data_format=c._data_format)


class QuantizedConv2DTranspose(_QuantPair):
    """Conv2DTranspose with fake quant (ref quant_layers.py:614)."""

    def forward(self, x):
        c = self.inner
        return F.conv2d_transpose(
            self._qx(x), self._qw(), c.bias, stride=c._stride,
            padding=c._padding, dilation=c._dilation, groups=c._groups,
            output_padding=getattr(c, "_output_padding", 0),
            data_format=c._data_format)


class QuantizedLinear(_QuantPair):
    """Linear with fake quant (ref quant_layers.py:730)."""

    def forward(self, x):
        return F.linear(self._qx(x), self._qw(), self.inner.bias)


class QuantizedColumnParallelLinear(_QuantPair):
    """TP column-parallel linear with fake quant (ref quant_layers.py:807).
    Quantization is per-shard; the gather/allreduce stays in the inner layer."""

    def forward(self, x):
        inner = self.inner
        w = self._qw()
        orig_w = inner.weight
        try:
            inner.weight = w
            return inner(self._qx(x))
        finally:
            inner.weight = orig_w


class QuantizedRowParallelLinear(QuantizedColumnParallelLinear):
    """TP row-parallel linear with fake quant (ref quant_layers.py:903)."""


class QuantizedMatmul(Layer):
    """matmul with fake quant on both operands (ref quant_layers.py:1003)."""

    def __init__(self, weight_bits=8, activation_bits=8, moving_rate=0.9,
                 **kw):
        super().__init__()
        self._bits = activation_bits

    def forward(self, x, y, transpose_x=False, transpose_y=False, name=None):
        from ... import tensor as T

        return T.matmul(fake_quant(x, self._bits), fake_quant(y, self._bits),
                        transpose_x=transpose_x, transpose_y=transpose_y)


class MAOutputScaleLayer(Layer):
    """Wrap a layer, record its output moving-average scale
    (ref quant_layers.py:1062)."""

    def __init__(self, layer=None, moving_rate=0.9, name=None,
                 dtype='float32', reduce_type=None):
        super().__init__()
        self._layer = layer
        self._ma_output_scale = MovingAverageAbsMaxScale(
            name, moving_rate, dtype)

    def forward(self, *inputs, **kwargs):
        out = self._layer(*inputs, **kwargs)
        if isinstance(out, (list, tuple)) and len(out) > 1:
            return out
        return self._ma_output_scale(out)


class FakeQuantMAOutputScaleLayer(Layer):
    """Wrap a layer, fake-quant its output with a moving-average scale
    (ref quant_layers.py:1100)."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9, name=None, *args, **kwargs):
        super().__init__()
        self._layer = layer
        self._fake_quant_output = FakeQuantMovingAverageAbsMax(
            moving_rate=moving_rate, quant_bits=activation_bits)

    def forward(self, *inputs, **kwargs):
        out = self._layer(*inputs, **kwargs)
        if isinstance(out, (list, tuple)) and len(out) > 1:
            return out
        return self._fake_quant_output(out)


class Int8Linear(Layer):
    """Weight-only int8 linear for HBM-bound decode (ref
    fused_multi_transformer_int8_op.cu weight-only path; see ops/int8.py).

    Holds w_q (int8, [K,N]) and per-channel scale as BUFFERS so
    jit.state_values / functional_call carry them through compiled
    generation. Built from a trained Linear via ``from_linear``."""

    def __init__(self, w_q, scale, bias=None, name=None):
        super().__init__()
        from ...framework.core import Tensor as _T

        self.register_buffer("weight_q", _T(w_q))
        self.register_buffer("weight_scale", _T(scale))
        self._has_bias = bias is not None
        if self._has_bias:
            self.register_buffer("bias", bias)
        self.in_features = int(w_q.shape[0])
        self.out_features = int(w_q.shape[1])

    @classmethod
    def from_linear(cls, linear):
        from ...ops.int8 import quantize_per_channel

        w_q, scale = quantize_per_channel(linear.weight.value)
        return cls(w_q, scale, bias=getattr(linear, "bias", None))

    def forward(self, x):
        from ...framework.dispatch import apply_op
        from ...ops.int8 import w8_matmul

        if self._has_bias:
            return apply_op(lambda v, wq, s, b: w8_matmul(v, wq, s) + b,
                            x, self.weight_q, self.weight_scale, self.bias,
                            op_name="w8_linear")
        return apply_op(lambda v, wq, s: w8_matmul(v, wq, s),
                        x, self.weight_q, self.weight_scale,
                        op_name="w8_linear")
