"""Layer wrappers of functional ops so QAT passes can hook their outputs
(ref: python/paddle/nn/quant/functional_layers.py)."""
from __future__ import annotations

from ... import tensor as T
from ..layer_base import Layer

__all__ = []


class FloatFunctionalLayer(Layer):
    def __init__(self):
        super().__init__()


class add(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return T.add(x, y)


class subtract(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return T.subtract(x, y)


class multiply(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return T.multiply(x, y)


class divide(FloatFunctionalLayer):
    def forward(self, x, y, name=None):
        return T.divide(x, y)


class reshape(FloatFunctionalLayer):
    def forward(self, x, shape, name=None):
        return T.reshape(x, shape)


class transpose(FloatFunctionalLayer):
    def forward(self, x, perm, name=None):
        return T.transpose(x, perm)


class concat(FloatFunctionalLayer):
    def forward(self, x, axis=0, name=None):
        return T.concat(x, axis)


class flatten(FloatFunctionalLayer):
    def forward(self, x, start_axis=0, stop_axis=-1, name=None):
        return T.flatten(x, start_axis, stop_axis)


class matmul(FloatFunctionalLayer):
    def forward(self, x, y, transpose_x=False, transpose_y=False, name=None):
        return T.matmul(x, y, transpose_x, transpose_y)
