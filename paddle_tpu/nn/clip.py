"""Gradient clipping (ref: python/paddle/fluid/clip.py ClipGradByGlobalNorm etc.)."""
from __future__ import annotations

import functools

import jax.numpy as jnp

from ..framework.core import Tensor
from ..framework.dispatch import apply_op


class ClipGradBase:
    def __call__(self, params_grads):
        return self._dygraph_clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, apply_op(lambda v: jnp.clip(v, self.min, self.max), g)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def _dygraph_clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue

            def f(v):
                n = jnp.sqrt(jnp.sum(jnp.square(v.astype(jnp.float32))))
                scale = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
                return (v * scale).astype(v.dtype)

            out.append((p, apply_op(f, g)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Ref fluid/clip.py ClipGradByGlobalNorm. In hybrid-parallel the global
    norm is additionally reduced across model-parallel groups — see
    distributed.fleet HybridParallelClipGrad (ref
    hybrid_parallel_optimizer.py:45)."""

    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = clip_norm

    def _dygraph_clip(self, params_grads):
        grads = [g for _, g in params_grads if g is not None]
        if not grads:
            return params_grads
        # ONE traced reduction tree — the old per-grad float() was a
        # blocking device->host sync per gradient per step; the scale now
        # stays a 0-d device scalar end to end (same math as the compiled
        # path's _pure_grad_clip, so eager and jit stay bit-consistent)
        sq = sum(jnp.sum(jnp.square(g.value.astype(jnp.float32)))
                 for g in grads)
        scale = jnp.minimum(
            self.clip_norm / jnp.maximum(jnp.sqrt(sq), 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, apply_op(lambda v: (v * scale).astype(v.dtype), g)))
        return out


GradientClipBase = ClipGradBase
GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    # traced reduction tree + unconditional min(scale, 1) multiply: no
    # per-grad host sync and no Python branch on a device scalar (the
    # scale==1 multiply is exact, so numerics match the old branchy form)
    if norm_type == float("inf"):
        total = functools.reduce(
            jnp.maximum, (jnp.max(jnp.abs(g.value)) for g in grads))
    else:
        total = sum(jnp.sum(jnp.power(jnp.abs(g.value.astype(jnp.float32)),
                                      norm_type))
                    for g in grads) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad = Tensor((p.grad.value * scale).astype(p.grad.value.dtype))
    return Tensor(jnp.asarray(total))


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad = Tensor(jnp.clip(p.grad.value, -clip_value, clip_value))
