"""Norm layers (ref: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .. import functional as F
from ..initializer import Constant
from ..layer_base import Layer
from ...framework.core import Tensor


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features], jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features], jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid-style BatchNorm (ref fluid.dygraph.BatchNorm)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05, **kwargs):
        super().__init__(num_channels, momentum, epsilon)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN. Under pjit, batch stats are computed over the global
    batch automatically when the batch axis is sharded (GSPMD inserts the
    cross-device mean) — the explicit NCCL allreduce of the reference
    (ref python/paddle/nn/layer/norm.py SyncBatchNorm) is unnecessary."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            out = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon)
            if layer.weight is not None:
                out.weight = layer.weight
            if layer.bias is not None:
                out.bias = layer.bias
            out._mean = layer._mean
            out._variance = layer._variance
        for name, sub in list(layer._sub_layers.items()):
            out._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return out


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else \
            [normalized_shape]
        self._normalized_shape = [int(n) for n in ns]
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            self._normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}, epsilon={self._epsilon}"


class RMSNorm(Layer):
    """LLaMA-style RMSNorm (new; the reference lacks it — see SURVEY §5.7 note)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else \
            [normalized_shape]
        self._normalized_shape = [int(n) for n in ns]
        self._epsilon = epsilon
        self.weight = self.create_parameter(self._normalized_shape, attr=weight_attr,
                                            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._num_channels = num_channels
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight, self.bias,
                            self._data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_features], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias, eps=self._epsilon)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12, dtype="float32"):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon

    def forward(self, weight):
        return F.spectral_norm(weight, self._dim, self._power_iters, self._epsilon)
