"""RNN layers (ref: python/paddle/nn/layer/rnn.py).

Recurrence is a lax.scan over time — the XLA-native loop form (the reference's
cudnn RNN kernels have no TPU analogue; scan compiles to a single fused while
loop that keeps weights resident in VMEM).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import functional as F
from ..initializer import Uniform
from ..layer_base import Layer
from ...framework.core import Tensor
from ...framework.dispatch import apply_op


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None, init_value=0.0,
                           batch_dim_idx=0):
        B = batch_ref.shape[batch_dim_idx]
        from ...tensor.creation import full

        if isinstance(self.state_shape, (list, tuple)) and \
                isinstance(self.state_shape[0], (list, tuple)):
            return tuple(full([B] + list(s), init_value, dtype or "float32")
                         for s in self.state_shape)
        return full([B] + list(self.state_shape), init_value, dtype or "float32")


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh", weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        k = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-k, k)
        self.weight_ih = self.create_parameter([hidden_size, input_size], weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size], weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter(
            [hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter(
            [hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def f(x, h, wi, wh, *biases):
            z = x @ wi.T + h @ wh.T
            for b in biases:
                z = z + b
            return act(z)

        args = [inputs, states, self.weight_ih, self.weight_hh]
        args += [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        h = apply_op(f, *args)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, proj_size=0, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-k, k)
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size], weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size], weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter(
            [4 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter(
            [4 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def f(x, hh, cc, wi, wh, *biases):
            z = x @ wi.T + hh @ wh.T
            for b in biases:
                z = z + b
            i, fgate, g, o = jnp.split(z, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            fgate = jax.nn.sigmoid(fgate)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            new_c = fgate * cc + i * g
            new_h = o * jnp.tanh(new_c)
            return new_h, new_c

        args = [inputs, h, c, self.weight_ih, self.weight_hh]
        args += [b for b in (self.bias_ih, self.bias_hh) if b is not None]
        new_h, new_c = apply_op(f, *args)
        return new_h, (new_h, new_c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        k = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-k, k)
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size], weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size], weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = None if bias_ih_attr is False else self.create_parameter(
            [3 * hidden_size], bias_ih_attr, is_bias=True, default_initializer=init)
        self.bias_hh = None if bias_hh_attr is False else self.create_parameter(
            [3 * hidden_size], bias_hh_attr, is_bias=True, default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def f(x, h, wi, wh, bi, bh):
            gi = x @ wi.T + (bi if bi is not None else 0)
            gh = h @ wh.T + (bh if bh is not None else 0)
            ir, iz, ig = jnp.split(gi, 3, -1)
            hr, hz, hg = jnp.split(gh, 3, -1)
            r = jax.nn.sigmoid(ir + hr)
            z = jax.nn.sigmoid(iz + hz)
            g = jnp.tanh(ig + r * hg)
            return (1 - z) * g + z * h

        if self.bias_ih is not None:
            h = apply_op(lambda x, hh, wi, wh, bi, bh: f(x, hh, wi, wh, bi, bh),
                         inputs, states, self.weight_ih, self.weight_hh, self.bias_ih,
                         self.bias_hh)
        else:
            h = apply_op(lambda x, hh, wi, wh: f(x, hh, wi, wh, None, None),
                         inputs, states, self.weight_ih, self.weight_hh)
        return h, h


class RNN(Layer):
    """Wraps a cell into a time-loop (ref nn/layer/rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        time_axis = 0 if self.time_major else 1
        T = inputs.shape[time_axis]
        states = initial_states
        outs = []
        rng = range(T - 1, -1, -1) if self.is_reverse else range(T)
        for t in rng:
            x_t = inputs[(slice(None),) * time_axis + (t,)] if False else (
                inputs[t] if self.time_major else inputs[:, t])
            out, states = self.cell(x_t, states)
            outs.append(out)
        if self.is_reverse:
            outs = outs[::-1]
        from ...tensor.manipulation import stack

        outputs = stack(outs, axis=time_axis)
        return outputs, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if initial_states is None:
            fw_states = bw_states = None
        else:
            fw_states, bw_states = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, fw_states, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, bw_states, sequence_length)
        from ...tensor.manipulation import concat

        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        bidirect = 2 if direction == "bidirect" or direction == "bidirectional" else 1
        self.num_directions = bidirect

        def make_cell(isize):
            if mode == "LSTM":
                return LSTMCell(isize, hidden_size, weight_ih_attr, weight_hh_attr,
                                bias_ih_attr, bias_hh_attr)
            if mode == "GRU":
                return GRUCell(isize, hidden_size, weight_ih_attr, weight_hh_attr,
                               bias_ih_attr, bias_hh_attr)
            return SimpleRNNCell(isize, hidden_size, "tanh", weight_ih_attr, weight_hh_attr,
                                 bias_ih_attr, bias_hh_attr)

        from .container import LayerList

        self.rnns = LayerList()
        for layer_i in range(num_layers):
            isize = input_size if layer_i == 0 else hidden_size * bidirect
            if bidirect == 2:
                self.rnns.append(BiRNN(make_cell(isize), make_cell(isize), time_major))
            else:
                self.rnns.append(RNN(make_cell(isize), False, time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        out = inputs
        final_states = []
        for i, rnn_l in enumerate(self.rnns):
            init_i = None
            if initial_states is not None:
                init_i = self._slice_states(initial_states, i)
            out, st = rnn_l(out, init_i, sequence_length)
            final_states.append(st)
            if self.dropout > 0 and i < self.num_layers - 1:
                out = F.dropout(out, self.dropout, training=self.training)
        return out, self._merge_states(final_states)

    def _slice_states(self, initial_states, i):
        # initial_states: (num_layers*dirs, B, H) or tuple of two for LSTM
        d = self.num_directions
        if self.mode == "LSTM":
            h, c = initial_states
            if d == 1:
                return (h[i], c[i])
            return ((h[2 * i], c[2 * i]), (h[2 * i + 1], c[2 * i + 1]))
        h = initial_states
        if d == 1:
            return h[i]
        return (h[2 * i], h[2 * i + 1])

    def _merge_states(self, final_states):
        from ...tensor.manipulation import stack

        d = self.num_directions
        if self.mode == "LSTM":
            hs, cs = [], []
            for st in final_states:
                if d == 1:
                    hs.append(st[0])
                    cs.append(st[1])
                else:
                    (h_f, c_f), (h_b, c_b) = st
                    hs += [h_f, h_b]
                    cs += [c_f, c_b]
            return stack(hs, 0), stack(cs, 0)
        hs = []
        for st in final_states:
            if d == 1:
                hs.append(st)
            else:
                hs += [st[0], st[1]]
        return stack(hs, 0)


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers, direction, time_major,
                         dropout, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction, time_major,
                         dropout, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction, time_major,
                         dropout, **kwargs)
