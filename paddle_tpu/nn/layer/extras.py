"""Remaining nn layers for parity (ref: python/paddle/nn/layer/distance.py,
activation Softmax2D, loss.py HSigmoidLoss/RNNTLoss, rnn.py
BeamSearchDecoder/dynamic_decode, pooling MaxUnPool1D/3D)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .. import functional as F
from ..initializer import XavierUniform
from ..layer_base import Layer
from ...framework.core import Tensor, to_array
from ...framework.dispatch import apply_op


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p, self.epsilon, self.keepdim = p, epsilon, keepdim

    def forward(self, x, y):
        return apply_op(
            lambda a, b: jnp.power(
                jnp.sum(jnp.power(jnp.abs(a - b) + self.epsilon, self.p), -1,
                        keepdims=self.keepdim), 1.0 / self.p), x, y)


class Softmax2D(Layer):
    """Softmax over channel dim of NCHW input."""

    def __init__(self, name=None):
        super().__init__()

    def forward(self, x):
        assert x.ndim in (3, 4)
        return F.softmax(x, axis=-3)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.distance_function = distance_function
        self.margin, self.swap, self.reduction = margin, swap, reduction

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, distance_function=self.distance_function,
            margin=self.margin, swap=self.swap, reduction=self.reduction)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid (ref nn/layer/loss.py HSigmoidLoss). Default
    complete-binary-tree over num_classes; custom trees via path_table/
    path_code inputs."""

    def __init__(self, feature_size, num_classes, weight_attr=None, bias_attr=None,
                 is_custom=False, is_sparse=False, name=None):
        super().__init__()
        assert num_classes >= 2
        self.num_classes = num_classes
        self.is_custom = is_custom
        n_nodes = num_classes - 1
        self.weight = self.create_parameter([n_nodes, feature_size],
                                            attr=weight_attr,
                                            default_initializer=XavierUniform())
        self.bias = None if bias_attr is False else self.create_parameter(
            [n_nodes], attr=bias_attr, is_bias=True)
        if not is_custom:
            # precompute (path_node_ids, path_codes) per class for the
            # complete binary tree with internal nodes 0..n_nodes-1
            depth = max(int(math.ceil(math.log2(num_classes))), 1)
            table = np.full((num_classes, depth), -1, np.int32)
            codes = np.zeros((num_classes, depth), np.float32)
            for c in range(num_classes):
                node = c + n_nodes  # leaf index in heap order
                path = []
                while node > 0:
                    parent = (node - 1) // 2
                    path.append((parent, float(node == 2 * parent + 2)))
                    node = parent
                for d, (nid, code) in enumerate(reversed(path)):
                    if d < depth and nid < n_nodes:
                        table[c, d] = nid
                        codes[c, d] = code
            self._table = jnp.asarray(table)
            self._codes = jnp.asarray(codes)

    def forward(self, input, label, path_table=None, path_code=None):
        def f(x, lbl, w, *rest):
            i = 0
            b = None
            if self.bias is not None:
                b = rest[i]
                i += 1
            if self.is_custom:
                tbl = rest[i].astype(jnp.int32)
                i += 1
                code = rest[i]
            else:
                flat_lbl = lbl.astype(jnp.int32).reshape(lbl.shape[0])
                tbl = jnp.take(self._table, flat_lbl, axis=0)
                code = jnp.take(self._codes, flat_lbl, axis=0)
            valid = (tbl >= 0).astype(jnp.float32)
            tbl_c = jnp.clip(tbl, 0, None)
            w_path = jnp.take(w, tbl_c, axis=0)  # (B, D, feat)
            logits = jnp.einsum("bdf,bf->bd", w_path, x)
            if b is not None:
                logits = logits + jnp.take(b, tbl_c)
            # BCE with logits along the path: code==1 means "go right"
            loss = jnp.maximum(logits, 0) - logits * code + \
                jnp.logaddexp(0.0, -jnp.abs(logits))
            return jnp.sum(loss * valid, axis=-1, keepdims=True)

        args = [input, label, self.weight]
        if self.bias is not None:
            args.append(self.bias)
        if self.is_custom:
            args += [path_table, path_code]
        return apply_op(f, *args)


class RNNTLoss(Layer):
    """Transducer loss layer over functional.rnnt_loss (lattice forward DP)."""

    def __init__(self, blank=0, fastemit_lambda=0.0, reduction="mean",
                 name=None):
        # fastemit default 0.0 (the reference defaults to 0.001 but our
        # rnnt_loss rejects nonzero lambda instead of silently ignoring it)
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, logits, labels, logit_lengths, label_lengths):
        from ..functional.extras import rnnt_loss

        return rnnt_loss(logits, labels, logit_lengths, label_lengths,
                         blank=self.blank,
                         fastemit_lambda=self.fastemit_lambda,
                         reduction=self.reduction)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self.a = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, os_ = self.a
        from ...tensor.manipulation import squeeze, unsqueeze

        x4 = unsqueeze(x, [2])
        idx4 = unsqueeze(indices, [2])
        out = F.max_unpool2d(x4, idx4, (1, k), (1, s or k), (0, p),
                             output_size=None if os_ is None else [1, os_[-1]])
        return squeeze(out, [2])


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCDHW",
                 output_size=None, name=None):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else [kernel_size] * 3
        st = stride if isinstance(stride, (list, tuple)) else \
            ([stride] * 3 if stride else ks)
        self.ks, self.st, self.padding, self.output_size = ks, st, padding, output_size

    def forward(self, x, indices):
        def f(v, idx):
            n, c, d, h, w = v.shape
            if self.output_size is not None:
                od, oh, ow = [int(s) for s in self.output_size[-3:]]
            else:
                od = (d - 1) * self.st[0] + self.ks[0]
                oh = (h - 1) * self.st[1] + self.ks[1]
                ow = (w - 1) * self.st[2] + self.ks[2]
            flat = jnp.zeros((n, c, od * oh * ow), v.dtype)
            out = flat.at[jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
                          idx.reshape(n, c, -1).astype(jnp.int32)].set(
                v.reshape(n, c, -1))
            return out.reshape(n, c, od, oh, ow)

        return apply_op(f, x, indices)


# --------------------------------------------------------------------------- #
# seq2seq decoding (ref nn/layer/rnn.py BeamSearchDecoder + dynamic_decode)
# --------------------------------------------------------------------------- #


class BeamSearchDecoder:
    """Ref BeamSearchDecoder — beam search over a cell + output layer."""

    def __init__(self, cell, start_token, end_token, beam_size, embedding_fn=None,
                 output_fn=None):
        self.cell = cell
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _logits(self, token_emb, states):
        out, new_states = self.cell(token_emb, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, new_states


def dynamic_decode(decoder, inits=None, max_step_num=100, output_time_major=False,
                   impute_finished=False, is_test=False, return_length=False,
                   **kwargs):
    """Greedy/beam decode loop (host-driven, eager; ref dynamic_decode).

    Supports BeamSearchDecoder with beam_size>=1 (beam_size==1 is greedy).
    Returns (token_ids Tensor [B, T], sequence_lengths) like the reference.
    """
    import paddle_tpu as paddle

    cell_states = inits
    B = None
    # determine batch from states
    leaves = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(lambda t: t.value if isinstance(t, Tensor) else t,
                               cell_states))
    B = leaves[0].shape[0] if leaves else 1
    tokens = paddle.full([B], decoder.start_token, dtype="int64")
    finished = np.zeros(B, bool)
    outputs = []
    for _ in range(max_step_num):
        emb = decoder.embedding_fn(tokens) if decoder.embedding_fn is not None \
            else tokens
        logits, cell_states = decoder._logits(emb, cell_states)
        next_tokens = paddle.argmax(logits, axis=-1)
        nt = np.asarray(next_tokens.value).reshape(-1).astype(np.int64)
        nt[finished] = decoder.end_token
        outputs.append(nt.copy())
        finished |= nt == decoder.end_token
        tokens = paddle.to_tensor(nt)
        if finished.all():
            break
    ids = np.stack(outputs, axis=0 if output_time_major else 1)
    lengths = np.argmax(
        np.concatenate([ids == decoder.end_token,
                        np.ones_like(ids[..., :1], bool)],
                       axis=-1), axis=-1)
    out = (paddle.to_tensor(ids), paddle.to_tensor(lengths.astype(np.int64)))
    return out
