"""LoRA (low-rank adaptation) for training AND batched serving.

Training side: :class:`LoRALinear` wraps an existing :class:`Linear` with a
trainable low-rank residual ``y = xW + (x A) B * (alpha/r)`` — the base
weight is frozen (``trainable=False``) so only the factors flow through the
optimizer. :func:`attach_lora` / :func:`merge_lora` walk a model and
wrap/fold the configured projection attributes in place;
:func:`export_adapter` / :func:`load_adapter` round-trip the factors
through ``.npz`` checkpoints consumable by the serving-side registry
(``inference/lora.py``).

Serving side: :func:`bgmv` is the batched-gathered-matrix-vector delta used
inside the paged decode/verify/prefill programs — per-row A/B factors
(already gathered from the adapter pool by row index) applied as two skinny
matmuls. Factors are stored and applied in f32 regardless of the base
dtype: adapters are tiny and the padded-rank zero columns must stay exact.
"""

from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..framework.dispatch import apply_op
from .initializer import Constant, Normal
from .layer.common import Linear
from .layer_base import Layer

__all__ = ["LoRALinear", "attach_lora", "merge_lora", "lora_parameters",
           "lora_state", "load_lora_state", "export_adapter", "load_adapter",
           "bgmv", "lora_matmul"]


class LoRALinear(Layer):
    """A frozen :class:`Linear` plus a trainable rank-``r`` residual.

    ``lora_A`` is Normal(0, 0.02) and ``lora_B`` is zeros, so the wrapped
    layer is numerically identical to the base until training moves B —
    the standard LoRA init that makes attach/detach safe mid-run."""

    def __init__(self, base: Linear, rank: int, alpha: Optional[float] = None):
        super().__init__()
        if rank < 1:
            raise ValueError(f"LoRA rank must be >= 1, got {rank}")
        self.base = base
        self.rank = int(rank)
        self.alpha = float(alpha if alpha is not None else rank)
        self.scaling = self.alpha / self.rank
        in_f, out_f = base._in_features, base._out_features
        base.weight.trainable = False
        base.weight.stop_gradient = True
        if base.bias is not None:
            base.bias.trainable = False
            base.bias.stop_gradient = True
        # factors stay f32 even under a bf16 base: the delta is computed in
        # f32 and cast at the end (matches the serving pool's layout)
        self.lora_A = self.create_parameter([in_f, rank], attr=Normal(0.0, 0.02),
                                            dtype="float32")
        self.lora_B = self.create_parameter([rank, out_f], attr=Constant(0.0),
                                            dtype="float32")

    def forward(self, x):
        s = self.scaling

        def lin(v, w, b, a, bb):
            y = jnp.matmul(v, w)
            if b is not None:
                y = y + b
            d = jnp.matmul(jnp.matmul(v.astype(jnp.float32), a), bb) * s
            return y + d.astype(y.dtype)

        if self.base.bias is not None:
            return apply_op(lambda v, w, b, a, bb: lin(v, w, b, a, bb),
                            x, self.base.weight, self.base.bias,
                            self.lora_A, self.lora_B, op_name="lora_linear")
        return apply_op(lambda v, w, a, bb: lin(v, w, None, a, bb),
                        x, self.base.weight, self.lora_A, self.lora_B,
                        op_name="lora_linear")

    def merged_weight(self) -> np.ndarray:
        """Base weight with the low-rank delta folded in (f32 numpy)."""
        # deliberate host boundary: merge/export runs off the hot path
        w = np.asarray(self.base.weight.value, dtype=np.float32)  # graftlint: noqa[host-sync]
        a = np.asarray(self.lora_A.value, dtype=np.float32)  # graftlint: noqa[host-sync]
        b = np.asarray(self.lora_B.value, dtype=np.float32)  # graftlint: noqa[host-sync]
        return w + self.scaling * (a @ b)

    def extra_repr(self):
        return (f"in_features={self.base._in_features}, "
                f"out_features={self.base._out_features}, rank={self.rank}, "
                f"alpha={self.alpha}")


def _wrap_sites(model: Layer, targets: Iterable[str]):
    """Yield (owner_layer, attr_name, child) for every target attribute that
    is a plain Linear anywhere in the model tree."""
    tset = tuple(targets)
    for _, layer in model.named_sublayers(include_self=True):
        for tname in tset:
            child = layer._sub_layers.get(tname)
            if isinstance(child, LoRALinear):
                yield layer, tname, child
            elif isinstance(child, Linear):
                yield layer, tname, child


def attach_lora(model: Layer, rank: int, alpha: Optional[float] = None,
                targets: Iterable[str] = ()) -> Layer:
    """Replace every ``targets`` attribute that is a plain :class:`Linear`
    with a :class:`LoRALinear` of the given rank. Idempotent on already
    wrapped sites. Returns the model (mutated in place)."""
    n = 0
    for layer, tname, child in list(_wrap_sites(model, targets)):
        if isinstance(child, LoRALinear):
            continue
        setattr(layer, tname, LoRALinear(child, rank, alpha))
        n += 1
    if n == 0 and not any(True for _ in _wrap_sites(model, targets)):
        raise ValueError(f"attach_lora found no Linear targets {tuple(targets)}")
    return model


def merge_lora(model: Layer, targets: Iterable[str] = ()) -> Layer:
    """Fold every LoRALinear's delta into its base weight and put the plain
    Linear back — the inverse of :func:`attach_lora` for inference export."""
    for layer, tname, child in list(_wrap_sites(model, targets)):
        if not isinstance(child, LoRALinear):
            continue
        base = child.base
        merged = child.merged_weight().astype(
            np.asarray(base.weight.value).dtype)  # graftlint: noqa[host-sync]
        base.weight.trainable = True
        base.weight.stop_gradient = False
        base.weight.set_value(merged)
        if base.bias is not None:
            base.bias.trainable = True
            base.bias.stop_gradient = False
        setattr(layer, tname, base)
    return model


def lora_parameters(model: Layer) -> List:
    """The trainable A/B factors — hand this to the optimizer."""
    out = []
    for _, layer in model.named_sublayers(include_self=True):
        if isinstance(layer, LoRALinear):
            out.extend([layer.lora_A, layer.lora_B])
    return out


def lora_state(model: Layer) -> Dict[str, Dict]:
    """{module_path: {"A": f32 ndarray, "B": f32 ndarray}} plus a "__meta__"
    entry carrying rank/alpha — the adapter checkpoint payload."""
    state: Dict[str, Dict] = {}
    meta = None
    for path, layer in model.named_sublayers(include_self=True):
        if not isinstance(layer, LoRALinear):
            continue
        # checkpoint export: the one-off host copy IS the point here
        state[path] = {"A": np.asarray(layer.lora_A.value, dtype=np.float32),  # graftlint: noqa[host-sync]
                       "B": np.asarray(layer.lora_B.value, dtype=np.float32)}  # graftlint: noqa[host-sync]
        if meta is None:
            meta = {"rank": layer.rank, "alpha": layer.alpha}
    if meta is None:
        raise ValueError("model has no LoRALinear layers to export")
    state["__meta__"] = meta
    return state


def load_lora_state(model: Layer, state: Dict[str, Dict]) -> Layer:
    """Restore exported factors into an already-attached model."""
    for path, layer in model.named_sublayers(include_self=True):
        if isinstance(layer, LoRALinear) and path in state:
            layer.lora_A.set_value(np.asarray(state[path]["A"], np.float32))
            layer.lora_B.set_value(np.asarray(state[path]["B"], np.float32))
    return model


def export_adapter(model: Layer, path: str) -> None:
    """Save the adapter checkpoint as ``.npz`` (keys ``A:<module path>`` /
    ``B:<module path>`` + json meta)."""
    state = lora_state(model)
    meta = state.pop("__meta__")
    arrays = {}
    for mpath, ab in state.items():
        arrays[f"A:{mpath}"] = ab["A"]
        arrays[f"B:{mpath}"] = ab["B"]
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta).encode("utf-8"), dtype=np.uint8)
    np.savez(path, **arrays)


def load_adapter(path: str) -> Dict[str, Dict]:
    """Load an ``.npz`` adapter checkpoint back into the
    :func:`lora_state` dict shape (consumable by ``load_lora_state`` or
    ``inference.lora.AdapterRegistry.register``)."""
    with np.load(path) as z:
        meta = json.loads(bytes(z["__meta__"].tobytes()).decode("utf-8"))
        state: Dict[str, Dict] = {"__meta__": meta}
        for key in z.files:
            if key.startswith("A:"):
                mpath = key[2:]
                state[mpath] = {"A": np.asarray(z[key], np.float32),
                                "B": np.asarray(z[f"B:{mpath}"], np.float32)}
    return state


# --------------------------------------------------------------------------- #
# serving-side batched delta
# --------------------------------------------------------------------------- #


def bgmv(x: Tensor, ab: Optional[Tuple]) -> Optional[Tensor]:
    """Batched gathered LoRA delta: ``ab = (A, B, scale)`` raw jnp arrays
    already gathered per row — A (B, in, R), B (B, R, out), scale (B,) with
    alpha/r pre-baked (scale 0 and zero factors on the null page make
    adapterless rows exact no-ops). x: Tensor (B, S, in). Returns the delta
    Tensor (B, S, out) in x's dtype; compute is f32 so the zero-padded rank
    columns cancel exactly."""
    if ab is None:
        return None
    A, B, s = ab

    def f(v, a, b, sc):
        d = jnp.einsum("bsh,bhr->bsr", v.astype(jnp.float32), a)
        d = jnp.einsum("bsr,bro->bso", d, b) * sc[:, None, None]
        return d.astype(v.dtype)

    return apply_op(f, x, Tensor(A), Tensor(B), Tensor(s), op_name="lora_bgmv")


def lora_matmul(x: Tensor, w: Tensor, ab: Optional[Tuple]) -> Tensor:
    """Base projection + gathered LoRA delta in ONE op:
    ``x @ w + ((x32 @ A) @ B) * scale`` with ``ab = (A, B, scale)`` as in
    :func:`bgmv` (None means plain matmul). Under the shared kernel
    dispatch (``ops.use_pallas()``) the whole expression runs as one Pallas
    program per batch row (``ops.paged_attention_pallas.fused_lora_matmul``)
    so multi-tenant decode stops paying a separate gather+matmul pass; the
    jnp composition is bit-identical to the Linear-then-:func:`bgmv`
    sequence it replaces (same primitives, same order)."""
    if ab is None:
        return apply_op(lambda v, wv: jnp.matmul(v, wv), x, w,
                        op_name="linear")
    A, B, s = ab

    def f(v, wv, a, b, sc):
        from ..ops import use_pallas

        if use_pallas():
            try:
                from ..ops.paged_attention_pallas import fused_lora_matmul
                return fused_lora_matmul(v, wv, a, b, sc)
            except NotImplementedError:
                pass
        y = jnp.matmul(v, wv)
        d = jnp.einsum("bsh,bhr->bsr", v.astype(jnp.float32), a)
        d = jnp.einsum("bsr,bro->bso", d, b) * sc[:, None, None]
        return y + d.astype(v.dtype)

    return apply_op(f, x, w, Tensor(A), Tensor(B), Tensor(s),
                    op_name="lora_linear")
