"""Layer: the module base class.

Ref: python/paddle/fluid/dygraph/layers.py (state_dict :1555,
set_state_dict :1593, hooks, sublayers, create_parameter). Parameters are
mutable ``Parameter`` objects owned by the layer; the jit/pjit path extracts
them into a pytree and swaps traced values in (functional-call pattern) —
see paddle_tpu.jit.functional_call.
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..framework.core import Parameter, Tensor, to_array
from ..framework.dtype import convert_dtype, get_default_dtype


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks: dict):
        self._hooks = hooks
        self._id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        object.__setattr__(self, "_parameters", collections.OrderedDict())
        object.__setattr__(self, "_sub_layers", collections.OrderedDict())
        object.__setattr__(self, "_buffers", collections.OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        self._name_scope = name_scope or self.__class__.__name__.lower()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._casted_by_pure_fp16 = False

    # ------------------------------------------------------------------ attrs
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__() before assigning parameters")
            params[name] = value
            layers.pop(name, None) if layers else None
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__() before assigning sublayers")
            layers[name] = value
            params.pop(name, None) if params else None
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                else:
                    params[name] = value
                return
            if buffers is not None and name in buffers:
                buffers[name] = value if (value is None or isinstance(value, Tensor)) \
                    else Tensor(value)
                return
            if layers is not None and name in layers and value is None:
                layers.pop(name)
                return
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + list(self._sub_layers) + \
            list(self._buffers)

    # -------------------------------------------------------------- creation
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .initializer import Constant, XavierUniform
        from . import initializer as I

        dtype = convert_dtype(dtype) or self._dtype
        init = default_initializer
        name = None
        learning_rate = 1.0
        trainable = True
        if attr is not None and attr is not False:
            from ..framework.param_attr import ParamAttr

            if isinstance(attr, ParamAttr):
                init = attr.initializer or init
                name = attr.name
                learning_rate = attr.learning_rate
                trainable = attr.trainable
            elif isinstance(attr, str):
                name = attr
            elif callable(attr):
                init = attr
        if init is None:
            init = I._global_initializer(is_bias)  # set_global_initializer
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        value = init(shape, dtype)
        p = Parameter(value, trainable=trainable, name=name or "")
        p.optimize_attr["learning_rate"] = learning_rate
        if getattr(attr, "regularizer", None) is not None:
            p.regularizer = attr.regularizer
        return p

    def create_variable(self, name=None, persistable=None, dtype=None):
        import jax.numpy as jnp

        return Tensor(jnp.zeros([], convert_dtype(dtype) or self._dtype))

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(tensor)
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        else:
            self._non_persistable_buffer_names.discard(name)

    # ------------------------------------------------------------- traversal
    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True,
                         include_self: bool = True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def buffers(self, include_sublayers: bool = True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        seen = set()
        for name, layer in self._sub_layers.items():
            if layer is not None and id(layer) not in seen:
                seen.add(id(layer))
                yield name, layer

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix: str = "", include_self: bool = False,
                        layers_set=None) -> Iterator[Tuple[str, "Layer"]]:
        if layers_set is None:
            layers_set = set()
        if id(self) in layers_set:
            return
        layers_set.add(id(self))
        if include_self or prefix == "":
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix, include_self=True,
                                             layers_set=layers_set)

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------ state dict
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "", use_hook: bool = True):
        """Ref layers.py:1555 — returns OrderedDict of params + persistable buffers."""
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip("."),
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self.named_sublayers(include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                full = f"{name}.{bname}" if name else bname
                if structured_name_prefix:
                    full = structured_name_prefix + full
                dest[full] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name: bool = True):
        """Ref layers.py:1593."""
        missing, unexpected = [], []
        own = self.state_dict()
        matched = set()
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                val = to_array(v) if isinstance(v, Tensor) else np.asarray(v)
                if tuple(val.shape) != tuple(tgt.shape):
                    raise ValueError(
                        f"shape mismatch for {k}: ckpt {tuple(val.shape)} vs "
                        f"model {tuple(tgt.shape)}")
                import jax.numpy as jnp

                tgt._value = jnp.asarray(val).astype(tgt.dtype)
                matched.add(k)
            else:
                unexpected.append(k)
        for k in own:
            if k not in matched:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ----------------------------------------------------------------- modes
    def train(self):
        self.training = True
        for l in self.sublayers(include_self=False):
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers(include_self=False):
            l.training = False
        return self

    # ----------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook) -> HookRemoveHelper:
        h = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook) -> HookRemoveHelper:
        h = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    # ------------------------------------------------------------------ call
    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            res = hook(self, inputs)
            if res is not None:
                inputs = res if isinstance(res, tuple) else (res,)
        out = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            res = hook(self, inputs, out)
            if res is not None:
                out = res
        return out

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------------------- utilities
    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        self._parameters[str(name)] = parameter
        return parameter

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._convert_dtype(convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._convert_dtype(convert_dtype(dtype))
        return self

    def _convert_dtype(self, dtype, only_float=True):
        import jax.numpy as jnp

        from ..framework.dtype import is_floating_point

        for layer in self.sublayers(include_self=True):
            layer._dtype = dtype
            for p in layer._parameters.values():
                if p is not None and (not only_float or is_floating_point(p.dtype)):
                    p._value = p._value.astype(dtype)
            for b in layer._buffers.values():
                if b is not None and (not only_float or is_floating_point(b.dtype)):
                    b._value = b._value.astype(dtype)

    def float(self):
        self._convert_dtype(convert_dtype("float32"))
        return self

    def bfloat16(self):
        self._convert_dtype(convert_dtype("bfloat16"))
        return self

    def half(self):
        self._convert_dtype(convert_dtype("float16"))
        return self

    def full_name(self) -> str:
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            rep = repr(layer).split("\n")
            rep = [rep[0]] + ["  " + r for r in rep[1:]]
            lines.append(f"  ({name}): " + "\n".join(rep))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"
