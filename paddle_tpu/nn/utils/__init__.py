"""paddle.nn.utils parity (ref: python/paddle/nn/utils/ —
weight_norm_hook.py weight_norm/remove_weight_norm, spectral_norm_hook.py
spectral_norm, transform_parameters.py parameters_to_vector /
vector_to_parameters).

Reparameterizations are implemented as forward-pre-hooks recomputing the
target weight from the stored factors before every call — the same shape as
the reference's hook design, over the eager tape instead of C++ hooks."""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from ...framework.core import Parameter, Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(w, dim):
    """L2 norm over all axes except ``dim`` (ref weight_norm_hook norm_except_dim)."""
    v = w.value if isinstance(w, Tensor) else w
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim)
    shape = [1] * v.ndim
    shape[dim] = v.shape[dim]
    return jnp.sqrt(jnp.sum(v * v, axis=axes)).reshape(shape)


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """w = g * v / ||v|| reparameterization (ref weight_norm_hook.py
    weight_norm): replaces ``layer.<name>`` with factors ``<name>_g`` /
    ``<name>_v`` and recomputes the weight in a forward-pre-hook so both
    factors train through the tape."""
    w = getattr(layer, name)
    g0 = _norm_except(w, dim)
    v0 = w.value
    g = Parameter(g0, name=f"{name}_g")
    v = Parameter(v0, name=f"{name}_v")
    # deregister the original parameter; register the factors
    if name in getattr(layer, "_parameters", {}):
        del layer._parameters[name]
    layer.add_parameter(f"{name}_g", g)
    layer.add_parameter(f"{name}_v", v)

    def _compute(lay, *args):
        gv = getattr(lay, f"{name}_g")
        vv = getattr(lay, f"{name}_v")
        # the norm must be ON the tape: g and v both receive the full
        # d(g·v/||v||) gradient incl. the norm-direction term
        axes = ([i for i in range(len(vv.shape)) if i != dim]
                if dim is not None else None)
        if axes is None:
            norm_t = (vv * vv).sum().sqrt()
        else:
            norm_t = (vv * vv).sum(axis=axes, keepdim=True).sqrt()
        setattr(lay, name, vv * (gv / norm_t))

    handle = layer.register_forward_pre_hook(lambda lay, inp: _compute(lay))
    layer._weight_norm_hook = (handle, name, dim)
    _compute(layer)
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    """Fold g·v/||v|| back into a single parameter (ref weight_norm_hook.py
    remove_weight_norm)."""
    handle, nm, dim = layer._weight_norm_hook
    assert nm == name, (nm, name)
    handle.remove()
    g = getattr(layer, f"{name}_g")
    v = getattr(layer, f"{name}_v")
    w = v.value * (g.value / _norm_except(v, dim))
    del layer._parameters[f"{name}_g"]
    del layer._parameters[f"{name}_v"]
    layer.add_parameter(name, Parameter(w, name=name))
    del layer._weight_norm_hook
    return layer


def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: int = None):
    """w / sigma_max(w) via power iteration (ref spectral_norm_hook.py
    spectral_norm): keeps ``<name>_orig`` trainable plus u/v power-iteration
    buffers updated each forward."""
    if dim is None:
        dim = 1 if layer.__class__.__name__.lower().find("linear") >= 0 else 0
    from ...framework.random import derived_rng

    w = getattr(layer, name)
    # one-time host copy at hook-install (init only, never per-forward)
    wv = np.asarray(w.value)  # graftlint: noqa[host-sync]
    wm = np.moveaxis(wv, dim, 0).reshape(wv.shape[dim], -1)
    # power-iteration init: seeded via the framework generator (GL003) —
    # deterministic per (shape, paddle.seed), not the global numpy stream
    rng = derived_rng("spectral_norm", wm.shape[0], wm.shape[1])
    u = rng.standard_normal(wm.shape[0]).astype(np.float32)
    v = rng.standard_normal(wm.shape[1]).astype(np.float32)
    u /= np.linalg.norm(u) + eps
    v /= np.linalg.norm(v) + eps
    orig = Parameter(w.value, name=f"{name}_orig")
    if name in getattr(layer, "_parameters", {}):
        del layer._parameters[name]
    layer.add_parameter(f"{name}_orig", orig)
    layer.register_buffer(f"{name}_u", Tensor(jnp.asarray(u)))
    layer.register_buffer(f"{name}_v", Tensor(jnp.asarray(v)))

    def _compute(lay, *args):
        wo = getattr(lay, f"{name}_orig")
        uu = getattr(lay, f"{name}_u").value
        vv_ = getattr(lay, f"{name}_v").value
        wmat = jnp.moveaxis(wo.value, dim, 0).reshape(wo.value.shape[dim], -1)
        for _ in range(n_power_iterations):
            vv_ = wmat.T @ uu
            vv_ = vv_ / (jnp.linalg.norm(vv_) + eps)
            uu = wmat @ vv_
            uu = uu / (jnp.linalg.norm(uu) + eps)
        getattr(lay, f"{name}_u")._value = uu
        getattr(lay, f"{name}_v")._value = vv_
        # power iteration is no-grad (u, v are buffers), but sigma = u^T W v
        # must differentiate through W: grad gets the -(u v^T)/sigma^2 term
        uvT = Tensor(jnp.moveaxis(
            jnp.outer(uu, vv_).reshape(
                (wo.value.shape[dim],) +
                tuple(np.delete(np.array(wo.value.shape), dim))), 0, dim))
        sigma = (wo * uvT).sum()
        setattr(lay, name, wo / sigma)

    handle = layer.register_forward_pre_hook(lambda lay, inp: _compute(lay))
    layer._spectral_norm_hook = (handle, name)
    _compute(layer)
    return layer


def parameters_to_vector(parameters, name=None):
    """Flatten a parameter list into one 1-D Tensor (ref
    transform_parameters.py parameters_to_vector)."""
    vals = [jnp.ravel(p.value) for p in parameters]
    return Tensor(jnp.concatenate(vals)) if vals else Tensor(jnp.zeros(0))


def vector_to_parameters(vec, parameters, name=None):
    """Scatter a flat vector back into the parameter list (ref
    transform_parameters.py vector_to_parameters)."""
    v = vec.value if isinstance(vec, Tensor) else jnp.asarray(vec)
    off = 0
    for p in parameters:
        n = int(np.prod(p.shape)) if p.shape else 1
        p._value = v[off:off + n].reshape(p.shape).astype(p.value.dtype)
        off += n
    return parameters
