"""paddle.nn.functional parity surface (ref: python/paddle/nn/functional/)."""
from .activation import (celu, elu, gelu, glu, gumbel_softmax, hardshrink, hardsigmoid,
                         hardswish, hardtanh, leaky_relu, log_sigmoid, log_softmax, maxout,
                         mish, prelu, relu, relu6, rrelu, selu, sigmoid, silu, softmax,
                         softplus, softshrink, softsign, swish, tanh, tanhshrink,
                         thresholded_relu)
from .attention import scaled_dot_product_attention
from .common import (alpha_dropout, bilinear, channel_shuffle, cosine_similarity, dropout,
                     dropout2d, dropout3d, embedding, fold, interpolate, label_smooth, linear,
                     one_hot, pad, pixel_shuffle, pixel_unshuffle, sequence_mask,
                     temporal_shift, unfold, upsample, zeropad2d)
from .conv import (conv1d, conv1d_transpose, conv2d, conv2d_transpose, conv3d,
                   conv3d_transpose)
from .loss import (binary_cross_entropy, binary_cross_entropy_with_logits,
                   cosine_embedding_loss, cross_entropy, ctc_loss, dice_loss,
                   gaussian_nll_loss, hinge_embedding_loss, huber_loss, kl_div, l1_loss,
                   log_loss, margin_ranking_loss, mse_loss, multi_label_soft_margin_loss,
                   multi_margin_loss, nll_loss, npair_loss, poisson_nll_loss,
                   sigmoid_focal_loss, smooth_l1_loss, soft_margin_loss,
                   softmax_with_cross_entropy, square_error_cost, triplet_margin_loss,
                   triplet_margin_with_distance_loss)
from .norm import (batch_norm, group_norm, instance_norm, layer_norm, local_response_norm,
                   normalize, rms_norm, spectral_norm)
from .extras import (affine_grid, class_center_sample, diag_embed, elu_, gather_tree,
                     grid_sample, hsigmoid_loss, margin_cross_entropy, max_unpool1d,
                     max_unpool3d, pairwise_distance, relu_, rnnt_loss, softmax_,
                     sparse_attention, tanh_)
from .pooling import (adaptive_avg_pool1d, adaptive_avg_pool2d, adaptive_avg_pool3d,
                      adaptive_max_pool1d, adaptive_max_pool2d, adaptive_max_pool3d,
                      avg_pool1d, avg_pool2d, avg_pool3d, max_pool1d, max_pool2d, max_pool3d,
                      max_unpool2d)

__all__ = [n for n in dir() if not n.startswith("_")]
