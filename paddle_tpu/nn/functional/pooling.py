"""Pooling ops (ref: python/paddle/nn/functional/pooling.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.dispatch import apply_op


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in (v if len(v) == n else list(v) * n)[:n])
    return tuple(int(v) for _ in range(n))


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)) and len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    p = _tup(padding, n)
    return [(i, i) for i in p]


def _pool(x, ksize, stride, padding, n, data_format, reducer, init, ceil_mode=False,
          avg_exclusive=True, count_include_pad=False):
    channel_last = data_format in ("NLC", "NHWC", "NDHWC")
    ksize = _tup(ksize, n)
    stride = _tup(stride, n) if stride is not None else ksize
    pads = _pads(padding, n)

    def f(v):
        nd = v.ndim
        if channel_last:
            spatial = list(range(1, 1 + n))
        else:
            spatial = list(range(2, nd))
        window = [1] * nd
        strides = [1] * nd
        for d, k, s in zip(spatial, ksize, stride):
            window[d] = k
            strides[d] = s
        if isinstance(pads, str):
            pad_cfg = pads
        else:
            pad_cfg = [(0, 0)] * nd
            for d, p in zip(spatial, pads):
                pad_cfg[d] = p
            if ceil_mode:
                pad_cfg = list(pad_cfg)
                for i, d in enumerate(spatial):
                    size = v.shape[d] + pad_cfg[d][0] + pad_cfg[d][1]
                    rem = (size - ksize[i]) % stride[i]
                    if rem != 0:
                        pad_cfg[d] = (pad_cfg[d][0], pad_cfg[d][1] + stride[i] - rem)
        if reducer == "max":
            out = jax.lax.reduce_window(v, -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
                                        else jnp.iinfo(v.dtype).min,
                                        jax.lax.max, window, strides, pad_cfg)
            return out
        # avg
        summed = jax.lax.reduce_window(v.astype(jnp.float32), 0.0, jax.lax.add, window,
                                       strides, pad_cfg)
        if count_include_pad or isinstance(pad_cfg, str):
            denom = float(np.prod(ksize))
            return (summed / denom).astype(v.dtype)
        ones = jnp.ones(v.shape, jnp.float32)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pad_cfg)
        return (summed / counts).astype(v.dtype)

    return apply_op(f, x, op_name=f"{reducer}_pool{n}d")


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               name=None):
    out = _pool(x, kernel_size, stride, padding, 1, "NCL", "max", None, ceil_mode)
    if return_mask:
        return out, _max_pool_indices_nd(x, kernel_size, stride, padding, 1,
                                         ceil_mode=ceil_mode)
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, data_format, "max", None, ceil_mode)
    if return_mask:
        idx = _max_pool_indices_nd(x, kernel_size, stride, padding, 2,
                                   ceil_mode=ceil_mode,
                                   channel_last=data_format == "NHWC")
        return out, idx
    return out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False, ceil_mode=False,
               data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, data_format, "max", None, ceil_mode)
    if return_mask:
        return out, _max_pool_indices_nd(x, kernel_size, stride, padding, 3,
                                         ceil_mode=ceil_mode,
                                         channel_last=data_format == "NDHWC")
    return out


def _max_pool_indices_nd(x, ksize, stride, padding, nd, ceil_mode=False,
                         channel_last=False):
    """Flattened-spatial argmax indices of an nd max-pool (paddle
    return_mask format, consumed by max_unpool{1,2,3}d). Positions stay
    int32 end to end (no float roundtrip — exact for any volume size)."""
    import itertools
    import math as _math

    k = _tup(ksize, nd)
    s = _tup(stride, nd) if stride is not None else k

    def f(v):
        if channel_last:  # normalize to channel-first; positions are over
            v = jnp.moveaxis(v, -1, 1)  # the spatial dims either way
        lead = v.shape[:2]
        spatial = v.shape[2:]
        p = _pads(padding, nd)
        string_pad = isinstance(p, str)
        if string_pad:  # 'SAME'/'VALID' → explicit amounts
            if p == "VALID":
                p = [(0, 0)] * nd
            else:
                p = []
                for i in range(nd):
                    out_i = _math.ceil(spatial[i] / s[i])
                    total = max((out_i - 1) * s[i] + k[i] - spatial[i], 0)
                    p.append((total // 2, total - total // 2))
        vp = jnp.pad(v, [(0, 0), (0, 0)] + list(p),
                     constant_values=-jnp.inf)
        size = 1
        for d in spatial:
            size *= d
        pos = jnp.arange(size, dtype=jnp.int32).reshape((1, 1) + spatial)
        posp = jnp.pad(pos, [(0, 0), (0, 0)] + list(p), constant_values=-1)
        # _pool skips its ceil extension for string padding (SAME already
        # ceils; VALID+ceil is rejected by the reference) — mirror that so
        # out and idx always have the SAME spatial shape
        if ceil_mode and not string_pad:
            extra = []
            for i in range(nd):
                out_i = _math.ceil((vp.shape[2 + i] - k[i]) / s[i]) + 1
                need = (out_i - 1) * s[i] + k[i]
                extra.append((0, max(0, need - vp.shape[2 + i])))
            vp = jnp.pad(vp, [(0, 0), (0, 0)] + extra,
                         constant_values=-jnp.inf)
            posp = jnp.pad(posp, [(0, 0), (0, 0)] + extra,
                           constant_values=-1)
        outd = [(vp.shape[2 + i] - k[i]) // s[i] + 1 for i in range(nd)]
        patches, ppos = [], []
        for offs in itertools.product(*[range(k[i]) for i in range(nd)]):
            sl = (slice(None), slice(None)) + tuple(
                slice(offs[i], offs[i] + outd[i] * s[i], s[i])
                for i in range(nd))
            patches.append(vp[sl])
            ppos.append(jnp.broadcast_to(posp[sl], lead + tuple(outd)))
        stacked = jnp.stack(patches, 0)
        spos = jnp.stack(ppos, 0)
        am = jnp.argmax(stacked, axis=0)
        idx = jnp.take_along_axis(spos, am[None], axis=0)[0]
        if channel_last:
            idx = jnp.moveaxis(idx, 1, -1)
        return idx.astype(jnp.int32)

    return apply_op(f, x)




def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True, ceil_mode=False,
               name=None):
    return _pool(x, kernel_size, stride, padding, 1, "NCL", "avg", None, ceil_mode,
                 count_include_pad=not exclusive)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, data_format, "avg", None, ceil_mode,
                 count_include_pad=not exclusive)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True,
               divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format, "avg", None, ceil_mode,
                 count_include_pad=not exclusive)


def _adaptive_pool(x, output_size, n, data_format, mode):
    channel_last = data_format in ("NLC", "NHWC", "NDHWC")
    os_ = output_size if isinstance(output_size, (list, tuple)) else [output_size] * n
    os_ = [int(o) if o is not None else None for o in os_]

    def f(v):
        nd = v.ndim
        spatial = list(range(1, 1 + n)) if channel_last else list(range(2, nd))
        out = v.astype(jnp.float32) if mode == "avg" else v
        for d, o in zip(spatial, os_):
            if o is None:
                continue
            in_s = out.shape[d]
            # paddle adaptive pooling: bin i covers [floor(i*in/o), ceil((i+1)*in/o))
            starts = [int(np.floor(i * in_s / o)) for i in range(o)]
            ends = [int(np.ceil((i + 1) * in_s / o)) for i in range(o)]
            segs = []
            for s_, e_ in zip(starts, ends):
                sl = jax.lax.slice_in_dim(out, s_, e_, axis=d)
                if mode == "avg":
                    segs.append(jnp.mean(sl, axis=d, keepdims=True))
                else:
                    segs.append(jnp.max(sl, axis=d, keepdims=True))
            out = jnp.concatenate(segs, axis=d)
        return out.astype(v.dtype)

    return apply_op(f, x, op_name=f"adaptive_{mode}_pool{n}d")


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_pool(x, output_size, 1, "NCL", "avg")


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive_pool(x, output_size, 2, data_format, "avg")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool(x, output_size, 3, data_format, "avg")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 1, "NCL", "max")
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 2, "NCHW", "max")
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_pool(x, output_size, 3, "NCDHW", "max")
    return (out, None) if return_mask else out


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
    k = _tup(kernel_size, 2)
    s = _tup(stride, 2) if stride is not None else k

    def f(v, idx):
        n, c, h, w = v.shape
        if output_size is not None:
            oh, ow = int(output_size[-2]), int(output_size[-1])
        else:
            oh = (h - 1) * s[0] + k[0]
            ow = (w - 1) * s[1] + k[1]
        flat = jnp.zeros((n, c, oh * ow), v.dtype)
        out = flat.at[
            jnp.arange(n)[:, None, None], jnp.arange(c)[None, :, None],
            idx.reshape(n, c, -1).astype(jnp.int32)].set(v.reshape(n, c, -1))
        return out.reshape(n, c, oh, ow)

    return apply_op(f, x, indices)
