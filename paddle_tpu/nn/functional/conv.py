"""Convolutions (ref: python/paddle/nn/functional/conv.py).

All lower to lax.conv_general_dilated, which XLA tiles onto the MXU.
Weights use paddle layout [out_c, in_c/groups, *spatial].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...framework.dispatch import apply_op


def _tup(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(i) for i in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
        return tuple(int(i) for i in v)
    return tuple(int(v) for _ in range(n))


def _pad_spec(padding, n, stride=None, dilation=None, ksize=None):
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, (list, tuple)) and len(padding) == n and \
            isinstance(padding[0], (list, tuple)):
        return [tuple(p) for p in padding]
    if isinstance(padding, (list, tuple)) and len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    p = _tup(padding, n)
    return [(int(i), int(i)) for i in p]


def _dimnums(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv_fn(v, w, *b, n, channel_last, stride, pad, dilation, groups):
    """Closure-free conv kernel fn: config arrives as hashable kwargs so the
    cached-vjp dispatch (framework/dispatch.py) can compile it once per
    (shape, config) instead of retracing every eager call."""
    # weight always [out, in/groups, *k] (paddle layout); convert per spec
    if n == 1:
        wj = w.transpose(2, 1, 0) if channel_last else w
    elif n == 2:
        wj = w.transpose(2, 3, 1, 0) if channel_last else w
    else:
        wj = w.transpose(2, 3, 4, 1, 0) if channel_last else w
    lhs_spec, rhs_spec, out_spec = _dimnums(n, channel_last)
    out = jax.lax.conv_general_dilated(
        v, wj,
        window_strides=stride,
        padding=pad,
        rhs_dilation=dilation,
        dimension_numbers=(lhs_spec, rhs_spec, out_spec),
        feature_group_count=groups,
        preferred_element_type=jnp.float32 if v.dtype == jnp.bfloat16 else None,
    )
    out = out.astype(v.dtype)
    if b:
        bias_shape = [1] * out.ndim
        bias_shape[-1 if channel_last else 1] = b[0].shape[0]
        out = out + b[0].reshape(bias_shape)
    return out


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = data_format in ("NLC", "NHWC", "NDHWC")
    kw = dict(n=n, channel_last=channel_last, stride=_tup(stride, n),
              pad=_hashable_pad(_pad_spec(padding, n)),
              dilation=_tup(dilation, n), groups=groups)
    if bias is None:
        return apply_op(_conv_fn, x, weight, op_name=f"conv{n}d", **kw)
    return apply_op(_conv_fn, x, weight, bias, op_name=f"conv{n}d", **kw)


def _hashable_pad(pad):
    if isinstance(pad, list):
        return tuple(tuple(p) if isinstance(p, (list, tuple)) else p for p in pad)
    return pad


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 "NLC" if data_format == "NLC" else "NCW")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups, n,
                    data_format, output_size):
    channel_last = data_format in ("NLC", "NHWC", "NDHWC")
    stride = _tup(stride, n)
    dilation = _tup(dilation, n)
    opad = _tup(output_padding, n) if output_padding is not None else (0,) * n
    if isinstance(padding, str):
        raise NotImplementedError("string padding for conv_transpose")
    pads = _pad_spec(padding, n)

    def f(v, w, *b):
        # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
        # grad-of-conv formulation: lhs-dilate input by stride
        if channel_last:
            perm = [0, n + 1] + list(range(1, n + 1))
            v_nc = v.transpose(perm)  # to NC...
        else:
            v_nc = v
        in_c = v_nc.shape[1]
        # build the forward-conv weight [in_c, out_c/groups, *k] -> use as
        # conv with flipped kernel: out = conv(dilated_x, flip(w^T))
        wt = jnp.flip(w, axis=tuple(range(2, 2 + n)))  # flip spatial
        # w: [in, out/g, *k] -> conv weight [out, in/g, *k]
        wc = jnp.reshape(wt, (groups, in_c // groups) + wt.shape[1:])
        wc = jnp.swapaxes(wc, 1, 2)  # [g, out/g, in/g, *k]
        wc = jnp.reshape(wc, (-1,) + wc.shape[2:])  # [out, in/g, *k]
        conv_pads = []
        for i in range(n):
            k_eff = dilation[i] * (w.shape[2 + i] - 1)
            lo, hi = pads[i]
            conv_pads.append((k_eff - lo, k_eff - hi + opad[i]))
        out = jax.lax.conv_general_dilated(
            v_nc, wc,
            window_strides=(1,) * n,
            padding=conv_pads,
            lhs_dilation=stride,
            rhs_dilation=dilation,
            dimension_numbers=_dimnums(n, False),
            feature_group_count=groups,
        ).astype(v.dtype)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[1] = b[0].shape[0]
            out = out + b[0].reshape(bias_shape)
        if channel_last:
            perm_back = [0] + list(range(2, n + 2)) + [1]
            out = out.transpose(perm_back)
        return out

    if bias is None:
        return apply_op(f, x, weight, op_name=f"conv{n}d_transpose")
    return apply_op(f, x, weight, bias, op_name=f"conv{n}d_transpose")


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1,
                     dilation=1, output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups,
                           1, "NLC" if data_format == "NLC" else "NCW", output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1,
                     dilation=1, output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups,
                           2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0, groups=1,
                     dilation=1, output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation, groups,
                           3, data_format, output_size)
