"""Activation functions (ref: python/paddle/nn/functional/activation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.dispatch import apply_op, defop

relu = defop(jax.nn.relu, "relu")
relu6 = defop(lambda x: jnp.clip(x, 0, 6), "relu6")
sigmoid = defop(jax.nn.sigmoid, "sigmoid")
tanh = defop(jnp.tanh, "tanh")
silu = defop(jax.nn.silu, "silu")
swish = silu
mish = defop(lambda x: x * jnp.tanh(jax.nn.softplus(x)), "mish")
hardswish = defop(lambda x: x * jnp.clip(x + 3, 0, 6) / 6, "hardswish")
hardsigmoid = defop(lambda x: jnp.clip(x / 6 + 0.5, 0, 1), "hardsigmoid")
tanhshrink = defop(lambda x: x - jnp.tanh(x), "tanhshrink")
softsign = defop(jax.nn.soft_sign, "softsign")
log_sigmoid = defop(jax.nn.log_sigmoid, "log_sigmoid")


def gelu(x, approximate=False, name=None):
    return apply_op(lambda v: jax.nn.gelu(v, approximate=approximate), x, op_name="gelu")


def elu(x, alpha=1.0, name=None):
    return apply_op(lambda v: jax.nn.elu(v, alpha=alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_op(lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x)


def celu(x, alpha=1.0, name=None):
    return apply_op(lambda v: jax.nn.celu(v, alpha=alpha), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_op(lambda v: jax.nn.leaky_relu(v, negative_slope=negative_slope), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def f(v, w):
        if w.size == 1:
            return jnp.where(v >= 0, v, w.reshape(()) * v)
        ch_axis = 1 if data_format == "NCHW" else v.ndim - 1
        shape = [1] * v.ndim
        shape[ch_axis] = w.size
        return jnp.where(v >= 0, v, w.reshape(shape) * v)

    return apply_op(f, x, weight)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    from ...framework.random import next_key

    def f(v):
        if training:
            a = jax.random.uniform(next_key(), v.shape, jnp.float32, lower, upper).astype(v.dtype)
        else:
            a = (lower + upper) / 2.0
        return jnp.where(v >= 0, v, a * v)

    return apply_op(f, x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_op(lambda v: jnp.clip(v, min, max), x)


def hardshrink(x, threshold=0.5, name=None):
    return apply_op(lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0), x)


def softshrink(x, threshold=0.5, name=None):
    return apply_op(
        lambda v: jnp.where(v > threshold, v - threshold,
                            jnp.where(v < -threshold, v + threshold, 0.0)), x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return apply_op(lambda v: jnp.where(v > threshold, v, value), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return apply_op(
        lambda v: jnp.where(v * beta > threshold, v, jax.nn.softplus(v * beta) / beta), x)


def maxout(x, groups, axis=1, name=None):
    def f(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)

    return apply_op(f, x)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import convert_dtype

    d = convert_dtype(dtype)

    def f(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.softmax(v, axis=axis)

    return apply_op(f, x, op_name="softmax")


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...framework.dtype import convert_dtype

    d = convert_dtype(dtype)

    def f(v):
        if d is not None:
            v = v.astype(d)
        return jax.nn.log_softmax(v, axis=axis)

    return apply_op(f, x, op_name="log_softmax")


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...framework.random import next_key

    def f(v):
        g = jax.random.gumbel(next_key(), v.shape, v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            y_hard = jnp.zeros_like(y)
            y_hard = jnp.put_along_axis(y_hard, idx, 1.0, axis=axis, inplace=False) \
                if hasattr(jnp, "put_along_axis") else \
                jnp.zeros_like(y).at[...].set(jax.nn.one_hot(
                    jnp.argmax(y, axis=axis), y.shape[axis], axis=axis, dtype=y.dtype))
            y = y_hard + jax.lax.stop_gradient(-y) + y
        return y

    return apply_op(f, x)


def glu(x, axis=-1, name=None):
    return apply_op(lambda v: jax.nn.glu(v, axis=axis), x)
