"""Remaining functional ops for parity (affine_grid/grid_sample, diag_embed,
margin_cross_entropy, gather_tree, inplace aliases...)."""
from __future__ import annotations

import math

import numpy as np

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, to_array
from ...framework.dispatch import apply_op


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    return apply_op(
        lambda a, b: jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p), -1,
                                       keepdims=keepdim), 1.0 / p), x, y)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def f(v):
        n = v.shape[-1] + abs(offset)
        out = jnp.zeros(v.shape[:-1] + (n, n), v.dtype)
        idx = jnp.arange(v.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(v)
        # move the two new dims to dim1/dim2
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for pos, src in order:
            perm.insert(pos, src)
        return out.transpose(perm)

    return apply_op(f, input)


def hsigmoid_loss(input, label, num_classes, weight, bias=None, path_table=None,
                  path_code=None, is_sparse=False, name=None):
    from ..layer.extras import HSigmoidLoss

    layer = HSigmoidLoss.__new__(HSigmoidLoss)
    from ..layer_base import Layer

    Layer.__init__(layer)
    layer.num_classes = num_classes
    layer.is_custom = path_table is not None
    layer.weight = weight
    layer.bias = bias
    if not layer.is_custom:
        import numpy as np

        n_nodes = num_classes - 1
        depth = max(int(math.ceil(math.log2(num_classes))), 1)
        table = np.full((num_classes, depth), -1, np.int32)
        codes = np.zeros((num_classes, depth), np.float32)
        for c in range(num_classes):
            node = c + n_nodes
            path = []
            while node > 0:
                parent = (node - 1) // 2
                path.append((parent, float(node == 2 * parent + 2)))
                node = parent
            for d, (nid, code) in enumerate(reversed(path)):
                if d < depth and nid < n_nodes:
                    table[c, d] = nid
                    codes[c, d] = code
        layer._table = jnp.asarray(table)
        layer._codes = jnp.asarray(codes)
    return layer.forward(input, label, path_table, path_code)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-style margin softmax (ref margin_cross_entropy op)."""

    def f(z, lbl):
        lbl_i = lbl.astype(jnp.int32)
        theta = jnp.arccos(jnp.clip(z, -1 + 1e-7, 1 - 1e-7))
        target_theta = margin1 * theta + margin2
        target_logit = jnp.cos(target_theta) - margin3
        onehot = jax.nn.one_hot(lbl_i, z.shape[-1], dtype=z.dtype)
        mod = jnp.where(onehot > 0, target_logit, z)
        logits_s = mod * scale
        logp = jax.nn.log_softmax(logits_s, -1)
        loss = -jnp.take_along_axis(logp, lbl_i[:, None], 1)[:, 0]
        if reduction == "mean":
            loss = jnp.mean(loss)
        elif reduction == "sum":
            loss = jnp.sum(loss)
        if return_softmax:
            return loss, jax.nn.softmax(logits_s, -1)
        return loss

    return apply_op(f, logits, label)


def gather_tree(ids, parents):
    """Beam-search backtrace (ref gather_tree op). ids/parents: (T, B, beam)."""

    def f(idv, par):
        T = idv.shape[0]
        idv = idv.astype(jnp.int32)
        par = par.astype(jnp.int32)

        def step(carry, t):
            beams = carry  # (B, beam) current beam indices
            tok = jnp.take_along_axis(idv[t], beams, axis=-1)
            new_beams = jnp.take_along_axis(par[t], beams, axis=-1)
            return new_beams, tok

        init = jnp.broadcast_to(
            jnp.arange(idv.shape[2], dtype=jnp.int32), idv.shape[1:]
        ).astype(jnp.int32)  # match take_along_axis output under x64
        _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
        return jnp.flip(toks, 0).astype(jnp.int64)

    return apply_op(f, ids, parents)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Ref affine_grid op: 2D affine θ (N,2,3) → sampling grid (N,H,W,2)."""
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in out_shape]

    def f(th):
        N, _, H, W = shape

        def lin(n):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, n)
            return (jnp.arange(n) * 2 + 1) / n - 1

        ys = lin(H)
        xs = lin(W)
        gx, gy = jnp.meshgrid(xs, ys)  # (H, W)
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # (HW, 3)
        out = jnp.einsum("nij,pj->npi", th, base)  # (N, HW, 2)
        return out.reshape(N, H, W, 2)

    return apply_op(f, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros", align_corners=True,
                name=None):
    """Ref grid_sample op: sample NCHW input at grid (N,H,W,2) in [-1,1]."""

    def f(v, g):
        N, C, H, W = v.shape

        def unnorm(coord, size):
            if align_corners:
                return (coord + 1) / 2 * (size - 1)
            return ((coord + 1) * size - 1) / 2

        gx = unnorm(g[..., 0], W)
        gy = unnorm(g[..., 1], H)

        if padding_mode == "border":
            gx = jnp.clip(gx, 0, W - 1)
            gy = jnp.clip(gy, 0, H - 1)
        elif padding_mode == "reflection":
            gx = jnp.abs(jnp.mod(gx, 2 * (W - 1)) - (W - 1)) if W > 1 else gx * 0
            gy = jnp.abs(jnp.mod(gy, 2 * (H - 1)) - (H - 1)) if H > 1 else gy * 0

        if mode == "nearest":
            xi = jnp.round(gx).astype(jnp.int32)
            yi = jnp.round(gy).astype(jnp.int32)
            valid = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
            xi = jnp.clip(xi, 0, W - 1)
            yi = jnp.clip(yi, 0, H - 1)
            out = v[jnp.arange(N)[:, None, None], :, yi, xi]
            out = jnp.where(valid[..., None], out, 0.0)
            return jnp.moveaxis(out, -1, 1)

        x0 = jnp.floor(gx).astype(jnp.int32)
        y0 = jnp.floor(gy).astype(jnp.int32)
        x1, y1 = x0 + 1, y0 + 1
        wx = gx - x0
        wy = gy - y0

        def sample(xi, yi):
            valid = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
            xi_c = jnp.clip(xi, 0, W - 1)
            yi_c = jnp.clip(yi, 0, H - 1)
            out = v[jnp.arange(N)[:, None, None], :, yi_c, xi_c]  # (N,h,w,C)
            return jnp.where(valid[..., None], out, 0.0)

        v00 = sample(x0, y0)
        v01 = sample(x1, y0)
        v10 = sample(x0, y1)
        v11 = sample(x1, y1)
        top = v00 * (1 - wx)[..., None] + v01 * wx[..., None]
        bot = v10 * (1 - wx)[..., None] + v11 * wx[..., None]
        out = top * (1 - wy)[..., None] + bot * wy[..., None]
        return jnp.moveaxis(out, -1, 1)

    return apply_op(f, x, grid)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
    from ..layer.extras import MaxUnPool1D

    return MaxUnPool1D(kernel_size, stride, padding, data_format, output_size)(
        x, indices)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    from ..layer.extras import MaxUnPool3D

    return MaxUnPool3D(kernel_size, stride, padding, data_format, output_size)(
        x, indices)


def sparse_attention(query, key, value, sparse_csr_offset, sparse_csr_columns,
                     key_padding_mask=None, attn_mask=None, name=None):
    """CSR-masked attention (ref sparse_attention op: per-row allowed
    columns in CSR offset/columns form). TPU-native note: the sparsity
    PATTERN is honored exactly, but compute is dense-masked — on the MXU a
    dense masked softmax beats the reference's CUDA block-sparse kernels at
    these sizes, and true long-sequence sparsity is served by ring/flash
    attention instead. The CSR layout is concretized (eager), matching the
    reference's host-resident layout tensors.

    q/k/v: (B, H, S, D); offset: (B, H, S+1); columns: (B, H, nnz).
    """
    offs = np.asarray(to_array(sparse_csr_offset)).astype(np.int64)
    cols = np.asarray(to_array(sparse_csr_columns)).astype(np.int64)
    mask = _csr_allow_mask(offs, cols)

    def f(q, k, v, *extra):
        d = q.shape[-1]
        sc = jnp.einsum("bhsd,bhtd->bhst", q, k) / jnp.sqrt(
            jnp.asarray(d, jnp.float32)).astype(q.dtype)
        sc = sc.astype(jnp.float32)
        i = 0
        # ADDITIVE masks (0 = keep, -inf/-1e30 = drop) — the convention the
        # rest of this package's attention ops use
        if key_padding_mask is not None:
            sc = sc + extra[i][:, None, None, :].astype(jnp.float32)
            i += 1
        if attn_mask is not None:
            sc = sc + extra[i].astype(jnp.float32)
        sc = jnp.where(mask, sc, -1e30)
        p = jax.nn.softmax(sc, axis=-1).astype(q.dtype)
        p = jnp.where(mask, p, 0.0)
        return jnp.einsum("bhst,bhtd->bhsd", p, v)

    extra = []
    if key_padding_mask is not None:
        extra.append(key_padding_mask)
    if attn_mask is not None:
        extra.append(attn_mask)
    return apply_op(f, query, key, value, *extra)


_CSR_MASK_CACHE: dict = {}


def _csr_allow_mask(offs, cols):
    """Dense (B,H,S,S) allow-mask from a CSR layout — one vectorized
    assignment per (b,h), cached on the layout bytes (training reuses the
    same sparsity pattern every step)."""
    key = (offs.tobytes(), cols.tobytes())
    hit = _CSR_MASK_CACHE.get(key)
    if hit is not None:
        return hit
    B, H, S = offs.shape[0], offs.shape[1], offs.shape[2] - 1
    allow = np.zeros((B * H, S, S), bool)
    offs2 = offs.reshape(B * H, S + 1)
    cols2 = cols.reshape(B * H, -1)
    for i in range(B * H):
        counts = np.diff(offs2[i])
        rows = np.repeat(np.arange(S), counts)
        cs = cols2[i, offs2[i, 0]:offs2[i, -1]]
        allow[i, rows, cs] = True
    mask = jnp.asarray(allow.reshape(B, H, S, S))
    if len(_CSR_MASK_CACHE) > 8:  # bound the cache
        _CSR_MASK_CACHE.clear()
    _CSR_MASK_CACHE[key] = mask
    return mask


def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-Transducer loss (ref warprnnt-backed rnnt_loss op) as the lattice
    forward DP, jit-compiled: alpha(t, u) over the (T, U+1) grid with
    blank transitions advancing t and label transitions advancing u.

    logits: (B, T, U+1, V) unnormalized; labels: (B, U) int; lengths per
    sample select each lattice's terminal cell. FastEmit regularization is
    not implemented — a nonzero ``fastemit_lambda`` raises rather than
    silently training without it.
    """
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss: fastemit_lambda != 0 is not supported (the FastEmit "
            "gradient-blending term is not implemented); pass 0.0")

    def f(lg, lb, tl, ul):
        lp = jax.nn.log_softmax(lg.astype(jnp.float32), axis=-1)
        B, T, U1, _ = lp.shape
        blank_lp = lp[..., blank]                      # (B, T, U+1)
        neg_inf = jnp.float32(-1e30)
        if U1 > 1:
            lab_lp = jnp.take_along_axis(
                lp[:, :, :U1 - 1, :], lb[:, None, :, None].astype(jnp.int32),
                axis=-1)[..., 0]                       # (B, T, U)
        else:  # U == 0: dummy column so traced indexing stays in bounds
            lab_lp = jnp.full((B, T, 1), neg_inf)
        lab_lp = jnp.concatenate(
            [jnp.full((B, T, 1), neg_inf), lab_lp], axis=2)  # u-1 gather pad

        # anti-diagonal wavefront: diagonal d holds cells (t = d-u, u) —
        # T+U sequential steps instead of T·U (each diagonal vectorized
        # over u), the standard transducer lattice schedule
        u_ar = jnp.arange(U1)

        def diag_step(alpha_prev, d):
            tvec = d - u_ar                             # (U1,) t per cell
            on = (tvec >= 0) & (tvec < T)
            tc = jnp.clip(tvec, 0, T - 1)
            # blank move from (t-1, u): previous diagonal, same u
            b_lp = blank_lp[:, jnp.clip(tvec - 1, 0, T - 1), u_ar]  # (B, U1)
            from_blank = jnp.where((tvec > 0)[None, :],
                                   alpha_prev + b_lp, neg_inf)
            # label move from (t, u-1): previous diagonal, u-1
            l_lp = lab_lp[:, tc, u_ar]                  # lab_lp[t, u-1] (B,U1)
            alpha_um1 = jnp.concatenate(
                [jnp.full((B, 1), neg_inf), alpha_prev[:, :-1]], axis=1)
            from_label = jnp.where((u_ar > 0)[None, :],
                                   alpha_um1 + l_lp, neg_inf)
            cur = jnp.logaddexp(from_blank, from_label)
            cur = jnp.where((d == 0) & (u_ar == 0)[None, :], 0.0, cur)
            cur = jnp.where(on[None, :], cur, neg_inf)
            return cur, cur

        _, diags = jax.lax.scan(diag_step, jnp.full((B, U1), neg_inf),
                                jnp.arange(T + U1 - 1))  # (T+U1-1, B, U1)
        tl_i = tl.astype(jnp.int32) - 1
        ul_i = ul.astype(jnp.int32)
        bi = jnp.arange(B)
        final_alpha = diags[tl_i + ul_i, bi, ul_i]
        final = final_alpha + blank_lp[bi, tl_i, ul_i]
        loss = -final
        if reduction == "mean":
            return jnp.mean(loss)
        if reduction == "sum":
            return jnp.sum(loss)
        return loss

    return apply_op(f, logits, labels, logit_lengths, label_lengths)


def class_center_sample(label, num_classes, num_samples, group=None,
                        seed=None):
    """Ref class_center_sample op (margin-softmax training): sample
    ``num_samples`` class centers containing every positive class; return
    (remapped labels into the sampled set, sampled class indices). The
    reference unions positives across the model-parallel group; here the
    single-process form (the TP path shards the classifier via GSPMD, which
    needs no explicit sampling).

    ``seed`` (the reference op accepts one too) makes the negative-center
    draw deterministic per call; when unset, fresh entropy is drawn from the
    framework generator each call (fresh negatives every step, yet the whole
    sequence is reproducible after ``paddle.seed``) and is immune to other
    global-RNG consumers."""
    lbl = np.asarray(to_array(label)).astype(np.int64).reshape(-1)
    pos = np.unique(lbl)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        from ...framework.random import derived_rng

        if seed is None:
            # advance the framework generator: fresh draw per call, still
            # reproducible as a sequence after paddle.seed
            import jax as _jax

            from ...framework.random import default_generator

            entropy = np.asarray(_jax.random.key_data(  # graftlint: noqa[host-sync]
                default_generator().next_key())).ravel().tolist()
        else:
            entropy = [int(seed)]
        # local generator: never perturbed by (or perturbing) np.random
        gen = derived_rng(*entropy, len(pos), num_classes)
        rest = np.setdiff1d(np.arange(num_classes), pos)
        extra = gen.permutation(rest)[:num_samples - len(pos)]
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, np.int64)
    remap[sampled] = np.arange(len(sampled))
    return (Tensor(jnp.asarray(remap[lbl])),
            Tensor(jnp.asarray(sampled)))


# in-place activation aliases
def relu_(x, name=None):
    x._value = jax.nn.relu(x.value)
    return x


def elu_(x, alpha=1.0, name=None):
    x._value = jax.nn.elu(x.value, alpha)
    return x


def softmax_(x, axis=-1, dtype=None, name=None):
    x._value = jax.nn.softmax(x.value, axis=axis)
    return x


def tanh_(x, name=None):
    x._value = jnp.tanh(x.value)
    return x
