"""Attention ops.

Ref: paddle/fluid/operators/fused/fused_attention_op.cu + fmha_ref.h — rebuilt
as a single jnp composition (XLA fuses) with an optional Pallas
flash-attention fast path (paddle_tpu.ops.flash_attention) used automatically
on TPU for long sequences.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...framework.dispatch import apply_op
from ...framework.flags import GLOBAL_FLAGS


def _sdpa_ref(q, k, v, mask=None, dropout_p=0.0, is_causal=False, scale=None):
    """q,k,v: (B, S, H, D) paddle convention."""
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    qh = jnp.swapaxes(q, 1, 2)  # B,H,S,D
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhsd,bhtd->bhst", qh, kh).astype(jnp.float32) * s
    if is_causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cm, logits, -1e30)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -1e30)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vh)
    return jnp.swapaxes(out, 1, 2)  # B,S,H,D


def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None, name=None):
    """Inputs (B, S, H, D). Uses the Pallas flash kernel on TPU when shapes
    allow, else the XLA reference path."""
    use_pallas = GLOBAL_FLAGS.get("use_pallas_kernels")
    if use_pallas and attn_mask is None and dropout_p == 0.0:
        try:
            from ...ops.flash_attention import flash_attention_bshd

            q_shape = query.shape
            # pallas kernel needs seq multiple of block; fall back otherwise
            if q_shape[1] % 128 == 0 and key.shape[1] % 128 == 0 and q_shape[-1] >= 64:
                return apply_op(
                    lambda q, k, v: flash_attention_bshd(q, k, v, causal=is_causal,
                                                         scale=scale),
                    query, key, value, op_name="flash_attention")
        except Exception:
            pass
    args = [query, key, value]
    if attn_mask is not None:
        return apply_op(
            lambda q, k, v, m: _sdpa_ref(q, k, v, m, dropout_p, is_causal, scale),
            query, key, value, attn_mask, op_name="sdpa")
    return apply_op(lambda q, k, v: _sdpa_ref(q, k, v, None, dropout_p, is_causal, scale),
                    query, key, value, op_name="sdpa")
