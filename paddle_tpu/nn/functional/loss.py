"""Loss functions (ref: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, to_array
from ...framework.dispatch import apply_op


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """Ref softmax_with_cross_entropy / F.cross_entropy semantics."""

    def f(logits, lbl, *w):
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-30, None))
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape == logits.shape):
            tgt = lbl.astype(jnp.float32)
            if label_smoothing > 0:
                k = logits.shape[axis]
                tgt = tgt * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
            if w:
                loss = loss * jnp.sum(tgt * w[0], axis=axis)
            return _reduce(loss, reduction)
        lbl_i = lbl.astype(jnp.int32)
        if lbl_i.ndim == logits.ndim:
            lbl_i = jnp.squeeze(lbl_i, axis=axis)
        if label_smoothing > 0:
            k = logits.shape[axis]
            onehot = jax.nn.one_hot(lbl_i, k, axis=axis, dtype=jnp.float32)
            tgt = onehot * (1 - label_smoothing) + label_smoothing / k
            loss = -jnp.sum(tgt * logp, axis=axis)
        else:
            loss = -jnp.take_along_axis(logp, jnp.expand_dims(lbl_i, axis), axis=axis)
            loss = jnp.squeeze(loss, axis=axis)
        valid = (lbl_i != ignore_index)
        loss = jnp.where(valid, loss, 0.0)
        if w:
            wt = jnp.take(w[0], jnp.clip(lbl_i, 0, None), axis=0)
            wt = jnp.where(valid, wt, 0.0)
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(f, *args, op_name="cross_entropy")


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)
    from .activation import softmax as _softmax

    loss = loss.unsqueeze(axis) if loss.ndim < len(logits.shape) else loss
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def f(logp, lbl, *w):
        lbl_i = lbl.astype(jnp.int32)
        loss = -jnp.take_along_axis(logp, jnp.expand_dims(lbl_i, 1), axis=1).squeeze(1)
        valid = lbl_i != ignore_index
        loss = jnp.where(valid, loss, 0.0)
        if w:
            wt = jnp.take(w[0], jnp.clip(lbl_i, 0, None))
            wt = jnp.where(valid, wt, 0.0)
            loss = loss * wt
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(wt), 1e-12)
        if reduction == "mean":
            return jnp.sum(loss) / jnp.maximum(jnp.sum(valid), 1)
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(f, *args)


def mse_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.square(a - b), reduction), input, label,
                    op_name="mse_loss")


def l1_loss(input, label, reduction="mean", name=None):
    return apply_op(lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d / delta, d - 0.5 * delta) * delta
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)

    return apply_op(f, input, label)


def huber_loss(input, label, delta=1.0, reduction="mean", name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d <= delta, 0.5 * d * d, delta * d - 0.5 * delta * delta)
        return _reduce(loss, reduction)

    return apply_op(f, input, label)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, t, *w):
        p32 = jnp.clip(p.astype(jnp.float32), 1e-12, 1 - 1e-7)
        loss = -(t * jnp.log(p32) + (1 - t) * jnp.log1p(-p32))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def f(z, t, *extra):
        z32 = z.astype(jnp.float32)
        t32 = t.astype(jnp.float32)
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]
            i += 1
        if pos_weight is not None:
            pw = extra[i]
        # stable: max(z,0) - z*t + log(1+exp(-|z|)), with pos_weight on the t term
        if pw is not None:
            log_w = (pw - 1) * t32 + 1
            loss = (1 - t32) * z32 + log_w * (jnp.logaddexp(0.0, -jnp.abs(z32))
                                              + jnp.maximum(-z32, 0.0))
        else:
            loss = jnp.maximum(z32, 0) - z32 * t32 + jnp.logaddexp(0.0, -jnp.abs(z32))
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)

    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply_op(f, *args)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(lp, t):
        if log_target:
            loss = jnp.exp(t) * (t - lp)
        else:
            loss = t * (jnp.log(jnp.clip(t, 1e-12, None)) - lp)
        if reduction == "batchmean":
            return jnp.sum(loss) / lp.shape[0]
        return _reduce(loss, reduction)

    return apply_op(f, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    return apply_op(
        lambda a, b, t: _reduce(jnp.maximum(0.0, -t * (a - b) + margin), reduction),
        input, other, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return apply_op(
        lambda a, t: _reduce(jnp.where(t == 1, a, jnp.maximum(0.0, margin - a)), reduction),
        input, label)


def cosine_embedding_loss(input1, input2, label, margin=0, reduction="mean", name=None):
    def f(a, b, t):
        cos = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1) *
                                    jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(t == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)

    return apply_op(f, input1, input2, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p), -1), 1 / p)

        dp = dist(a, pos)
        dn = dist(a, neg)
        if swap:
            dn = jnp.minimum(dn, dist(pos, neg))
        return _reduce(jnp.maximum(dp - dn + margin, 0.0), reduction)

    return apply_op(f, input, positive, negative)


def triplet_margin_with_distance_loss(input, positive, negative, distance_function=None,
                                      margin=1.0, swap=False, reduction="mean", name=None):
    if distance_function is None:
        return triplet_margin_loss(input, positive, negative, margin=margin, swap=swap,
                                   reduction=reduction)
    dp = distance_function(input, positive)
    dn = distance_function(input, negative)
    if swap:
        dpn = distance_function(positive, negative)
        dn = apply_op(jnp.minimum, dn, dpn)
    return apply_op(lambda a, b: _reduce(jnp.maximum(a - b + margin, 0.0), reduction), dp, dn)


def soft_margin_loss(input, label, reduction="mean", name=None):
    return apply_op(
        lambda a, t: _reduce(jnp.log1p(jnp.exp(-t * a)), reduction), input, label)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def f(z, t, *w):
        loss = -(t * jax.nn.log_sigmoid(z) + (1 - t) * jax.nn.log_sigmoid(-z))
        loss = jnp.mean(loss, axis=-1)
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(f, *args)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, t, *nrm):
        p = jax.nn.sigmoid(z.astype(jnp.float32))
        ce = jnp.maximum(z, 0) - z * t + jnp.logaddexp(0.0, -jnp.abs(z))
        p_t = p * t + (1 - p) * (1 - t)
        a_t = alpha * t + (1 - alpha) * (1 - t)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if nrm:
            loss = loss / nrm[0]
        return _reduce(loss, reduction)

    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply_op(f, *args)


def log_loss(input, label, epsilon=1e-4, name=None):
    return apply_op(
        lambda p, t: -t * jnp.log(p + epsilon) - (1 - t) * jnp.log1p(epsilon - p + 1e-30),
        input, label)


def square_error_cost(input, label):
    return apply_op(lambda a, b: jnp.square(a - b), input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0, reduction="mean",
             norm_by_times=False):
    """CTC via the standard forward algorithm in log space (lax.scan over time).

    Ref: warpctc op. log_probs: (T, B, C) already log-softmaxed or raw logits.
    """

    def f(lp, lbl, in_len, lbl_len):
        lp = jax.nn.log_softmax(lp.astype(jnp.float32), axis=-1)
        T, B, C = lp.shape
        L = lbl.shape[1]
        S = 2 * L + 1
        # extended labels: blank, l1, blank, l2, ... blank
        ext = jnp.full((B, S), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lbl.astype(jnp.int32))
        neg_inf = -1e30
        alpha0 = jnp.full((B, S), neg_inf)
        alpha0 = alpha0.at[:, 0].set(lp[0, jnp.arange(B), blank])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lbl_len > 0, lp[0, jnp.arange(B), ext[:, 1]], neg_inf))

        same_as_prev2 = jnp.concatenate(
            [jnp.ones((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, lp_t):
            shift1 = jnp.concatenate([jnp.full((B, 1), neg_inf), alpha[:, :-1]], 1)
            shift2 = jnp.concatenate([jnp.full((B, 2), neg_inf), alpha[:, :-2]], 1)
            shift2 = jnp.where(same_as_prev2, neg_inf, shift2)
            new = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
            emit = lp_t[jnp.arange(B)[:, None], ext]
            return new + emit, None

        def scan_fn(carry, t):
            alpha = carry
            new, _ = step(alpha, lp[t])
            # freeze past input_length
            new = jnp.where((t < in_len)[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(scan_fn, alpha0, jnp.arange(1, T))
        end1 = alpha[jnp.arange(B), 2 * lbl_len.astype(jnp.int32)]
        end2 = alpha[jnp.arange(B), jnp.maximum(2 * lbl_len.astype(jnp.int32) - 1, 0)]
        ll = jnp.logaddexp(end1, end2)
        loss = -ll
        if reduction == "mean":
            return jnp.mean(loss / jnp.maximum(lbl_len.astype(jnp.float32), 1.0))
        return _reduce(loss, reduction)

    return apply_op(f, log_probs, labels, input_lengths, label_lengths, op_name="ctc_loss")


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def f(a, p, lbl):
        logits = a @ p.T
        t = (lbl[:, None] == lbl[None, :]).astype(jnp.float32)
        t = t / jnp.sum(t, -1, keepdims=True)
        ce = -jnp.sum(t * jax.nn.log_softmax(logits, -1), -1)
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, -1)) + jnp.mean(jnp.sum(p * p, -1))) * 0.25
        return jnp.mean(ce) + reg

    return apply_op(f, anchor, positive, labels)


def dice_loss(input, label, epsilon=1e-5, name=None):
    def f(p, t):
        t1 = jax.nn.one_hot(t.squeeze(-1).astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        inter = jnp.sum(p * t1, axis=tuple(range(1, p.ndim)))
        union = jnp.sum(p, axis=tuple(range(1, p.ndim))) + \
            jnp.sum(t1, axis=tuple(range(1, p.ndim)))
        return jnp.mean(1 - 2 * inter / (union + epsilon))

    return apply_op(f, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6, reduction="mean",
                      name=None):
    def f(mu, t, var):
        var = jnp.maximum(var, epsilon)
        loss = 0.5 * (jnp.log(var) + jnp.square(t - mu) / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.pi)
        return _reduce(loss, reduction)

    return apply_op(f, input, label, variance)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(z, t):
        if log_input:
            loss = jnp.exp(z) - t * z
        else:
            loss = z - t * jnp.log(z + epsilon)
        if full:
            stirling = t * jnp.log(jnp.maximum(t, 1.0)) - t + \
                0.5 * jnp.log(2 * jnp.pi * jnp.maximum(t, 1.0))
            loss = loss + jnp.where(t > 1, stirling, 0.0)
        return _reduce(loss, reduction)

    return apply_op(f, input, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None, reduction="mean",
                      name=None):
    def f(z, t, *w):
        n, c = z.shape
        correct = jnp.take_along_axis(z, t.astype(jnp.int32)[:, None], 1)
        diff = jnp.maximum(0.0, margin - correct + z)
        diff = jnp.power(diff, p)
        if w:
            wt = jnp.take(w[0], t.astype(jnp.int32))[:, None]
            diff = diff * wt
        mask = jax.nn.one_hot(t.astype(jnp.int32), c) == 0
        loss = jnp.sum(diff * mask, -1) / c
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply_op(f, *args)


def rnnt_loss(*args, **kwargs):
    raise NotImplementedError("rnnt_loss: planned (transducer loss via lax.scan)")
