"""Normalization ops (ref: python/paddle/nn/functional/norm.py).

On TPU these fuse into surrounding element-wise chains via XLA; the Pallas
fused rms/layer-norm kernels in paddle_tpu.ops are used by the transformer
fast path.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, to_array
from ...framework.dispatch import apply_op


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(v):
        norm = jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=axis, keepdims=True), 1.0 / p)
        return v / jnp.maximum(norm, epsilon)

    return apply_op(f, x)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    ns = normalized_shape if isinstance(normalized_shape, (list, tuple)) else \
        [normalized_shape]
    n_axes = len(ns)

    def f(v, *wb):
        axes = tuple(range(v.ndim - n_axes, v.ndim))
        mean = jnp.mean(v.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(v.astype(jnp.float32), axis=axes, keepdims=True)
        out = (v.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].astype(jnp.float32)
        return out.astype(v.dtype)

    args = [a for a in (weight, bias) if a is not None]
    return apply_op(f, x, *args, op_name="layer_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-05, data_format="NCHW", use_global_stats=None,
               name=None):
    channel_axis = 1 if data_format.startswith("NC") else -1
    use_stats = (not training) if use_global_stats is None else use_global_stats

    def f(v, rm, rv, *wb):
        axes = tuple(i for i in range(v.ndim) if i != channel_axis % v.ndim)
        shape = [1] * v.ndim
        shape[channel_axis % v.ndim] = v.shape[channel_axis % v.ndim]
        if use_stats:
            mean, var = rm, rv
        else:
            xf = v.astype(jnp.float32)
            mean = jnp.mean(xf, axis=axes)
            var = jnp.var(xf, axis=axes)
        out = (v.astype(jnp.float32) - mean.reshape(shape)) * jax.lax.rsqrt(
            var.reshape(shape).astype(jnp.float32) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape).astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape).astype(jnp.float32)
        return out.astype(v.dtype)

    args = [a for a in (weight, bias) if a is not None]
    out = apply_op(f, x, running_mean, running_var, *args, op_name="batch_norm")

    # update running stats (stateful side effect, eager semantics)
    if training and not use_stats and isinstance(running_mean, Tensor):
        v = to_array(x)
        axes = tuple(i for i in range(v.ndim) if i != channel_axis % v.ndim)
        batch_mean = jnp.mean(v.astype(jnp.float32), axis=axes)
        batch_var = jnp.var(v.astype(jnp.float32), axis=axes)
        n = 1
        for i in axes:
            n *= v.shape[i]
        unbiased = batch_var * (n / max(n - 1, 1))
        running_mean._value = (momentum * running_mean.value
                               + (1 - momentum) * batch_mean).astype(running_mean.dtype)
        running_var._value = (momentum * running_var.value
                              + (1 - momentum) * unbiased).astype(running_var.dtype)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW",
                  name=None):
    def f(v, *wb):
        # normalize over spatial dims per (N, C)
        axes = tuple(range(2, v.ndim))
        xf = v.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axes, keepdims=True)
        var = jnp.var(xf, axis=axes, keepdims=True)
        out = (xf - mean) * jax.lax.rsqrt(var + eps)
        shape = [1, v.shape[1]] + [1] * (v.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape).astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape).astype(jnp.float32)
        return out.astype(v.dtype)

    args = [a for a in (weight, bias) if a is not None]
    return apply_op(f, x, *args)


def group_norm(x, num_groups, epsilon=1e-05, weight=None, bias=None, data_format="NCHW",
               name=None):
    def f(v, *wb):
        channel_last = not data_format.startswith("NC")
        if channel_last:
            v_nc = jnp.moveaxis(v, -1, 1)
        else:
            v_nc = v
        n, c = v_nc.shape[:2]
        spatial = v_nc.shape[2:]
        g = v_nc.reshape(n, num_groups, c // num_groups, *spatial).astype(jnp.float32)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        out = ((g - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v_nc.shape)
        shape = [1, c] + [1] * (v_nc.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape).astype(jnp.float32)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape).astype(jnp.float32)
        out = out.astype(v.dtype)
        if channel_last:
            out = jnp.moveaxis(out, 1, -1)
        return out

    args = [a for a in (weight, bias) if a is not None]
    return apply_op(f, x, *args, op_name="group_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (LLaMA-style). Not in the reference's 2.4 API — added because
    our flagship models need it; the Pallas fused version lives in
    paddle_tpu.ops.fused_norm."""

    def f(v, *w):
        xf = v.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        out = xf * jax.lax.rsqrt(ms + epsilon)
        if w:
            out = out * w[0].astype(jnp.float32)
        return out.astype(v.dtype)

    args = [weight] if weight is not None else []
    return apply_op(f, x, *args, op_name="rms_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    def f(v):
        channel_axis = 1 if data_format.startswith("NC") else v.ndim - 1
        sq = jnp.square(v.astype(jnp.float32))
        c = v.shape[channel_axis]
        half = size // 2
        sq_m = jnp.moveaxis(sq, channel_axis, 0)
        padded = jnp.pad(sq_m, [(half, size - 1 - half)] + [(0, 0)] * (sq_m.ndim - 1))
        acc = jnp.zeros_like(sq_m)
        for i in range(size):
            acc = acc + padded[i:i + c]
        acc = jnp.moveaxis(acc, 0, channel_axis)
        return (v / jnp.power(k + alpha * acc / size, beta).astype(v.dtype)).astype(v.dtype)

    return apply_op(f, x)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    def f(w):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((wm.shape[0],), jnp.float32)
        v = None
        for _ in range(power_iters):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v if v is not None else jnp.linalg.norm(wm, 2)
        return w / sigma

    return apply_op(f, weight)
