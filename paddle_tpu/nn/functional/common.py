"""Common nn ops: linear, dropout, pad, interpolate, etc.
(ref: python/paddle/nn/functional/common.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, to_array
from ...framework.dispatch import apply_op
from ...framework.random import next_key


def linear(x, weight, bias=None, name=None):
    """y = x @ W + b with W stored [in, out] (paddle layout,
    ref python/paddle/nn/functional/common.py linear)."""
    if bias is None:
        return apply_op(lambda v, w: jnp.matmul(v, w), x, weight, op_name="linear")
    return apply_op(lambda v, w, b: jnp.matmul(v, w) + b, x, weight, bias, op_name="linear")


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return apply_op(lambda v: v, x)
    key = next_key()

    def f(v):
        shape = list(v.shape)
        if axis is not None:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            shape = [s if i in [a % v.ndim for a in axes] else 1 for i, s in enumerate(shape)]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)

    return apply_op(f, x, op_name="dropout")


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=ax, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=ax, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return apply_op(lambda v: v, x)
    key = next_key()

    def f(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / (1.0 - p) / (1 + p * alpha_p ** 2 / (1.0 - p))) ** 0.5 \
            if p < 1 else 0.0
        a = ((1.0 - p) * (1 + p * alpha_p ** 2)) ** -0.5
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return apply_op(f, x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]

    def f(v):
        nd = v.ndim
        if len(pad) == 2 * nd:
            # full-form pads, paddle order is per-axis ascending
            widths = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
        else:
            # partial spec applies to spatial dims per data_format
            n_spatial = len(pad) // 2
            widths = [(0, 0)] * nd
            if data_format.endswith("C") or data_format in ("NLC", "NHWC", "NDHWC"):
                spatial = list(range(1, 1 + n_spatial))
            else:
                spatial = list(range(nd - n_spatial, nd))
            # paddle pad order: last-dim first pair? For NCHW pad=[l,r,t,b]:
            # pads W then H — i.e. reversed spatial order
            for i, dim in enumerate(reversed(spatial)):
                widths[dim] = (pad[2 * i], pad[2 * i + 1])
        jmode = {"constant": "constant", "reflect": "reflect", "replicate": "edge",
                 "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(v, widths, mode=jmode, constant_values=value)
        return jnp.pad(v, widths, mode=jmode)

    return apply_op(f, x, op_name="pad")


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def interpolate(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
                align_mode=0, data_format="NCHW", name=None):
    if isinstance(size, Tensor):
        size = size.tolist()
    if isinstance(scale_factor, Tensor):
        scale_factor = scale_factor.tolist()

    def f(v):
        channel_last = data_format in ("NHWC", "NLC", "NDHWC")
        nd = v.ndim
        n_spatial = nd - 2
        if channel_last:
            spatial = list(range(1, 1 + n_spatial))
        else:
            spatial = list(range(2, nd))
        in_sizes = [v.shape[d] for d in spatial]
        if size is not None:
            out_sizes = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else \
                [scale_factor] * n_spatial
            out_sizes = [int(i * s) for i, s in zip(in_sizes, sf)]
        out_shape = list(v.shape)
        for d, s in zip(spatial, out_sizes):
            out_shape[d] = s
        jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
                 "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
        if mode == "nearest":
            # jax.image nearest matches paddle (floor) semantics
            return jax.image.resize(v, out_shape, method="nearest")
        if align_corners:
            # build index grid with align_corners semantics per spatial dim
            out = v
            for d, s_out in zip(spatial, out_sizes):
                s_in = out.shape[d]
                if s_out == s_in:
                    continue
                if s_out == 1 or s_in == 1:
                    idx = jnp.zeros((s_out,), jnp.float32)
                else:
                    idx = jnp.linspace(0.0, s_in - 1.0, s_out)
                lo = jnp.floor(idx).astype(jnp.int32)
                hi = jnp.clip(lo + 1, 0, s_in - 1)
                w = (idx - lo).astype(v.dtype)
                lo_v = jnp.take(out, lo, axis=d)
                hi_v = jnp.take(out, hi, axis=d)
                bshape = [1] * out.ndim
                bshape[d] = s_out
                w = w.reshape(bshape)
                out = lo_v * (1 - w) + hi_v * w
            return out.astype(v.dtype)
        return jax.image.resize(v, out_shape, method=jmode).astype(v.dtype)

    return apply_op(f, x, op_name="interpolate")


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c // (r * r), r, r, h, w)
            v = v.transpose(0, 1, 4, 2, 5, 3)
            return v.reshape(n, c // (r * r), h * r, w * r)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, r, r, c // (r * r))
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h * r, w * r, c // (r * r))

    return apply_op(f, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor

    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, c, h // r, r, w // r, r)
            v = v.transpose(0, 1, 3, 5, 2, 4)
            return v.reshape(n, c * r * r, h // r, w // r)
        n, h, w, c = v.shape
        v = v.reshape(n, h // r, r, w // r, r, c)
        v = v.transpose(0, 1, 3, 2, 4, 5)
        return v.reshape(n, h // r, w // r, c * r * r)

    return apply_op(f, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(v):
        if data_format == "NCHW":
            n, c, h, w = v.shape
            v = v.reshape(n, groups, c // groups, h, w)
            v = v.transpose(0, 2, 1, 3, 4)
            return v.reshape(n, c, h, w)
        n, h, w, c = v.shape
        v = v.reshape(n, h, w, groups, c // groups)
        v = v.transpose(0, 1, 2, 4, 3)
        return v.reshape(n, h, w, c)

    return apply_op(f, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (ref unfold op)."""
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2
    if len(pd) == 2:
        pd = [pd[0], pd[1], pd[0], pd[1]]

    def f(v):
        n, c, h, w = v.shape
        v = jnp.pad(v, [(0, 0), (0, 0), (pd[0], pd[2]), (pd[1], pd[3])])
        out_h = (v.shape[2] - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        out_w = (v.shape[3] - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        patches = []
        for i in range(ks[0]):
            for j in range(ks[1]):
                sl = v[:, :, i * dl[0]: i * dl[0] + out_h * st[0]: st[0],
                       j * dl[1]: j * dl[1] + out_w * st[1]: st[1]]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # n, c, k*k, oh, ow
        return out.reshape(n, c * ks[0] * ks[1], out_h * out_w)

    return apply_op(f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    os_ = output_sizes if isinstance(output_sizes, (list, tuple)) else [output_sizes] * 2
    ks = kernel_sizes if isinstance(kernel_sizes, (list, tuple)) else [kernel_sizes] * 2
    st = strides if isinstance(strides, (list, tuple)) else [strides] * 2
    pd = paddings if isinstance(paddings, (list, tuple)) else [paddings] * 2
    dl = dilations if isinstance(dilations, (list, tuple)) else [dilations] * 2

    def f(v):
        n, ckk, L = v.shape
        c = ckk // (ks[0] * ks[1])
        out_h = os_[0] + 2 * pd[0]
        out_w = os_[1] + 2 * pd[1]
        n_h = (out_h - (dl[0] * (ks[0] - 1) + 1)) // st[0] + 1
        n_w = (out_w - (dl[1] * (ks[1] - 1) + 1)) // st[1] + 1
        v = v.reshape(n, c, ks[0], ks[1], n_h, n_w)
        out = jnp.zeros((n, c, out_h, out_w), v.dtype)
        for i in range(ks[0]):
            for j in range(ks[1]):
                out = out.at[:, :, i * dl[0]: i * dl[0] + n_h * st[0]: st[0],
                             j * dl[1]: j * dl[1] + n_w * st[1]: st[1]].add(v[:, :, i, j])
        return out[:, :, pd[0]: out_h - pd[0], pd[1]: out_w - pd[1]]

    return apply_op(f, x)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)

    return apply_op(f, x1, x2)


def bilinear(x1, x2, weight, bias=None, name=None):
    def f(a, b, w, *bb):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if bb:
            out = out + bb[0]
        return out

    if bias is None:
        return apply_op(lambda a, b, w: f(a, b, w), x1, x2, weight)
    return apply_op(f, x1, x2, weight, bias)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    def f(idx, w):
        out = jnp.take(w, idx.astype(jnp.int32), axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx != padding_idx)[..., None]
            out = out * mask.astype(out.dtype)
        return out

    return apply_op(f, x, weight, op_name="embedding")


def one_hot(x, num_classes, name=None):
    return apply_op(
        lambda v: jax.nn.one_hot(v.astype(jnp.int32), num_classes, dtype=jnp.float32), x)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(lbl, *pd):
        k = lbl.shape[-1]
        if pd:
            return (1 - epsilon) * lbl + epsilon * pd[0]
        return (1 - epsilon) * lbl + epsilon / k

    if prior_dist is None:
        return apply_op(f, label)
    return apply_op(f, label, prior_dist)


def class_center_sample(label, num_classes, num_samples, group=None):
    raise NotImplementedError("class_center_sample: PS-style API, out of TPU MVP scope")


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """Ref fluid sequence_mask op: lengths → boolean/int mask."""
    from ...framework.dtype import convert_dtype

    d = convert_dtype(dtype)

    def f(lengths):
        # data-dependent output width: maxlen must be concrete (eager-only
        # path when maxlen is None)
        m = maxlen if maxlen is not None else int(jnp.max(lengths))  # graftlint: noqa[host-sync]
        rng = jnp.arange(m)
        return (rng[None, :] < lengths[..., None]).astype(d)

    return apply_op(f, x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    """Ref temporal_shift op (video models): shift channels across time."""

    def f(v):
        nt, c, h, w = v.shape
        n = nt // seg_num
        v5 = v.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([v5[:, 1:, :fold], jnp.zeros_like(v5[:, :1, :fold])], 1)
        right = jnp.concatenate([jnp.zeros_like(v5[:, :1, fold:2 * fold]),
                                 v5[:, :-1, fold:2 * fold]], 1)
        rest = v5[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)

    return apply_op(f, x)
