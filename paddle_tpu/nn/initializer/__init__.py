"""Weight initializers (ref: python/paddle/nn/initializer/).

Each initializer is a callable ``(shape, dtype) -> jax.Array`` drawing from
the global generator (paddle.seed-controlled).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.random import next_key


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle uses [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(tuple(shape), self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return (jax.random.normal(next_key(), tuple(shape), jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype=jnp.float32):
        lo = (self.a - self.mean) / self.std
        hi = (self.b - self.mean) / self.std
        x = jax.random.truncated_normal(next_key(), lo, hi, tuple(shape), jnp.float32)
        return (x * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(next_key(), tuple(shape), jnp.float32, self.low,
                                  self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(next_key(), tuple(shape), jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(next_key(), tuple(shape), jnp.float32, -limit,
                                  limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if \
            self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fi)
        return (jax.random.normal(next_key(), tuple(shape), jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2)) if \
            self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(next_key(), tuple(shape), jnp.float32, -limit,
                                  limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        from ...framework.core import Tensor

        v = self.value
        if isinstance(v, Tensor):
            v = v.value
        arr = jnp.asarray(v, dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign initializer shape {arr.shape} != {tuple(shape)}"
        return arr


class Bilinear(Initializer):
    """Bilinear upsampling kernel init (for transposed conv)."""

    def __call__(self, shape, dtype=jnp.float32):
        weight = np.zeros(tuple(shape), dtype=np.float32)
        f = math.ceil(shape[-1] / 2)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(np.prod(shape[-2:])):
            x = i % shape[-1]
            y = (i // shape[-1]) % shape[-2]
            val = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
            weight[..., y, x] = val
        return jnp.asarray(weight, dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        return jax.nn.initializers.orthogonal(self.gain)(next_key(), tuple(shape),
                                                         jnp.float32).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        return jax.nn.initializers.delta_orthogonal()(next_key(), tuple(shape),
                                                      jnp.float32).astype(dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0


# functional aliases matching paddle.nn.initializer names
constant = Constant
normal = Normal
uniform = Uniform


# --- global default initializers (ref fluid/initializer.py:1168) ---
_global_weight_init = None
_global_bias_init = None


def set_global_initializer(weight_init, bias_init=None):
    """Set process-wide default initializers consulted by
    ``Layer.create_parameter`` when neither a ParamAttr initializer nor a
    default_initializer is given (ref fluid/initializer.py:1168
    set_global_initializer).  Pass ``None`` to reset."""
    global _global_weight_init, _global_bias_init
    _global_weight_init = weight_init
    _global_bias_init = bias_init


def _global_initializer(is_bias: bool):
    return _global_bias_init if is_bias else _global_weight_init
