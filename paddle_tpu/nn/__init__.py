"""paddle.nn parity surface (ref: python/paddle/nn/__init__.py)."""
from . import functional  # noqa: F401
from . import utils  # noqa: F401
from .utils import spectral_norm  # noqa: F401  (nn-level alias, ref nn/__init__)
from . import initializer  # noqa: F401
from . import quant  # noqa: F401
from .clip import (ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue, clip_grad_norm_,
                   clip_grad_value_)
from .layer_base import Layer
from .layer.activation import (CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid, Hardswish,
                               Hardtanh, LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish,
                               PReLU, ReLU, ReLU6, RReLU, SELU, Sigmoid, Silu, Softmax,
                               Softplus, Softshrink, Softsign, Swish, Tanh, Tanhshrink,
                               ThresholdedReLU)
from .layer.common import (AlphaDropout, Bilinear, ChannelShuffle, CosineSimilarity, Dropout,
                           Dropout2D, Dropout3D, Embedding, Flatten, Fold, Identity, Linear,
                           Pad1D, Pad2D, Pad3D, PixelShuffle, PixelUnshuffle, Unfold,
                           Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D)
from .layer.container import LayerDict, LayerList, ParameterList, Sequential
from .layer.conv import (Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D,
                         Conv3DTranspose)
from .layer.loss import (BCELoss, BCEWithLogitsLoss, CTCLoss, CosineEmbeddingLoss,
                         CrossEntropyLoss, GaussianNLLLoss, HingeEmbeddingLoss, HuberLoss,
                         KLDivLoss, L1Loss, MarginRankingLoss, MSELoss,
                         MultiLabelSoftMarginLoss, MultiMarginLoss, NLLLoss, PoissonNLLLoss,
                         SmoothL1Loss, SoftMarginLoss, TripletMarginLoss)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm,
                         InstanceNorm1D, InstanceNorm2D, InstanceNorm3D, LayerNorm,
                         LocalResponseNorm, RMSNorm, SpectralNorm, SyncBatchNorm)
from .layer.pooling import (AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
                            AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D,
                            AvgPool1D, AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D,
                            MaxUnPool2D)
from .layer.rnn import (BiRNN, GRU, GRUCell, LSTM, LSTMCell, RNN, RNNCellBase, SimpleRNN,
                        SimpleRNNCell)
from .layer.transformer import (MultiHeadAttention, Transformer, TransformerDecoder,
                                TransformerDecoderLayer, TransformerEncoder,
                                TransformerEncoderLayer)
from .layer.extras import (BeamSearchDecoder, HSigmoidLoss, MaxUnPool1D, MaxUnPool3D,
                           PairwiseDistance, RNNTLoss, Softmax2D,
                           TripletMarginWithDistanceLoss, dynamic_decode)
from .lora import (LoRALinear, attach_lora, export_adapter, load_adapter,
                   lora_parameters, merge_lora)
from ..framework.param_attr import ParamAttr  # noqa: F401  (paddle.ParamAttr alias)

__all__ = [n for n in dir() if not n.startswith("_")]
