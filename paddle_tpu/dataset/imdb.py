"""IMDB sentiment dataset (ref: python/paddle/dataset/imdb.py).

Real aclImdb tarball parsing when cached; deterministic synthetic corpus
otherwise. Samples: (word-id list, label 0/1).
"""
from __future__ import annotations

import re
import string
import tarfile

import numpy as np

from . import common

__all__ = []

_SYNTH_VOCAB = ["the", "movie", "film", "great", "bad", "plot", "acting",
                "good", "terrible", "wonderful", "boring", "classic"]


def _synth_docs(pattern_is_pos, n=200, seed=0):
    rng = np.random.RandomState(seed + int(pattern_is_pos))
    pos_words = ["great", "good", "wonderful", "classic"]
    neg_words = ["bad", "terrible", "boring"]
    bias = pos_words if pattern_is_pos else neg_words
    for _ in range(n):
        length = rng.randint(5, 30)
        words = [
            _SYNTH_VOCAB[rng.randint(len(_SYNTH_VOCAB))] if rng.rand() < 0.7
            else bias[rng.randint(len(bias))] for _ in range(length)
        ]
        yield words


def tokenize(pattern):
    """Yield token lists for docs matching ``pattern`` inside the tarball."""
    tarball = common.cached_path('imdb', 'aclImdb_v1.tar.gz')
    if tarball is None:
        is_pos = 'pos' in getattr(pattern, 'pattern', str(pattern))
        yield from _synth_docs(is_pos)
        return
    with tarfile.open(tarball) as tarf:
        tf = tarf.next()
        while tf is not None:
            if bool(pattern.match(tf.name)):
                data = tarf.extractfile(tf).read().decode('latin-1')
                yield (data.lower()
                       .translate(str.maketrans("", "", string.punctuation))
                       .split())
            tf = tarf.next()


def build_dict(pattern, cutoff):
    """Word frequency dict over docs matching pattern, freq > cutoff."""
    word_freq = {}
    for doc in tokenize(pattern):
        for word in doc:
            word_freq[word] = word_freq.get(word, 0) + 1
    word_freq = [x for x in word_freq.items() if x[1] > cutoff]
    dictionary = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*dictionary)) if dictionary else ((), ())
    word_idx = dict(list(zip(words, range(len(words)))))
    word_idx['<unk>'] = len(words)
    return word_idx


def reader_creator(pos_pattern, neg_pattern, word_idx):
    unk = word_idx['<unk>']

    def reader():
        for doc in tokenize(pos_pattern):
            yield [word_idx.get(w, unk) for w in doc], 0
        for doc in tokenize(neg_pattern):
            yield [word_idx.get(w, unk) for w in doc], 1

    return reader


def train(word_idx):
    return reader_creator(
        re.compile(r"aclImdb/train/pos/.*\.txt$"),
        re.compile(r"aclImdb/train/neg/.*\.txt$"), word_idx)


def test(word_idx):
    return reader_creator(
        re.compile(r"aclImdb/test/pos/.*\.txt$"),
        re.compile(r"aclImdb/test/neg/.*\.txt$"), word_idx)


def word_dict():
    return build_dict(re.compile(r"aclImdb/((train)|(test))/((pos)|(neg))/.*\.txt$"), 150 if common.cached_path('imdb', 'aclImdb_v1.tar.gz') else 0)


def fetch():
    pass
