"""VOC2012 segmentation reader API (ref: python/paddle/dataset/voc2012.py).

Delegates to paddle_tpu.vision.datasets.VOC2012 (real files when cached,
synthetic fallback otherwise). Samples: (image CHW uint8, label map HW).
"""
from __future__ import annotations

import numpy as np

from ..vision.datasets import VOC2012

__all__ = []


def reader_creator(mode):
    ds = VOC2012(mode=mode, download=False)

    def reader():
        for i in range(len(ds)):
            img, label = ds[i]
            yield np.asarray(img), np.asarray(label)

    return reader


def train():
    return reader_creator('train')


def test():
    return reader_creator('test')


def val():
    return reader_creator('valid')


def fetch():
    pass
