"""Image preprocessing helpers (ref: python/paddle/dataset/image.py).

numpy-only implementations (the reference shells out to cv2); these are host
-side and feed the device pipeline with contiguous CHW float arrays.
"""
from __future__ import annotations

import numpy as np

__all__ = []


def _resize_nn(im, h, w):
    """Nearest-neighbour resize, HWC or HW."""
    src_h, src_w = im.shape[:2]
    rows = (np.arange(h) * src_h / h).astype(np.int64).clip(0, src_h - 1)
    cols = (np.arange(w) * src_w / w).astype(np.int64).clip(0, src_w - 1)
    return im[rows][:, cols]


def resize_short(im, size):
    """Resize so the short edge == size, keeping aspect (ref image.py)."""
    h, w = im.shape[:2]
    if h < w:
        new_h, new_w = size, int(round(w * size / h))
    else:
        new_h, new_w = int(round(h * size / w)), size
    return _resize_nn(im, new_h, new_w)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start:h_start + size, w_start:w_start + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h_start = np.random.randint(0, h - size + 1)
    w_start = np.random.randint(0, w - size + 1)
    return im[h_start:h_start + size, w_start:w_start + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def to_chw(im, order=(2, 0, 1)):
    assert len(im.shape) == len(order)
    return im.transpose(order)


def simple_transform(im, resize_size, crop_size, is_train, is_color=True,
                     mean=None):
    """resize_short → crop (+flip when training) → CHW → mean-subtract."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size)
        if np.random.randint(2) == 0:
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size)
    if len(im.shape) == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.array(mean, dtype=np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, np.newaxis, np.newaxis]
        im -= mean
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    try:
        from PIL import Image

        im = np.asarray(Image.open(filename).convert(
            "RGB" if is_color else "L"))
    except ImportError:
        raise ImportError("PIL is required to load image files")
    return simple_transform(im, resize_size, crop_size, is_train, is_color,
                            mean)
