"""CoNLL-05 SRL dataset (ref: python/paddle/dataset/conll05.py).

Synthetic fallback producing the 9-field SRL sample schema:
(word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark, label_ids).
"""
from __future__ import annotations

import numpy as np

__all__ = []

UNK_IDX = 0

_WORDS = ["the", "company", "said", "it", "will", "buy", "shares", "today",
          "market", "price"]
_LABELS = ["B-A0", "I-A0", "B-A1", "I-A1", "B-V", "O"]


def load_label_dict(filename=None):
    d = {}
    for lab in _LABELS:
        if lab.startswith("B-") or lab.startswith("I-"):
            d[lab] = len(d)
    d["O"] = len(d)
    return d


def load_dict(filename=None):
    return {w: i for i, w in enumerate(_WORDS)}


def get_dict():
    """(word_dict, verb_dict, label_dict) — ref conll05.py:208."""
    word_dict = load_dict()
    verb_dict = {"said": 0, "buy": 1, "will": 2}
    label_dict = load_label_dict()
    return word_dict, verb_dict, label_dict


def get_embedding():
    """Deterministic synthetic embedding table (ref downloads emb file)."""
    rng = np.random.RandomState(0)
    return rng.normal(size=(len(_WORDS), 32)).astype(np.float32)


def corpus_reader(n=200, seed=0):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = rng.randint(4, 15)
            sentence = [_WORDS[rng.randint(len(_WORDS))]
                        for _ in range(length)]
            labels = [_LABELS[rng.randint(len(_LABELS))]
                      for _ in range(length)]
            yield sentence, labels

    return reader


def reader_creator(corpus_rdr, word_dict, verb_dict, label_dict):
    def pad_ctx(ids, i, off):
        j = i + off
        return ids[j] if 0 <= j < len(ids) else UNK_IDX

    def reader():
        for sentence, labels in corpus_rdr():
            word_ids = [word_dict.get(w, UNK_IDX) for w in sentence]
            lab_ids = [label_dict.get(l, label_dict["O"]) for l in labels]
            verb_positions = [i for i, l in enumerate(labels) if l == "B-V"]
            vi = verb_positions[0] if verb_positions else 0
            pred_id = verb_dict.get(sentence[vi], 0)
            n = len(word_ids)
            ctx_n2 = [pad_ctx(word_ids, vi, -2)] * n
            ctx_n1 = [pad_ctx(word_ids, vi, -1)] * n
            ctx_0 = [word_ids[vi]] * n
            ctx_p1 = [pad_ctx(word_ids, vi, 1)] * n
            ctx_p2 = [pad_ctx(word_ids, vi, 2)] * n
            mark = [1 if i == vi else 0 for i in range(n)]
            yield (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2,
                   [pred_id] * n, mark, lab_ids)

    return reader


def test():
    word_dict, verb_dict, label_dict = get_dict()
    return reader_creator(corpus_reader(seed=1), word_dict, verb_dict,
                          label_dict)


def fetch():
    pass
