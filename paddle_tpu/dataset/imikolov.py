"""imikolov (PTB) language-model dataset (ref: python/paddle/dataset/imikolov.py)."""
from __future__ import annotations

import collections
import tarfile

import numpy as np

from . import common

__all__ = []


class DataType:
    NGRAM = 1
    SEQ = 2


def _synth_lines(n=500, seed=0):
    rng = np.random.RandomState(seed)
    vocab = ["the", "a", "market", "stock", "traders", "said", "on",
             "monday", "rose", "fell", "points", "percent"]
    for _ in range(n):
        yield " ".join(vocab[rng.randint(len(vocab))]
                       for _ in range(rng.randint(4, 20)))


def _lines(which):
    tarball = common.cached_path('imikolov', 'simple-examples.tgz')
    if tarball is None:
        yield from _synth_lines(seed=0 if 'train' in which else 1)
        return
    with tarfile.open(tarball) as tf:
        f = tf.extractfile(f"./simple-examples/data/ptb.{which}.txt")
        for line in f:
            yield line.decode().strip()


def word_count(f, word_freq=None):
    if word_freq is None:
        word_freq = collections.defaultdict(int)
    for line in f:
        for w in line.strip().split():
            word_freq[w] += 1
        word_freq['<s>'] += 1
        word_freq['<e>'] += 1
    return word_freq


def build_dict(min_word_freq=50):
    """Word→id dict over the train corpus, '<unk>' last (ref :53)."""
    if common.cached_path('imikolov', 'simple-examples.tgz') is None:
        min_word_freq = 0
    word_freq = word_count(_lines('train'))
    word_freq = [x for x in word_freq.items()
                 if x[1] > min_word_freq and x[0] != '<unk>']
    word_freq_sorted = sorted(word_freq, key=lambda x: (-x[1], x[0]))
    words, _ = list(zip(*word_freq_sorted)) if word_freq_sorted else ((), ())
    word_idx = dict(list(zip(words, range(len(words)))))
    word_idx['<unk>'] = len(words)
    return word_idx


def reader_creator(which, word_idx, n, data_type):
    def reader():
        UNK = word_idx['<unk>']
        for line in _lines(which):
            if DataType.NGRAM == data_type:
                assert n > -1, 'Invalid gram length'
                line_ids = ['<s>'] + line.strip().split() + ['<e>']
                line_ids = [word_idx.get(w, UNK) for w in line_ids]
                if len(line_ids) >= n:
                    line_ids = np.asarray(line_ids, dtype='int64')
                    for i in range(n, len(line_ids) + 1):
                        yield tuple(line_ids[i - n:i])
            elif DataType.SEQ == data_type:
                line_ids = line.strip().split()
                line_ids = [word_idx.get(w, UNK) for w in line_ids]
                src_seq = [word_idx['<s>']] + line_ids
                trg_seq = line_ids + [word_idx['<e>']]
                if n > 0 and len(line_ids) > n:
                    continue
                yield src_seq, trg_seq
            else:
                assert False, 'Unknown data type'

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator('train', word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return reader_creator('valid', word_idx, n, data_type)


def fetch():
    pass
