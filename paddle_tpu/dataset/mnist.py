"""MNIST reader-creator API (ref: python/paddle/dataset/mnist.py).

Parses real idx-format gz files when cached; synthetic fallback otherwise.
Samples: (image float32[784] scaled to [-1, 1], label int).
"""
from __future__ import annotations

import gzip
import struct

import numpy as np

from . import common

__all__ = []


def _idx_reader(image_path, label_path, buffer_size):
    with gzip.open(image_path, 'rb') as fi, gzip.open(label_path, 'rb') as fl:
        magic, n, rows, cols = struct.unpack('>IIII', fi.read(16))
        _, n_lab = struct.unpack('>II', fl.read(8))
        for start in range(0, n, buffer_size):
            cnt = min(buffer_size, n - start)
            images = np.frombuffer(
                fi.read(cnt * rows * cols), dtype=np.uint8
            ).reshape(cnt, rows * cols).astype(np.float32)
            images = images / 255.0 * 2.0 - 1.0
            labels = np.frombuffer(fl.read(cnt), dtype=np.uint8).astype('int64')
            for i in range(cnt):
                yield images[i, :], int(labels[i])


def _synth_reader(n, seed):
    rng = np.random.RandomState(seed)
    for i in range(n):
        yield (rng.uniform(-1, 1, size=(784,)).astype(np.float32),
               int(rng.randint(0, 10)))


def reader_creator(image_filename, label_filename, buffer_size):
    def reader():
        if image_filename and label_filename:
            yield from _idx_reader(image_filename, label_filename, buffer_size)
        else:
            yield from _synth_reader(buffer_size * 10, 0)

    return reader


def train():
    return reader_creator(
        common.cached_path('mnist', 'train-images-idx3-ubyte.gz'),
        common.cached_path('mnist', 'train-labels-idx1-ubyte.gz'), 100)


def test():
    return reader_creator(
        common.cached_path('mnist', 't10k-images-idx3-ubyte.gz'),
        common.cached_path('mnist', 't10k-labels-idx1-ubyte.gz'), 100)


def fetch():
    pass
