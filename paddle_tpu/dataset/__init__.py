"""paddle.dataset parity (ref: python/paddle/dataset/__init__.py).

Legacy reader-creator dataset modules. Zero-egress: each module parses the
real on-disk format when the file is cached under
``~/.cache/paddle_tpu/dataset/<name>/`` and otherwise serves deterministic
synthetic data with the same schema (see module docstrings).
"""
from . import (  # noqa: F401
    cifar, common, conll05, flowers, image, imdb, imikolov, mnist, movielens,
    uci_housing, voc2012, wmt14, wmt16,
)

__all__ = [
    'mnist', 'imikolov', 'imdb', 'cifar', 'movielens', 'conll05',
    'uci_housing', 'wmt14', 'wmt16', 'flowers', 'voc2012', 'image', 'common',
]
