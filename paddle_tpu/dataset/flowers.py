"""Flowers-102 reader-creator API (ref: python/paddle/dataset/flowers.py).

Delegates to paddle_tpu.vision.datasets.Flowers (which parses the real files
when cached, synthetic otherwise) and re-exposes the legacy reader interface.
"""
from __future__ import annotations

import functools

from ..reader import map_readers, xmap_readers
from ..vision.datasets import Flowers

__all__ = []


def default_mapper(is_train, sample):
    img, label = sample
    return img, label


train_mapper = functools.partial(default_mapper, True)
test_mapper = functools.partial(default_mapper, False)


def reader_creator(mode, mapper, buffered_size=1024, use_xmap=True,
                   cycle=False):
    ds = Flowers(mode=mode, download=False)

    def reader():
        while True:
            for i in range(len(ds)):
                img, label = ds[i]
                yield img, int(label)
            if not cycle:
                break

    if use_xmap:
        return xmap_readers(mapper, reader, 4, buffered_size)
    return map_readers(mapper, reader)


def train(mapper=train_mapper, buffered_size=1024, use_xmap=True, cycle=False):
    return reader_creator('train', mapper, buffered_size, use_xmap, cycle)


def test(mapper=test_mapper, buffered_size=1024, use_xmap=True, cycle=False):
    return reader_creator('test', mapper, buffered_size, use_xmap, cycle)


def valid(mapper=test_mapper, buffered_size=1024, use_xmap=True):
    return reader_creator('valid', mapper, buffered_size, use_xmap)


def fetch():
    pass
