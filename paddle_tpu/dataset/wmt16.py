"""WMT16 multimodal en/de translation (ref: python/paddle/dataset/wmt16.py).

Synthetic fallback; same token conventions as the reference: <s>=0, <e>=1,
<unk>=2, configurable src/trg dict sizes and language direction.
"""
from __future__ import annotations

import numpy as np

__all__ = []

TOTAL_EN_WORDS = 11250
TOTAL_DE_WORDS = 19220
START_MARK = "<s>"
END_MARK = "<e>"
UNK_MARK = "<unk>"

_EN = ["the", "cat", "dog", "house", "runs", "sees", "a", "red", "man", "tree"]
_DE = ["die", "katze", "hund", "haus", "läuft", "sieht", "ein", "rot",
       "mann", "baum"]


def _synth_pairs(n=300, seed=0):
    rng = np.random.RandomState(seed)
    for _ in range(n):
        length = rng.randint(3, 12)
        idxs = [int(rng.randint(len(_EN))) for _ in range(length)]
        yield ([_EN[i] for i in idxs], [_DE[i] for i in idxs])


def __get_dict_size(src_dict_size, trg_dict_size, src_lang):
    src_dict_size = min(src_dict_size,
                        TOTAL_EN_WORDS if src_lang == "en" else TOTAL_DE_WORDS)
    trg_dict_size = min(trg_dict_size,
                        TOTAL_DE_WORDS if src_lang == "en" else TOTAL_EN_WORDS)
    return src_dict_size, trg_dict_size


def __load_dict(dict_size, lang, reverse=False):
    base = _EN if lang == "en" else _DE
    d = {START_MARK: 0, END_MARK: 1, UNK_MARK: 2}
    for w in base[:max(0, dict_size - 3)]:
        d[w] = len(d)
    if reverse:
        return {v: k for k, v in d.items()}
    return d


def reader_creator(which, src_dict_size, trg_dict_size, src_lang):
    def reader():
        src_dict = __load_dict(src_dict_size, src_lang)
        trg_dict = __load_dict(trg_dict_size,
                               "de" if src_lang == "en" else "en")
        unk = src_dict[UNK_MARK]
        seed = {"train": 0, "test": 1, "val": 2}.get(which, 0)
        for en_words, de_words in _synth_pairs(seed=seed):
            s, t = (en_words, de_words) if src_lang == "en" else (de_words,
                                                                  en_words)
            src_ids = [src_dict.get(w, unk) for w in s]
            trg_ids = [trg_dict.get(w, trg_dict[UNK_MARK]) for w in t]
            trg_ids_next = trg_ids + [trg_dict[END_MARK]]
            trg_ids = [trg_dict[START_MARK]] + trg_ids
            yield src_ids, trg_ids, trg_ids_next

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    if src_lang not in ["en", "de"]:
        raise ValueError("An error language type. Only support: "
                         "en (for English); de(for Germany).")
    src_dict_size, trg_dict_size = __get_dict_size(
        src_dict_size, trg_dict_size, src_lang)
    return reader_creator('train', src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    if src_lang not in ["en", "de"]:
        raise ValueError("An error language type. Only support: "
                         "en (for English); de(for Germany).")
    src_dict_size, trg_dict_size = __get_dict_size(
        src_dict_size, trg_dict_size, src_lang)
    return reader_creator('test', src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    if src_lang not in ["en", "de"]:
        raise ValueError("An error language type. Only support: "
                         "en (for English); de(for Germany).")
    src_dict_size, trg_dict_size = __get_dict_size(
        src_dict_size, trg_dict_size, src_lang)
    return reader_creator('val', src_dict_size, trg_dict_size, src_lang)


def get_dict(lang, dict_size, reverse=False):
    dict_size = min(dict_size,
                    TOTAL_EN_WORDS if lang == "en" else TOTAL_DE_WORDS)
    return __load_dict(dict_size, lang, reverse)


def fetch():
    pass
