"""MovieLens-1M dataset (ref: python/paddle/dataset/movielens.py).

Real ml-1m zip parsing when cached; deterministic synthetic catalog otherwise.
Sample: movie.value() + user.value() + [rating].
"""
from __future__ import annotations

import re
import zipfile

import numpy as np

from . import common

__all__ = []

age_table = [1, 18, 25, 35, 45, 50, 56]


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self):
        return [self.index,
                [CATEGORIES_DICT[c] for c in self.categories],
                [MOVIE_TITLE_DICT[w.lower()] for w in self.title.split()]]

    def __repr__(self):
        return (f"<MovieInfo id({self.index}), title({self.title}), "
                f"categories({self.categories})>")


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == 'M'
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]

    def __repr__(self):
        return (f"<UserInfo id({self.index}), "
                f"gender({'M' if self.is_male else 'F'}), "
                f"age({age_table[self.age]}), job({self.job_id})>")


MOVIE_INFO = None
MOVIE_TITLE_DICT = None
CATEGORIES_DICT = None
USER_INFO = None
RATINGS = None


def _synth_meta():
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO, RATINGS
    rng = np.random.RandomState(0)
    cats = ["Action", "Comedy", "Drama", "Thriller", "Sci-Fi"]
    CATEGORIES_DICT = {c: i for i, c in enumerate(cats)}
    words = ["the", "of", "return", "night", "story", "king", "day", "lost"]
    MOVIE_TITLE_DICT = {w: i for i, w in enumerate(words)}
    MOVIE_INFO, USER_INFO, RATINGS = {}, {}, []
    for i in range(1, 201):
        title = " ".join(words[rng.randint(len(words))] for _ in range(3))
        mcats = [cats[rng.randint(len(cats))]]
        MOVIE_INFO[i] = MovieInfo(i, mcats, title)
    for i in range(1, 101):
        USER_INFO[i] = UserInfo(
            i, 'M' if rng.rand() < 0.5 else 'F',
            age_table[rng.randint(len(age_table))], rng.randint(0, 21))
    for _ in range(2000):
        # same [1,5] -> [-3,5] rescale as the real-data path (_parse_zip)
        RATINGS.append((rng.randint(1, 101), rng.randint(1, 201),
                        float(rng.randint(1, 6)) * 2 - 5.0))


def _parse_zip(fn):
    global MOVIE_INFO, MOVIE_TITLE_DICT, CATEGORIES_DICT, USER_INFO, RATINGS
    pattern = re.compile(r'^(.*)\((\d+)\)$')
    MOVIE_INFO, categories_set, title_word_set = {}, set(), set()
    with zipfile.ZipFile(fn) as package:
        for info in package.infolist():
            assert isinstance(info, zipfile.ZipInfo)
        with package.open('ml-1m/movies.dat') as movie_file:
            for line in movie_file:
                line = line.decode(encoding='latin1')
                movie_id, title, categories = line.strip().split('::')
                categories = categories.split('|')
                for c in categories:
                    categories_set.add(c)
                title = pattern.match(title).group(1)
                MOVIE_INFO[int(movie_id)] = MovieInfo(
                    index=movie_id, categories=categories, title=title)
                for w in title.split():
                    title_word_set.add(w.lower())
        MOVIE_TITLE_DICT = {w: i for i, w in enumerate(title_word_set)}
        CATEGORIES_DICT = {c: i for i, c in enumerate(categories_set)}
        USER_INFO = {}
        with package.open('ml-1m/users.dat') as user_file:
            for line in user_file:
                line = line.decode(encoding='latin1')
                uid, gender, age, job, _ = line.strip().split("::")
                USER_INFO[int(uid)] = UserInfo(
                    index=uid, gender=gender, age=age, job_id=job)
        RATINGS = []
        with package.open('ml-1m/ratings.dat') as rating:
            for line in rating:
                line = line.decode(encoding='latin1')
                uid, mov_id, rat, _ = line.strip().split("::")
                # ref python/paddle/dataset/movielens.py:167 — ratings are
                # rescaled from [1,5] to [-3,5]
                RATINGS.append((int(uid), int(mov_id), float(rat) * 2 - 5.0))


def __initialize_meta_info__():
    if MOVIE_INFO is None:
        fn = common.cached_path('movielens', 'ml-1m.zip')
        if fn is None:
            _synth_meta()
        else:
            _parse_zip(fn)


def __reader__(rand_seed=0, test_ratio=0.1, is_test=False):
    __initialize_meta_info__()
    rng = np.random.RandomState(rand_seed)
    for uid, mov_id, rating in RATINGS:
        if (rng.rand() < test_ratio) == is_test:
            mov = MOVIE_INFO[mov_id]
            usr = USER_INFO[uid]
            yield usr.value() + mov.value() + [[rating]]


def __reader_creator__(**kwargs):
    return lambda: __reader__(**kwargs)


train = __reader_creator__(is_test=False)
test = __reader_creator__(is_test=True)


def get_movie_title_dict():
    __initialize_meta_info__()
    return MOVIE_TITLE_DICT


def max_movie_id():
    __initialize_meta_info__()
    return max(MOVIE_INFO.values(), key=lambda m: m.index).index


def max_user_id():
    __initialize_meta_info__()
    return max(USER_INFO.values(), key=lambda u: u.index).index


def max_job_id():
    __initialize_meta_info__()
    return max(USER_INFO.values(), key=lambda u: u.job_id).job_id


def movie_categories():
    __initialize_meta_info__()
    return CATEGORIES_DICT


def user_info():
    __initialize_meta_info__()
    return USER_INFO


def movie_info():
    __initialize_meta_info__()
    return MOVIE_INFO


def fetch():
    __initialize_meta_info__()
