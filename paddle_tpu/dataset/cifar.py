"""CIFAR-10/100 reader-creator API (ref: python/paddle/dataset/cifar.py).

Parses the real python-pickle tarballs when cached; synthetic fallback
otherwise. Samples: (image float32[3072] in [0,1], label int).
"""
from __future__ import annotations

import itertools
import pickle
import tarfile

import numpy as np

from . import common

__all__ = []


def _tar_reader(filename, sub_name, cycle=False):
    def reader():
        while True:
            with tarfile.open(filename, mode='r') as f:
                names = [n for n in f.getnames() if sub_name in n]
                for name in names:
                    batch = pickle.load(f.extractfile(name), encoding='bytes')
                    data = batch[b'data']
                    labels = batch.get(b'labels', batch.get(b'fine_labels'))
                    for sample, label in zip(data, labels):
                        yield (np.asarray(sample, dtype=np.float32) / 255.0,
                               int(label))
            if not cycle:
                break

    return reader


def _synth(n_classes, cycle=False):
    def reader():
        rng = np.random.RandomState(n_classes)
        it = itertools.count() if cycle else range(500)
        for i in it:
            yield (rng.uniform(0, 1, size=(3072,)).astype(np.float32),
                   int(rng.randint(0, n_classes)))

    return reader


def reader_creator(filename, sub_name, cycle=False):
    if filename:
        return _tar_reader(filename, sub_name, cycle)
    return _synth(100 if '100' in sub_name else 10, cycle)


def train100():
    return reader_creator(
        common.cached_path('cifar', 'cifar-100-python.tar.gz'), 'train')


def test100():
    return reader_creator(
        common.cached_path('cifar', 'cifar-100-python.tar.gz'), 'test')


def train10(cycle=False):
    return reader_creator(
        common.cached_path('cifar', 'cifar-10-python.tar.gz'),
        'data_batch', cycle=cycle)


def test10(cycle=False):
    return reader_creator(
        common.cached_path('cifar', 'cifar-10-python.tar.gz'),
        'test_batch', cycle=cycle)


def fetch():
    pass
