"""Dataset cache/helpers (ref: python/paddle/dataset/common.py).

Zero-egress environment: ``download`` never fetches from the network; it
returns the cached path when the file is already on disk and raises a clear
error otherwise. Dataset modules fall back to deterministic synthetic data with
the real schema so recipes still run end-to-end (same convention as
paddle_tpu.vision.datasets).
"""
from __future__ import annotations

import hashlib
import os

__all__ = []

DATA_HOME = os.path.expanduser("~/.cache/paddle_tpu/dataset")


def must_mkdirs(path):
    # called from download(), NOT at import time: importing paddle_tpu must
    # not write to the filesystem (read-only $HOME safe)
    os.makedirs(path, exist_ok=True)


def md5file(fname):
    hash_md5 = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            hash_md5.update(chunk)
    return hash_md5.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Return the cached local path for a dataset file (no network egress).

    Ref common.py download(): fetches over HTTP with md5 retry. Here the cache
    dir is checked; a missing file raises with guidance to place it manually.
    """
    dirname = os.path.join(DATA_HOME, module_name)
    try:
        must_mkdirs(dirname)
    except OSError:
        pass  # read-only $HOME: the existence check below still works
    filename = os.path.join(
        dirname, url.split("/")[-1] if save_name is None else save_name)
    if os.path.exists(filename) and (
            not md5sum or md5file(filename) == md5sum):
        return filename
    raise RuntimeError(
        f"dataset file {filename} not present and network egress is disabled; "
        f"place the file from {url} at that path, or use the module's "
        f"synthetic fallback readers")


def cached_path(module_name, filename):
    """Path under DATA_HOME/<module>/<filename>, or None if absent."""
    p = os.path.join(DATA_HOME, module_name, filename)
    return p if os.path.exists(p) else None
