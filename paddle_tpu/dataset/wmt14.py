"""WMT14 en→fr translation dataset (ref: python/paddle/dataset/wmt14.py).

Synthetic parallel corpus fallback with the reference's token conventions:
<s>=0 (START), <e>=1 (END), <unk>=2 (UNK).
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = []

START = "<s>"
END = "<e>"
UNK = "<unk>"
UNK_IDX = 2


def _synth_pairs(n=300, seed=0):
    rng = np.random.RandomState(seed)
    en = ["the", "cat", "dog", "house", "runs", "sees", "a", "red"]
    fr = ["le", "chat", "chien", "maison", "court", "voit", "un", "rouge"]
    for _ in range(n):
        length = rng.randint(3, 12)
        idxs = [int(rng.randint(len(en))) for _ in range(length)]
        yield ([en[i] for i in idxs], [fr[i] for i in idxs])


def __read_to_dict(dict_size):
    words = sorted({w for s, t in _synth_pairs() for w in s})
    twords = sorted({w for s, t in _synth_pairs() for w in t})

    def to_dict(ws):
        d = {START: 0, END: 1, UNK: 2}
        for w in ws[:dict_size - 3]:
            d[w] = len(d)
        return d

    return to_dict(words), to_dict(twords)


def reader_creator(which, dict_size):
    def reader():
        src_dict, trg_dict = __read_to_dict(dict_size)
        seed = 0 if which == 'train' else 1
        for src_words, trg_words in _synth_pairs(seed=seed):
            src_ids = [src_dict.get(w, UNK_IDX) for w in src_words]
            trg_ids = [trg_dict.get(w, UNK_IDX) for w in trg_words]
            trg_ids_next = trg_ids + [trg_dict[END]]
            trg_ids = [trg_dict[START]] + trg_ids
            yield src_ids, trg_ids, trg_ids_next

    return reader


def train(dict_size):
    return reader_creator('train', dict_size)


def test(dict_size):
    return reader_creator('test', dict_size)


def gen(dict_size):
    return reader_creator('gen', dict_size)


def get_dict(dict_size, reverse=True):
    src_dict, trg_dict = __read_to_dict(dict_size)
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict


def fetch():
    pass
