"""UCI housing dataset (ref: python/paddle/dataset/uci_housing.py).

Parses the real whitespace-separated 14-column file when cached locally;
otherwise serves a deterministic synthetic sample with the same schema
(13 normalized features, 1 target).
"""
from __future__ import annotations

import numpy as np

from . import common

__all__ = []

feature_names = [
    'CRIM', 'ZN', 'INDUS', 'CHAS', 'NOX', 'RM', 'AGE', 'DIS', 'RAD', 'TAX',
    'PTRATIO', 'B', 'LSTAT', 'convert',
]

UCI_TRAIN_DATA = None
UCI_TEST_DATA = None


def load_data(filename, feature_num=14, ratio=0.8):
    global UCI_TRAIN_DATA, UCI_TEST_DATA
    if UCI_TRAIN_DATA is not None and UCI_TEST_DATA is not None:
        return
    if filename is not None:
        data = np.fromfile(filename, sep=' ')
    else:
        rng = np.random.RandomState(0)
        data = rng.uniform(0.0, 10.0, size=506 * feature_num)
    data = data.reshape(data.shape[0] // feature_num, feature_num)
    maximums, minimums, avgs = (data.max(axis=0), data.min(axis=0),
                                data.sum(axis=0) / data.shape[0])
    for i in range(feature_num - 1):
        span = maximums[i] - minimums[i]
        data[:, i] = (data[:, i] - avgs[i]) / (span if span else 1.0)
    offset = int(data.shape[0] * ratio)
    UCI_TRAIN_DATA = data[:offset]
    UCI_TEST_DATA = data[offset:]


def _ensure_loaded():
    load_data(common.cached_path('uci_housing', 'housing.data'))


def train():
    """Reader creator yielding (features[13], price[1]) samples."""
    _ensure_loaded()

    def reader():
        for d in UCI_TRAIN_DATA:
            yield d[:-1], d[-1:]

    return reader


def test():
    _ensure_loaded()

    def reader():
        for d in UCI_TEST_DATA:
            yield d[:-1], d[-1:]

    return reader


def predict_reader():
    _ensure_loaded()

    def reader():
        yield (UCI_TEST_DATA[0][:-1],)

    return reader


def fetch():
    _ensure_loaded()
