"""Sequence-length bucketing — the dynamic-shape policy (SURVEY §7 hard
part (e)).

XLA compiles one program per input shape: naively feeding variable-length
batches recompiles per distinct length (seconds each on TPU).  The policy
here caps the shape set to a fixed bucket ladder:

- :func:`bucket_boundaries` — geometric ladder of lengths (each ~``growth``
  over the previous, ending at ``max_len``): compile count is
  O(log max_len), padding waste per batch < (growth-1).
- :func:`pad_to_bucket` — right-pad a [B, S] batch (and labels, with
  ``ignore_index`` so padded positions drop out of the loss) up to the
  smallest bucket >= S.
- :class:`LengthBucketBatchSampler` — groups sample indices by bucketed
  length so each batch pads to ITS bucket, minimizing waste while keeping
  the shape set fixed.  Drop-in ``batch_sampler`` for ``DataLoader``.

The reference has no analogue (GPU kernels take dynamic shapes); this is
the TPU-native replacement for that flexibility.
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["bucket_boundaries", "pad_to_bucket",
           "LengthBucketBatchSampler"]


def bucket_boundaries(max_len: int, min_len: int = 32,
                      growth: float = 1.3) -> List[int]:
    """Geometric bucket ladder, multiples of 8 (TPU lane-friendly),
    capped at ``max_len``."""
    if growth <= 1.0:
        raise ValueError(f"growth must be > 1 (got {growth}); growth <= 1 "
                         f"would never reach max_len")
    if min_len <= 0 or max_len < min_len:
        raise ValueError(f"need 0 < min_len <= max_len, got "
                         f"({min_len}, {max_len})")
    out = []
    cur = float(min_len)
    while cur < max_len:
        b = min(int(math.ceil(cur / 8.0) * 8), max_len)
        if not out or b > out[-1]:
            out.append(b)
        cur *= growth
    if not out or out[-1] != max_len:
        out.append(max_len)
    return out


def _bucket_of(length: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if length <= b:
            return b
    raise ValueError(f"sequence length {length} exceeds the largest bucket "
                     f"{buckets[-1]}")


def pad_to_bucket(ids, buckets: Sequence[int], pad_value: int = 0,
                  labels=None, ignore_index: int = -100):
    """Right-pad ``ids`` [B, S] up to the smallest bucket >= S.  When
    ``labels`` is given it is padded with ``ignore_index`` so the padded
    positions contribute nothing to the loss.  Returns (padded_ids,
    padded_labels_or_None, true_length)."""
    from ..framework.core import Tensor, to_array

    arr = np.asarray(to_array(ids) if isinstance(ids, Tensor) else ids)
    S = arr.shape[-1]
    tgt = _bucket_of(S, buckets)
    if tgt != S:
        pad = [(0, 0)] * (arr.ndim - 1) + [(0, tgt - S)]
        arr = np.pad(arr, pad, constant_values=pad_value)
    out_ids = Tensor(np.ascontiguousarray(arr)) if isinstance(ids, Tensor) \
        else arr
    out_labels = None
    if labels is not None:
        lab = np.asarray(to_array(labels) if isinstance(labels, Tensor)
                         else labels)
        if lab.shape[-1] != S:
            raise ValueError(
                f"labels last dim {lab.shape[-1]} != ids last dim {S}; "
                f"shift labels before padding so ignore_index lands on the "
                f"padded positions")
        if tgt != S:
            pad = [(0, 0)] * (lab.ndim - 1) + [(0, tgt - S)]
            lab = np.pad(lab, pad, constant_values=ignore_index)
        out_labels = Tensor(np.ascontiguousarray(lab)) \
            if isinstance(labels, Tensor) else lab
    return out_ids, out_labels, S


class LengthBucketBatchSampler:
    """Batch sampler grouping indices by length bucket (ref: the role
    Paddle's DistributedBatchSampler plays for the loader, with the
    TPU-specific shape policy added).

    ``lengths``: per-sample sequence lengths (list/array or a callable
    index -> length).  Batches are homogeneous in bucket, shuffled across
    and within buckets per epoch when ``shuffle``."""

    def __init__(self, lengths, batch_size: int,
                 buckets: Optional[Sequence[int]] = None,
                 shuffle: bool = True, drop_last: bool = False,
                 seed: int = 0, num_replicas: Optional[int] = None,
                 rank: Optional[int] = None):
        if callable(lengths):
            raise TypeError("pass materialized lengths (list/ndarray)")
        self._lengths = np.asarray(lengths, np.int64)
        self.batch_size = int(batch_size)
        self.buckets = list(buckets) if buckets is not None else \
            bucket_boundaries(int(self._lengths.max()))
        self.shuffle = shuffle
        self.drop_last = drop_last
        self._seed = seed
        self._epoch = 0
        # data-parallel sharding, DistributedBatchSampler-style: each rank
        # takes every nranks-th batch of the (deterministically shuffled)
        # global batch list
        if num_replicas is None and rank is None:
            self.nranks, self.local_rank = 1, 0
        else:
            from ..distributed import get_rank, get_world_size

            self.nranks = (num_replicas if num_replicas is not None
                           else get_world_size())
            self.local_rank = rank if rank is not None else get_rank()
        # bucket->indices assignment is immutable: compute once
        self._by_bucket = {}
        for idx, ln in enumerate(self._lengths):
            self._by_bucket.setdefault(
                _bucket_of(int(ln), self.buckets), []).append(idx)

    def set_epoch(self, epoch: int):
        self._epoch = int(epoch)

    def _global_batches(self):
        rng = np.random.default_rng([self._seed, self._epoch])
        batches = []
        for b, idxs in sorted(self._by_bucket.items()):
            idxs = np.asarray(idxs)
            if self.shuffle:
                rng.shuffle(idxs)
            for i in range(0, len(idxs), self.batch_size):
                chunk = idxs[i:i + self.batch_size]
                if self.drop_last and len(chunk) < self.batch_size:
                    continue
                batches.append(chunk.tolist())
        if self.shuffle:
            order = rng.permutation(len(batches))
            batches = [batches[i] for i in order]
        return batches

    def __iter__(self):
        batches = self._global_batches()
        if self.nranks > 1:
            # pad so every rank sees the same batch count (wrap-around),
            # then stride — identical global order on every rank by seed
            total = math.ceil(len(batches) / self.nranks) * self.nranks
            batches = batches + batches[: total - len(batches)]
            batches = batches[self.local_rank:: self.nranks]
        return iter(batches)

    def __len__(self):
        n = 0
        for idxs in self._by_bucket.values():
            n += (len(idxs) // self.batch_size if self.drop_last
                  else math.ceil(len(idxs) / self.batch_size))
        if self.nranks > 1:
            n = math.ceil(n / self.nranks)
        return n
